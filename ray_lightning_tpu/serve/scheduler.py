"""Continuous-batching scheduler: iteration-level admission over a
DecodeEngine.

Orca-style scheduling loop: at every step boundary the scheduler (1)
drops cancelled/expired work, (2) admits queued requests into free engine
slots — bounded by ``max_prefills_per_step`` so a burst of prompt
prefills can't starve in-flight decode latency (the prefill/decode
interleave policy), (3) advances up to ``max_prefill_chunks_per_step``
chunks of in-progress chunked prefills (engines built with
``prefill_chunk`` — a long prompt's prefill then interleaves with decode
folds instead of freezing them for its whole admission), (4) runs one
decode iteration for everything resident. Requests carry per-request
sampling params, an optional priority (lower value = served first; FIFO
within a priority, with optional aging toward priority 0 via
``priority_age_s`` so sustained high-priority traffic can't starve the
rest forever), and an optional deadline.

The scheduler also keeps the COST LEDGER: per-request accounting
(queue seconds, prefill chunks, prefix-cache hits, decode folds,
speculative accept shares, emitted tokens, and an estimated
device-seconds figure — each step's wall time split over its resident
requests) accumulated from submit to terminal and emitted as one
record at finish/cancel/expire through ``ServeMetrics.record_cost``
(windowed ``cost`` stats + tenant-labelled ``rlt_serve_request_cost_*``
series) and a ``request_cost`` typed event. Emitted-token totals
balance exactly against the engine token counter (test-asserted), so
goodput — emitted tokens per device-second — is a true ratio.

The scheduler owns no threads: ``step()`` is driven by whoever hosts the
engine (ServeReplica's loop thread, a test, the bench). ``submit`` /
``cancel`` are thread-safe so a replica's RPC surface can feed the loop.
The lock guards ONLY the queue/bookkeeping state: ``step()`` snapshots
its decisions under the lock and runs every engine call (prefill,
decode dispatch, harvest) outside it, so the RPC surface never stalls
behind device compute — with a folded engine a single dispatch can cover
``decode_fold`` tokens of wall time.
"""
from __future__ import annotations

import heapq
import itertools
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Any, Dict, List, Optional, Sequence

from ray_lightning_tpu.obs import trace as _trace
from ray_lightning_tpu.serve.metrics import CANARY_TENANT, ServeMetrics

if TYPE_CHECKING:  # engine pulls jax; keep the package import light
    from ray_lightning_tpu.obs.events import EventLog
    from ray_lightning_tpu.obs.journal import WorkloadJournal
    from ray_lightning_tpu.obs.trace import RequestTracer
    from ray_lightning_tpu.serve.engine import DecodeEngine


@dataclass(frozen=True)
class SamplingParams:
    """Per-request decode knobs (the engine consumes them as traced
    per-slot arrays, so any mix shares one compiled step)."""

    max_new_tokens: int = 32
    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    seed: int = 0
    eos_token: Optional[int] = None


@dataclass
class Request:
    prompt: List[int]
    sampling: SamplingParams = field(default_factory=SamplingParams)
    request_id: str = ""
    priority: int = 0
    #: Optional tenant/API-key label: rides into the cost ledger and the
    #: tenant-labelled ``rlt_serve_request_cost_*`` series (None bills
    #: to the "default" tenant).
    tenant: Optional[str] = None
    #: Relative deadline in seconds from submission; queued requests past
    #: it are expired, in-flight ones are cancelled at the next boundary.
    deadline_s: Optional[float] = None
    submitted_at: float = 0.0
    #: Set when the request enters a slot (chunked prefill may still be
    #: running); the TTFT queue-vs-prefill breakdown pivots on it.
    admitted_at: float = 0.0
    #: Fleet KV plane: the router's warm-peer hint
    #: (``{"peer": idx, "digests": [hex...]}``) — when the local tiers
    #: miss, admission PARKS the request transfer-pending and fetches
    #: the chain from the peer instead of re-prefilling cold. Consumed
    #: (set None) after one attempt; timeout/staleness degrade to the
    #: cold prefill the hint replaced.
    kv_hint: Optional[Dict[str, Any]] = None
    #: Disaggregated prefill: the decode replica this request's
    #: finished-prefill KV pages ship to (prefill-role placement). None
    #: = decode locally (the classic path).
    ship_to: Optional[int] = None

    def expired(self, now: float) -> bool:
        return (
            self.deadline_s is not None
            and now - self.submitted_at > self.deadline_s
        )


@dataclass(frozen=True)
class TokenEvent:
    """One scheduler-step outcome for one request."""

    request_id: str
    token: Optional[int]  # None for lifecycle-only events
    done: bool
    #: "token" | "finished" | "cancelled" | "expired" | "migrated" |
    #: "shipped" ("migrated": evicted by a preemption drain FOR
    #: resubmission on a survivor — terminal on THIS engine, not for the
    #: request; the client follows its route table instead of failing
    #: the stream. "shipped": a prefill-role completion whose KV pages
    #: went to ``ship_to`` — the client resubmits there and the stream
    #: continues warm).
    reason: str = "token"
    #: The decode replica a "shipped" request's pages went to.
    ship_to: Optional[int] = None
    #: The shipped digest chain (hexes): the client's follow-up
    #: resubmission carries them back as a fetch hint, so a lost/raced
    #: ship self-heals (the decode replica fetches from the shipper)
    #: instead of silently re-prefilling cold.
    ship_digests: Optional[List[str]] = None


class Scheduler:
    def __init__(
        self,
        engine: DecodeEngine,
        metrics: Optional[ServeMetrics] = None,
        max_prefills_per_step: int = 1,
        max_prefill_chunks_per_step: int = 1,
        priority_age_s: Optional[float] = None,
        tracer: Optional["RequestTracer"] = None,
        events: Optional["EventLog"] = None,
        journal: Optional["WorkloadJournal"] = None,
        faults: Optional[Any] = None,
        kvfleet: Optional[Any] = None,
        role: str = "mixed",
        kvstore: Optional[Any] = None,
        kvstore_writethrough: bool = False,
    ) -> None:
        self.engine = engine
        #: Fleet KV plane (serve.kvfleet.KVFleetPlane): cross-replica
        #: prefix fetches + disaggregated prefill shipping. None = the
        #: isolated-cache engine (zero cost). ``role`` shapes step():
        #: a "prefill" replica ships every finished prefill's pages to
        #: its request's ``ship_to`` decode replica instead of decoding.
        self.kvfleet = kvfleet
        self.role = str(role)
        #: Persistent KV store (serve.kvstore.FleetKVStore): the tier
        #: of last resort. With ``kvstore_writethrough`` on, every
        #: completed prefill's exported pages write through (so they
        #: survive autoscale-retire and full fleet bounces); session
        #: parking exports land here too. None = no persistent tier.
        self.kvstore = kvstore
        self.kvstore_writethrough = bool(kvstore_writethrough)
        #: Deterministic fault injection (serve.faults.FaultInjector):
        #: step() reports named lifecycle points so a chaos plan can
        #: kill/delay this process at a FIXED logical step instead of a
        #: wall-clock instant. None = off (one attribute check).
        self.faults = faults
        self.metrics = metrics or ServeMetrics(engine.num_slots)
        # Label the phase histogram with this replica's fleet role (the
        # anatomy decomposition reports per-role tails).
        set_role = getattr(self.metrics, "set_role", None)
        if set_role is not None:
            set_role(self.role)
        #: Request tracer (obs.trace): lifecycle events recorded from the
        #: scheduler's vantage point; the engine shares the same tracer
        #: for its chunk/seed events. None = tracing off (zero cost).
        self.tracer = tracer
        if tracer is not None and getattr(engine, "tracer", None) is None:
            engine.tracer = tracer
        # The fleet KV plane records its own phase-boundary marks (ship
        # landings, faults) — share this scheduler's tracer/injector so
        # its spans land in the same ring the anatomy ledger stitches.
        if kvfleet is not None:
            if getattr(kvfleet, "tracer", None) is None:
                kvfleet.tracer = tracer
            if getattr(kvfleet, "faults", None) is None:
                kvfleet.faults = faults
        #: Per-request phase ledger (obs.anatomy): at each terminal,
        #: fold the request's lifecycle timestamps into a compact
        #: {phase: seconds} map emitted to the metrics window (fleet
        #: latency decomposition) and the journal outcome record
        #: (offline autopsy). Toggleable for the anatomy_overhead bench;
        #: the per-request cost is a handful of float subtractions.
        self.phase_ledger = True
        #: Structured event log (obs.events): coarse lifecycle happenings
        #: (admission bursts, cancels, expiries) — one event per
        #: occurrence, never per token; the engine shares it for its
        #: prefix-pool evictions. None = off (zero cost).
        self.events = events
        if events is not None and getattr(engine, "events", None) is None:
            engine.events = events
        #: Workload journal (obs.journal): the deterministic capture of
        #: every externally-sourced input (submits with full sampling
        #: params, cancels) plus per-request emitted-token outcomes —
        #: the replay substrate. None = off (zero cost). Token values
        #: accumulate inline in step()'s existing loops (one list append
        #: per emission, no extra pass) and flush at the ledger close.
        self.journal = journal
        self._jr_tokens: Dict[str, List[int]] = {}
        self._jr_ttft: Dict[str, float] = {}
        self.max_prefills_per_step = max(1, int(max_prefills_per_step))
        #: Chunk-vs-fold interleave budget: prefill chunks advanced per
        #: step (chunked engines only; sits next to the admission budget).
        self.max_prefill_chunks_per_step = max(
            1, int(max_prefill_chunks_per_step)
        )
        #: Aging rate: a queued request's effective priority drops by 1
        #: toward 0 every ``priority_age_s`` seconds, so priority-1 work
        #: cannot starve forever under a sustained priority-0 stream.
        #: None = pure (priority, seq) ordering.
        self.priority_age_s = (
            None if priority_age_s is None else float(priority_age_s)
        )
        self._lock = threading.RLock()
        self._seq = itertools.count()
        #: (priority, seq, Request) min-heap: FIFO within a priority.
        self._pending: List[Any] = []
        self._cancelled: set = set()
        #: Subset of _cancelled evicted BY a preemption drain: their
        #: terminal events read "migrated" so the client keeps the
        #: stream open across the re-route instead of failing it.
        self._migrating: set = set()
        self._slot_req: Dict[int, Request] = {}
        #: Last-seen engine speculative-decoding counters (cumulative);
        #: step() diffs them into per-step metrics deltas.
        self._spec_seen = (0, 0, 0)
        #: Last-seen engine tiered prefix-cache counters (cumulative,
        #: per tier); step() diffs them into per-step metrics deltas —
        #: the tier-labelled rlt_serve_prefix_* series.
        self._prefix_seen: Dict[str, Dict[str, int]] = {}
        #: Last-seen engine KV page-allocator counters (paged engines);
        #: step() diffs them into per-step metrics deltas — the
        #: rlt_serve_kv_page_*_total series and the kv_pages gauges.
        self._kv_seen: Dict[str, int] = {}
        #: Out-of-pages backpressure latch: set while the queue head is
        #: parked waiting for pages, so the warn event fires once per
        #: park episode, not once per step.
        self._kv_parked = False
        #: Requests popped for admission but not yet registered in
        #: _slot_req (engine.admit runs OUTSIDE the lock); cancel() must
        #: still find them so a cancel racing an admission is honored at
        #: the next boundary instead of reported unknown.
        self._admitting: set = set()
        #: Cost ledger: per-request accounting accumulated from submit
        #: to terminal (queue_s, chunks, folds, emitted tokens, an
        #: estimated device-seconds share) and emitted as ONE record at
        #: finish/cancel/expire via metrics.record_cost + a typed event.
        self._acct: Dict[str, Dict[str, Any]] = {}
        #: Preemption drain: a pending ``request_drain`` budget (s) the
        #: next step() consumes, and the plan it produced — engine work
        #: (prefix-block export) must run on the loop thread, so the RPC
        #: surface arms the drain and waits on the condition instead of
        #: touching the engine itself.
        self._drain_req: Optional[float] = None
        self._drain_result: Optional[Dict[str, Any]] = None
        self._drain_cv = threading.Condition()
        #: Prefix-block payloads handed off by a dying peer, queued here
        #: (RPC thread) and imported into the engine pool at the top of
        #: the next step() (loop thread) — engine state never mutates
        #: off the driving thread.
        self._pending_imports: List[Any] = []
        #: Transfer-pending PARK state: requests popped from the queue
        #: whose warm pages are in flight from a peer —
        #: request_id -> (priority, seq, Request). They re-queue under
        #: their ORIGINAL (priority, seq) when the fetch lands (warm
        #: admit) or fails (cold prefill), so parking never reorders
        #: the queue around them.
        self._transfer_pending: Dict[str, Any] = {}
        #: Session parking: a pending ``request_park`` (the idle
        #: conversation's full token stream) the next step() consumes —
        #: engine exports/evictions must run on the loop thread, so the
        #: RPC surface arms the park and waits on the condition, exactly
        #: like the preemption drain above.
        self._park_req: Optional[Any] = None
        self._park_result: Optional[Dict[str, Any]] = None
        self._park_cv = threading.Condition()

    # -- cost ledger ------------------------------------------------------
    def _acct_open(self, req: Request) -> None:
        self._acct[req.request_id] = {
            "request_id": req.request_id,
            "tenant": req.tenant,
            "prompt_tokens": len(req.prompt),
            "submitted_at": req.submitted_at,
            "queue_s": 0.0,
            "prefill_chunks": 0,
            "prefix_hit_tokens": 0,
            "decode_folds": 0,
            "spec_verifies": 0.0,
            "spec_accepted_tokens": 0.0,
            "emitted_tokens": 0,
            "device_s": 0.0,
        }

    def _acct_close(self, rid: str, outcome: str) -> None:
        """Finalize one request's ledger record and emit it (metrics
        window + Prometheus series + a typed event). Safe to call for
        unknown ids (already flushed / submitted before a restart)."""
        rec = self._acct.pop(rid, None)
        if rec is None:
            return
        rec["outcome"] = outcome
        rec["total_s"] = round(
            time.monotonic() - rec.pop("submitted_at"), 6
        )
        rec["queue_s"] = round(rec["queue_s"], 6)
        rec["device_s"] = round(rec["device_s"], 6)
        rec["spec_verifies"] = round(rec["spec_verifies"], 3)
        rec["spec_accepted_tokens"] = round(
            rec["spec_accepted_tokens"], 3
        )
        # Compact phase ledger: the scheduler-local latency decomposition
        # (the cross-process phases — client_wait, ship transit,
        # stream_gap — only the anatomy stitcher can see). Underscore
        # stashes pop out of the record whether or not the ledger is on.
        fetch_s = rec.pop("_kv_fetch_s", 0.0)
        land_t = rec.pop("_kv_land_t", None)
        kv_src = rec.pop("_kv_src", None)
        rec.pop("_kv_park_t", None)
        admit_t = rec.pop("_admit_t", None)
        ttft = rec.pop("_ttft_s", None)
        phases: Optional[Dict[str, float]] = None
        if self.phase_ledger:
            phases = {}
            park_s = (
                max(0.0, admit_t - land_t)
                if admit_t is not None and land_t is not None
                else 0.0
            )
            phases["queue"] = max(
                0.0, rec["queue_s"] - fetch_s - park_s
            )
            if fetch_s > 0.0:
                phases["kv_fetch"] = fetch_s
                if kv_src:
                    phases["kv_fetch_source"] = kv_src
            if park_s > 0.0:
                phases["transfer_park"] = park_s
            if ttft is not None:
                phases["prefill"] = max(0.0, ttft - rec["queue_s"])
                tail = max(0.0, rec["total_s"] - ttft)
                phases["ship" if outcome == "shipped" else "decode"] = tail
            phases = {
                k: (round(v, 6) if isinstance(v, float) else v)
                for k, v in phases.items()
            }
            self.metrics.record_phases(
                phases, tenant=rec["tenant"], outcome=outcome
            )
        self.metrics.record_cost(rec)
        self._event(
            "request_cost",
            request_id=rid,
            tenant=rec["tenant"] or "default",
            outcome=outcome,
            emitted_tokens=rec["emitted_tokens"],
            device_s=rec["device_s"],
            queue_s=rec["queue_s"],
        )
        if self.journal is not None:
            # The outcome entry rides the ledger close: the emitted
            # token values (accumulated inline as they were harvested)
            # + this cost record — the recorded truth a replay asserts
            # bit-exactness against.
            self.journal.record_outcome(
                rid, outcome, cost=rec,
                tokens=self._jr_tokens.pop(rid, None),
                ttft_s=self._jr_ttft.pop(rid, None),
                phases=phases,
            )

    def _trace(
        self, rid: str, span: str, t: Optional[float] = None, **attrs: Any
    ) -> None:
        if self.tracer is not None:
            self.tracer.event(rid, span, t=t, attrs=attrs or None)

    def _event(self, name: str, level: str = "info", **kv: Any) -> None:
        if self.events is not None:
            self.events.record("scheduler", name, level=level, **kv)

    def _fault(self, point: str) -> None:
        if self.faults is not None:
            self.faults.hit(point)

    # -- intake (thread-safe) --------------------------------------------
    def submit(
        self,
        prompt: Sequence[int],
        sampling: Optional[SamplingParams] = None,
        *,
        request_id: Optional[str] = None,
        priority: int = 0,
        deadline_s: Optional[float] = None,
        tenant: Optional[str] = None,
        kv_hint: Optional[Dict[str, Any]] = None,
        ship_to: Optional[int] = None,
    ) -> str:
        """Queue a request; returns its id. Rejects (ValueError) requests
        that can never fit the engine, instead of queueing them to fail.

        ``kv_hint``/``ship_to`` are fleet-KV placement hints (see
        :class:`Request`) — routing metadata, not request identity, so
        the journal does NOT record them: a failover resubmission or a
        replay decodes locally, which is always correct."""
        sampling = sampling or SamplingParams()
        prompt = [int(t) for t in prompt]
        if not prompt or sampling.max_new_tokens < 1:
            raise ValueError("need a non-empty prompt and max_new_tokens >= 1")
        # Raises when the prompt can never be admitted (over every bucket,
        # or — chunked — leaving no room for a generated token).
        self.engine.check_prompt_len(len(prompt))
        if len(prompt) + sampling.max_new_tokens > self.engine.max_seq:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({sampling.max_new_tokens}) exceeds engine max_seq "
                f"{self.engine.max_seq}"
            )
        req = Request(
            prompt=prompt,
            sampling=sampling,
            request_id=request_id or uuid.uuid4().hex[:12],
            priority=int(priority),
            deadline_s=deadline_s,
            submitted_at=time.monotonic(),
            tenant=tenant,
            kv_hint=dict(kv_hint) if kv_hint else None,
            ship_to=None if ship_to is None else int(ship_to),
        )
        with self._lock:
            heapq.heappush(
                self._pending, (req.priority, next(self._seq), req)
            )
            depth = self._organic_depth_locked()
            self.metrics.record_submit(depth)
            self._acct_open(req)
        if self.journal is not None:
            s = req.sampling
            self.journal.record_submit(
                request_id=req.request_id,
                prompt=req.prompt,
                sampling={
                    "max_new_tokens": s.max_new_tokens,
                    "temperature": s.temperature,
                    "top_k": s.top_k,
                    "top_p": s.top_p,
                    "seed": s.seed,
                    "eos_token": s.eos_token,
                },
                priority=req.priority,
                deadline_s=req.deadline_s,
                tenant=req.tenant,
                t_mono=req.submitted_at,
            )
        if self.tracer is not None:
            self.tracer.event(
                req.request_id, _trace.SPAN_SUBMIT, t=req.submitted_at,
                attrs={"prompt_tokens": len(prompt), "priority": req.priority},
            )
            self.tracer.event(
                req.request_id, _trace.SPAN_QUEUED,
                attrs={"queue_depth": depth},
            )
        return req.request_id

    def cancel(self, request_id: str) -> bool:
        """Mark a request cancelled; queued ones are dropped and in-flight
        ones evicted at the next step boundary. Returns whether the id was
        known (queued or in flight)."""
        with self._lock:
            known = (
                request_id in self._admitting
                or request_id in self._transfer_pending
                or any(
                    r.request_id == request_id for _, _, r in self._pending
                )
                or any(
                    r.request_id == request_id
                    for r in self._slot_req.values()
                )
            )
            if known:
                self._cancelled.add(request_id)
        if self.journal is not None:
            self.journal.record_cancel(request_id, known)
        return known

    def queue_depth(self) -> int:
        """ORGANIC queue depth: pending requests excluding the reserved
        canary tenant. This is the number the metrics gauge — and
        through it the router's views and the autoscaler's pressure
        signal — sees, so a canary-only fleet reports zero load."""
        with self._lock:
            return self._organic_depth_locked()

    def _organic_depth_locked(self) -> int:
        """Under self._lock: len(self._pending) minus canary probes."""
        return sum(
            1 for _, _, r in self._pending if r.tenant != CANARY_TENANT
        )

    def has_work(self) -> bool:
        with self._lock:
            if (
                bool(self._pending)
                or self.engine.num_active > 0
                or self._drain_req is not None
                or self._park_req is not None
                or bool(self._pending_imports)
                or bool(self._transfer_pending)
            ):
                return True
        # Fleet KV inbox (peer fetches/ships): outside the lock — the
        # emptiness probe may cross a process boundary.
        return self.kvfleet is not None and self.kvfleet.pending()

    # -- preemption drain (thread-safe arm/wait; work runs in step()) -----
    def request_drain(self, budget_s: float) -> None:
        """Arm a graceful drain: the next step() classifies in-flight
        work into finish-in-grace vs migrate (cancelling + exporting the
        migrate set) and publishes the plan for :meth:`drain_result`."""
        with self._lock:
            self._drain_req = float(budget_s)

    def drain_result(
        self, timeout: Optional[float] = 10.0
    ) -> Optional[Dict[str, Any]]:
        """Block until the armed drain's plan is ready (None on
        timeout); consumes the plan."""
        with self._drain_cv:
            if self._drain_result is None:
                self._drain_cv.wait(timeout)
            plan, self._drain_result = self._drain_result, None
            return plan

    # -- session parking (thread-safe arm/wait; work runs in step()) ------
    def request_park(
        self, tokens: Sequence[int], request_id: Optional[str] = None
    ) -> None:
        """Arm a session park: the next step() exports the idle
        conversation's cached chain (loop thread — compiled pool
        reads), writes it through to the persistent store, and frees
        the local pages ONLY if every block landed (a partial write
        keeps the warm copies; pages are lost loudly, never silently).
        The restored turn hits the chain back through the ordinary
        store-fetch path, bit-exactly."""
        with self._lock:
            self._park_req = ([int(t) for t in tokens], request_id)

    def park_result(
        self, timeout: Optional[float] = 10.0
    ) -> Optional[Dict[str, Any]]:
        """Block until the armed park's record is ready (None on
        timeout); consumes the record."""
        with self._park_cv:
            if self._park_result is None:
                self._park_cv.wait(timeout)
            out, self._park_result = self._park_result, None
            return out

    def _apply_park(self) -> None:
        """Consume a pending park request (inside step(), loop
        thread): export -> store write-through -> local eviction."""
        with self._lock:
            req, self._park_req = self._park_req, None
        if req is None:
            return
        tokens, rid = req
        blocks: List[Any] = []
        stored = freed = 0
        if getattr(self.engine, "prefix_blocks", 0):
            blocks = self.engine.export_prefix_blocks(tokens)
        if blocks and self.kvstore is not None:
            stored = self.kvstore.put_blocks(blocks)
            if stored == len(blocks):
                evict = getattr(self.engine, "evict_prefix_chain", None)
                if evict is not None:
                    freed = evict([b[0] for b in blocks])
        result = {
            "digests": [b[0] for b in blocks],
            "blocks": len(blocks),
            "stored": stored,
            "freed": freed,
        }
        if rid is not None:
            self._trace(
                rid, _trace.SPAN_KV_PARK,
                blocks=len(blocks), stored=stored, freed=freed,
            )
        self._event(
            "kv_park",
            level="info" if stored == len(blocks) else "warn",
            request_id=rid, blocks=len(blocks), stored=stored,
            freed=freed,
        )
        with self._park_cv:
            self._park_result = result
            self._park_cv.notify_all()

    def enqueue_prefix_import(self, blocks: Any) -> int:
        """Queue a dying peer's exported prefix blocks for import at the
        top of the next step() (engine mutations stay on the loop
        thread). Returns the number of blocks queued."""
        with self._lock:
            self._pending_imports.append(blocks)
        return len(blocks)

    def _service_kvfleet(self) -> None:
        """One pump of the fleet KV plane (loop thread): answer peer
        fetches, apply inbound imports, and settle this scheduler's
        parked transfer-pending requests."""
        plane = self.kvfleet
        export_fn = getattr(self.engine, "export_blocks_by_digest", None)
        svc = plane.service(
            export_fn=export_fn if export_fn is not None else (
                lambda digests: []
            ),
            import_fn=self.engine.import_prefix_blocks,
            layer_import_fn=getattr(
                self.engine, "import_prefix_block_layer", None
            ),
            abort_fn=getattr(self.engine, "abort_layer_imports", None),
        )
        resumed: List[Any] = []
        store_rids = set(svc.get("store_fetched") or ())
        with self._lock:
            for rid, _n in svc["fetched"]:
                entry = self._transfer_pending.pop(rid, None)
                if entry is not None:
                    # The blocks are already in the pool; the request
                    # re-queues under its original (priority, seq) and
                    # its admission walk now hits warm.
                    heapq.heappush(self._pending, entry)
                    resumed.append((rid, "warm"))
        for rid in store_rids:
            self._trace(rid, _trace.SPAN_KV_RESTORE)
        with self._lock:
            for rid, reason in svc["failed"]:
                entry = self._transfer_pending.pop(rid, None)
                if entry is not None:
                    heapq.heappush(self._pending, entry)
                    resumed.append((rid, reason))
        t_land = time.monotonic()
        for rid, how in resumed:
            # Phase-boundary mark: the parked transfer settled (warm or
            # failed) — closes the ledger's kv_fetch phase; the land →
            # re-admit gap becomes transfer_park.
            acct = self._acct.get(rid)
            src = "store" if rid in store_rids else (
                (acct or {}).get("_kv_src") or "peer"
            )
            if acct is not None and "_kv_park_t" in acct:
                acct["_kv_fetch_s"] = t_land - acct["_kv_park_t"]
                acct["_kv_land_t"] = t_land
            self._trace(
                rid, _trace.SPAN_KV_LAND, t=t_land,
                source=src, ok=how == "warm",
                **({} if how == "warm" else {"reason": how}),
            )
            self._event(
                "kv_transfer_resume",
                level="info" if how == "warm" else "warn",
                request_id=rid, outcome=how,
            )

    def _apply_drain(self, events: List[TokenEvent]) -> None:
        """Consume a pending drain request (inside step(), loop thread).

        Policy: a resident request whose estimated completion fits in
        half the grace window (the other half is the respawn/failover
        margin) runs to completion; everything else — the rest of the
        residents and the whole queue — is cancelled here and listed as
        the MIGRATE set, each with its prompt's cached prefix blocks
        serialized for the survivor (the cross-replica KV handoff). The
        estimate is conservative: with no recent decode-rate sample,
        everything migrates — better a warm replay on a survivor than a
        stream the deadline truncates.
        """
        with self._lock:
            budget = self._drain_req
            if budget is None:
                return
            self._drain_req = None
            rate = float(
                self.metrics.snapshot().get("decode_tokens_per_sec") or 0.0
            )
            resident = list(self._slot_req.values())
            n_res = max(1, len(resident))
            finish: List[str] = []
            migrate: List[Any] = []
            for req in resident:
                acct = self._acct.get(req.request_id) or {}
                left = max(
                    0,
                    req.sampling.max_new_tokens
                    - int(acct.get("emitted_tokens", 0)),
                )
                est = (left * n_res / rate) if rate > 0 else None
                if est is not None and est <= 0.5 * budget:
                    finish.append(req.request_id)
                else:
                    migrate.append(req)
                    # The boundary eviction scan below this call picks
                    # it up in the SAME step; _migrating makes its
                    # terminal events read "migrated" (the client keeps
                    # the stream open across the re-route).
                    self._cancelled.add(req.request_id)
                    self._migrating.add(req.request_id)
            queued = [r for _, _, r in self._pending]
            # Transfer-pending parks are queued work too: their fetches
            # die with this replica, so they migrate like the queue
            # (any late fetch response is discarded harmlessly).
            queued += [r for _, _, r in self._transfer_pending.values()]
            self._pending = []
            self._transfer_pending = {}
            for req in queued:
                self._cancelled.discard(req.request_id)
                migrate.append(req)
                self.metrics.record_cancel(queue_depth=0)
                self._trace(req.request_id, _trace.SPAN_CANCEL)
                self._acct_close(req.request_id, "migrated")
                events.append(
                    TokenEvent(req.request_id, None, True, "migrated")
                )
        if self.journal is not None:
            # A drain-induced cancel must look like any other cancel to
            # a replay of this journal (the client-side journal, not
            # this one, is what resubmits the migrated request).
            for req in migrate:
                self.journal.record_cancel(req.request_id, True)
        # Engine work outside the lock: serialize each migrating
        # request's cached prefix so the survivor's admission walk hits
        # warm instead of re-prefilling cold.
        plan = {
            "budget_s": budget,
            "finish": finish,
            "migrate": [
                {
                    "request_id": req.request_id,
                    "blocks": self.engine.export_prefix_blocks(req.prompt)
                    if getattr(self.engine, "prefix_blocks", 0)
                    else [],
                }
                for req in migrate
            ],
        }
        self._event(
            "drain_plan", level="warn",
            budget_s=round(budget, 3), finish=len(finish),
            migrate=len(migrate),
            kv_blocks=sum(len(m["blocks"]) for m in plan["migrate"]),
        )
        with self._drain_cv:
            self._drain_result = plan
            self._drain_cv.notify_all()

    # -- the loop body (single driver thread) -----------------------------
    def step(self) -> List[TokenEvent]:
        """One iteration: evict cancelled/expired, admit (bounded),
        advance prefill chunks (bounded), run one engine fold. Queue
        decisions happen under the lock; every engine call runs OUTSIDE
        it, so submit()/cancel() never wait on device compute."""
        events: List[TokenEvent] = []
        t0 = time.monotonic()
        # Peer KV handoff + preemption drain ride the loop thread:
        # apply queued block imports first, then consume any armed drain
        # request so its cancellations land in THIS step's boundary
        # scan (engine state never mutates off the driving thread).
        with self._lock:
            imports, self._pending_imports = self._pending_imports, []
        for blocks in imports:
            self.engine.import_prefix_blocks(blocks)
        if self.kvfleet is not None:
            # Fleet KV plane: serve peer fetches (compiled pool reads —
            # this thread), import inbound ships/fetch responses BEFORE
            # the admission scan below (so a shipped request admits
            # warm), and re-queue parked requests whose transfer landed
            # (warm) or failed (cold prefill — timeout/staleness never
            # lose the request, they only lose the shortcut).
            self._service_kvfleet()
        if self._drain_req is not None:
            self._apply_drain(events)
        if self._park_req is not None:
            self._apply_park()
        to_evict: List[Any] = []
        admits: List[Request] = []
        #: (priority, seq, Request, peer, digests): candidates popped
        #: for a cross-replica KV fetch instead of admission — the
        #: fetch RPC runs outside the lock; success parks them
        #: transfer-pending, refusal re-queues them for cold prefill.
        to_fetch: List[Any] = []
        #: (rid, outcome) terminals from ENGINE work this step; their
        #: ledger records flush after this step's device-seconds are
        #: attributed, so a request's final fold is in its bill.
        closed: List[Any] = []
        with self._lock:
            resident_rids = [
                r.request_id for r in self._slot_req.values()
            ]
            # 0) Priority aging: re-score the queue so long-waiting
            # requests drift toward priority 0 (FIFO seq breaks ties, so
            # an aged request outranks younger same-priority arrivals).
            if self.priority_age_s is not None and self._pending:
                self._pending = [
                    (
                        max(
                            0,
                            r.priority
                            - int(
                                (t0 - r.submitted_at) / self.priority_age_s
                            ),
                        ),
                        s,
                        r,
                    )
                    for _, s, r in self._pending
                ]
                heapq.heapify(self._pending)
            # 1) Collect boundary evictions of in-flight cancels/expiries
            # (mid-prefill requests included — release drops their state
            # machine and unpins their prefix blocks).
            for slot, req in list(self._slot_req.items()):
                rid = req.request_id
                cancelled = rid in self._cancelled
                if cancelled or req.expired(t0):
                    del self._slot_req[slot]
                    self._cancelled.discard(rid)
                    if rid in self._migrating:
                        self._migrating.discard(rid)
                        kind = "migrated"
                    else:
                        kind = "cancelled" if cancelled else "expired"
                    to_evict.append((slot, req, kind))
            # 2) Pop admission candidates: bounded prefills per step,
            # sized to the slots that are (or are about to be) free.
            # Paged engines add a PAGE budget: a candidate is admitted
            # only while the allocatable pages cover its whole life
            # (prompt + decode reserve — engine.pages_for); otherwise
            # the queue head PARKS in place (no pop, priority order
            # kept) until residents finish and free pages — out of
            # pages backpressures, it never deadlocks and never lets
            # an admission fail inside the engine.
            budget = min(
                self.max_prefills_per_step,
                len(self.engine.free_slots()) + len(to_evict),
            )
            paged = getattr(self.engine, "paged", False)
            pages_left = self.engine.pages_available() if paged else 0
            parked = False
            while len(admits) < budget and self._pending:
                prio, seqno, req = self._pending[0]
                if req.request_id in self._cancelled:
                    heapq.heappop(self._pending)
                    self._cancelled.discard(req.request_id)
                    self.metrics.record_cancel(
                        queue_depth=self._organic_depth_locked()
                    )
                    self._trace(req.request_id, _trace.SPAN_CANCEL)
                    self._event("cancel", request_id=req.request_id,
                                where="queued")
                    self._acct_close(req.request_id, "cancelled")
                    events.append(
                        TokenEvent(req.request_id, None, True, "cancelled")
                    )
                    continue
                if req.expired(t0):
                    heapq.heappop(self._pending)
                    self.metrics.record_expire(
                        queue_depth=self._organic_depth_locked()
                    )
                    self._trace(req.request_id, _trace.SPAN_EXPIRE)
                    self._event("expire", level="warn",
                                request_id=req.request_id, where="queued")
                    self._acct_close(req.request_id, "expired")
                    events.append(
                        TokenEvent(req.request_id, None, True, "expired")
                    )
                    continue
                if self.kvfleet is not None and req.kv_hint is not None:
                    # Cross-replica prefix sharing: the router said a
                    # peer holds this prompt's chain — or, with
                    # ``store: True``, that no live replica does but
                    # the persistent store does. One attempt per
                    # request (the hint is consumed here); only worth a
                    # fetch when the LOCAL tiers hold strictly less
                    # than the hint promises — the probe is a pure
                    # host-side digest walk, safe under the lock.
                    hint, req.kv_hint = req.kv_hint, None
                    digests = list(hint.get("digests") or [])
                    peer = hint.get("peer")
                    from_store = bool(hint.get("store"))
                    probe = getattr(
                        self.engine, "cached_prefix_blocks", None
                    )
                    if (
                        digests
                        and (peer is not None or from_store)
                        and probe is not None
                        and getattr(self.engine, "prefix_blocks", 0)
                        and probe(req.prompt) < len(digests)
                    ):
                        heapq.heappop(self._pending)
                        to_fetch.append((
                            prio, seqno, req,
                            None if from_store else int(peer),
                            digests,
                        ))
                        continue
                if paged:
                    need = self.engine.pages_for(
                        len(req.prompt), req.sampling.max_new_tokens
                    )
                    if need > pages_left:
                        parked = True
                        break
                    pages_left -= need
                heapq.heappop(self._pending)
                admits.append(req)
                self._admitting.add(req.request_id)
            if parked and not self._kv_parked:
                self._event(
                    "kv_pages_backpressure", level="warn",
                    queue_depth=len(self._pending),
                    pages_available=pages_left,
                )
            self._kv_parked = parked
        # -- engine work, lock NOT held --------------------------------
        for prio, seqno, req, peer, digests in to_fetch:
            # The fetch RPC (a queue put, possibly cross-process) runs
            # here; a refused fetch (budget, unknown peer, bandwidth
            # cap) re-queues for cold prefill NEXT step — bounded
            # in-flight bytes never turn into a queue. ``peer is None``
            # means the hint pointed at the persistent store, not a
            # live replica; same park→import→admit-warm path, different
            # resolver.
            ok = (
                self.kvfleet.request_store_fetch(req.request_id, digests)
                if peer is None
                else self.kvfleet.request_fetch(req.request_id, peer, digests)
            )
            if ok:
                with self._lock:
                    self._transfer_pending[req.request_id] = (
                        prio, seqno, req,
                    )
                acct = self._acct.get(req.request_id)
                if acct is not None:
                    acct["_kv_park_t"] = time.monotonic()
                    acct["_kv_src"] = "store" if peer is None else "peer"
                self._trace(
                    req.request_id,
                    _trace.SPAN_KVSTORE_FETCH if peer is None
                    else _trace.SPAN_KV_FETCH,
                    peer=peer, blocks=len(digests),
                )
                self._event(
                    "kv_transfer_park", request_id=req.request_id,
                    peer=peer, blocks=len(digests),
                    store=peer is None,
                )
            else:
                with self._lock:
                    heapq.heappush(self._pending, (prio, seqno, req))
        for slot, req, kind in to_evict:
            self.engine.release(slot)
            (self.metrics.record_expire if kind == "expired"
             else self.metrics.record_cancel)(
                queue_depth=self.queue_depth()
            )
            self._trace(
                req.request_id,
                _trace.SPAN_EXPIRE if kind == "expired"
                else _trace.SPAN_CANCEL,
                slot=slot,
            )
            self._event(
                "expire" if kind == "expired" else "cancel",
                level="warn" if kind == "expired" else "info",
                request_id=req.request_id, where="slot", slot=slot,
                migrated=kind == "migrated",
            )
            closed.append((req.request_id, kind))
            events.append(TokenEvent(req.request_id, None, True, kind))
        newly: Dict[int, Request] = {}
        finished_rids: List[str] = []
        finished_slots: List[int] = []
        if admits:
            # One burst: every admission chain is dispatched before the
            # first token sync (engine.admit_many), so admission i's host
            # round trip overlaps admission i+1's prefill. Chunked
            # engines return first_tok=None here — the first token
            # arrives from prefill_step below once the final chunk runs.
            t_admit = time.monotonic()
            results = self.engine.admit_many(
                [
                    dict(
                        prompt=req.prompt,
                        request_id=req.request_id,
                        max_new_tokens=req.sampling.max_new_tokens,
                        temperature=req.sampling.temperature,
                        top_k=req.sampling.top_k,
                        top_p=req.sampling.top_p,
                        seed=req.sampling.seed,
                        eos_token=req.sampling.eos_token,
                    )
                    for req in admits
                ]
            )
            # One event per BURST, not per admission — the hot loop's
            # event budget.
            self._event(
                "admit_burst", n=len(admits),
                queue_depth=self.queue_depth(),
            )
            for req, (slot, first_tok, done) in zip(admits, results):
                req.admitted_at = t_admit
                self.metrics.record_admit(
                    t_admit - req.submitted_at, self.queue_depth()
                )
                acct = self._acct.get(req.request_id)
                if acct is not None:
                    acct["queue_s"] = t_admit - req.submitted_at
                    acct["_admit_t"] = t_admit
                # Record-time timestamp (not t_admit): the engine's own
                # admission-block events (prefix_seed) land between
                # queued and here, and a trace's timestamps must be
                # monotonic in record order. queue_s keeps the exact
                # admission clock.
                self._trace(
                    req.request_id, _trace.SPAN_ADMITTED,
                    slot=slot,
                    queue_s=round(t_admit - req.submitted_at, 6),
                )
                if first_tok is None:
                    newly[slot] = req  # chunked prefill in progress
                    continue
                now = time.monotonic()
                self.metrics.record_first_token(
                    now - req.submitted_at, now - t_admit, 1, 0,
                    len(req.prompt),
                )
                self._trace(
                    req.request_id, _trace.SPAN_FIRST_TOKEN, t=now,
                    ttft_s=round(now - req.submitted_at, 6),
                )
                if acct is not None:
                    acct["emitted_tokens"] += 1
                    acct["_ttft_s"] = now - req.submitted_at
                if self.journal is not None:
                    self._jr_tokens[req.request_id] = [int(first_tok)]
                    self._jr_ttft[req.request_id] = (
                        now - req.submitted_at
                    )
                events.append(
                    TokenEvent(
                        req.request_id, first_tok, done,
                        "finished" if done else "token",
                    )
                )
                if done:
                    self.metrics.record_finish(
                        queue_depth=self.queue_depth()
                    )
                    self._trace(req.request_id, _trace.SPAN_FINISH)
                    finished_rids.append(req.request_id)
                    closed.append((req.request_id, "finished"))
                else:
                    newly[slot] = req
        if admits:
            # Fault point: requests hold slots, chunked ones have no
            # first token yet — dying here strands admitted-not-started
            # work (the failover set's hardest case).
            self._fault("post_admit")
        # 3) Advance chunked prefills. Two shapes: the classic
        # chunk-vs-fold interleave (separate prefill_step dispatches
        # competing with the fold for device time), or — with
        # piggyback_chunks on — NO separate dispatch at all: chunk rows
        # ride inside the decode fold below and their completions drain
        # from pop_chunk_events after it. (Snapshot the in-progress
        # count first: the fault hook below must fire on every step
        # that ADVANCED a chunk, not only the one that completed a
        # prefill — "mid-prefill" is the point.)
        piggyback = getattr(self.engine, "piggyback_chunks", 0) > 0
        prefilling = getattr(self.engine, "num_prefilling", 0)
        chunk_events = (
            []
            if piggyback
            else self.engine.prefill_step(self.max_prefill_chunks_per_step)
        )
        prefilled = self._finish_prefills(
            chunk_events, newly, events, finished_rids, finished_slots,
            closed,
        )
        if not piggyback and (chunk_events or prefilling):
            # Fault point: a multi-chunk prompt is part-way through its
            # prefill (device KV holds a partial range nobody can read
            # back — the request MUST be replayed from its submit).
            self._fault("mid_prefill_chunk")
        # 4) One engine fold for everything resident (up to decode_fold
        # tokens per slot fan out of a single dispatch+harvest).
        active = self.engine.num_active
        emitted = 0
        fold_results = self.engine.step()
        if piggyback:
            # Piggybacked chunk rows rode INSIDE that fold dispatch;
            # their completions drain here and flow through the same
            # finish path (first-token metrics, writethrough, ship) —
            # one dispatch did all the work, the host accounting is
            # identical either way.
            pb_events = self.engine.pop_chunk_events()
            if pb_events:
                chunk_events = list(chunk_events) + pb_events
                prefilled += self._finish_prefills(
                    pb_events, newly, events, finished_rids,
                    finished_slots, closed, piggyback=True,
                )
            if pb_events or prefilling:
                # Same fault point as the separate-dispatch path, just
                # after the fused fold that advanced the chunks.
                self._fault("mid_prefill_chunk")
        # Tokens per request this fold: the shared granularity of the
        # decode-side trace events, the spec attribution, and the cost
        # ledger (one dict pass per fold, never per token).
        fold_tokens: Dict[str, int] = {}
        for _, rid, _, _ in fold_results:
            fold_tokens[rid] = fold_tokens.get(rid, 0) + 1
        if getattr(self.engine, "spec", "off") != "off":
            # Accept accounting: the engine's cumulative counters diffed
            # into this step's delta (zombie tokens already excluded at
            # harvest). One metrics record per step, never per token.
            v = self.engine.spec_verifies
            d = self.engine.spec_drafted_tokens
            a = self.engine.spec_accepted_tokens
            dv = v - self._spec_seen[0]
            if dv:
                da = a - self._spec_seen[2]
                self.metrics.record_spec(dv, d - self._spec_seen[1], da)
                # Ledger attribution: the verify forwards are batched
                # over slots, so per-request shares are estimates —
                # accepted tokens proportional to tokens emitted this
                # fold, verifies split evenly among the riders.
                total = sum(fold_tokens.values())
                for rid, n in fold_tokens.items():
                    acct = self._acct.get(rid)
                    if acct is not None:
                        acct["spec_verifies"] += dv / len(fold_tokens)
                        if total:
                            acct["spec_accepted_tokens"] += da * n / total
                if self.tracer is not None:
                    for rid, n in fold_tokens.items():
                        self.tracer.event(
                            rid, _trace.SPAN_SPEC_VERIFY,
                            attrs={
                                "tokens": n,
                                "drafted": d - self._spec_seen[1],
                                "accepted": da,
                            },
                        )
            self._spec_seen = (v, d, a)
        # Tiered prefix cache: diff the engine's cumulative per-tier
        # counters into one metrics record per step that saw tier
        # traffic (admissions walk the tiers; steady decode never does).
        tier_fn = getattr(self.engine, "prefix_tier_counters", None)
        if tier_fn is not None and getattr(self.engine, "prefix_blocks", 0):
            tiers = tier_fn()
            if tiers != self._prefix_seen:
                seen = self._prefix_seen
                self.metrics.record_prefix_tiers(
                    {
                        t: {
                            k: n - seen.get(t, {}).get(k, 0)
                            for k, n in kv.items()
                        }
                        for t, kv in tiers.items()
                    },
                    self.engine.prefix_tier_bytes(),
                )
                self._prefix_seen = tiers
        # Paged KV: diff the engine's cumulative page-allocator counters
        # into one metrics record per step that saw page traffic, and
        # refresh the state gauges (free/resident/aliased) alongside.
        if getattr(self.engine, "paged", False):
            kv = self.engine.kv_page_counters()
            if kv != self._kv_seen:
                self.metrics.record_kv_pages(
                    {
                        k: n - self._kv_seen.get(k, 0)
                        for k, n in kv.items()
                    },
                    self.engine.kv_page_stats(),
                )
                self._kv_seen = kv
        for rid, n in fold_tokens.items():
            acct = self._acct.get(rid)
            if acct is not None:
                acct["decode_folds"] += 1
                acct["emitted_tokens"] += n
        if self.tracer is not None and fold_tokens:
            # One event per request per fold (not per token): "this fold,
            # this request rode it for n tokens" — the decode-side trace
            # granularity the hot loop can afford. Recorded before the
            # finish events below so a trace's fold events always precede
            # its terminal span.
            for rid, n in fold_tokens.items():
                self.tracer.event(
                    rid, _trace.SPAN_DECODE_FOLD, attrs={"tokens": n}
                )
        jr_on = self.journal is not None
        for slot, rid, tok, done in fold_results:
            emitted += 1
            if jr_on:
                self._jr_tokens.setdefault(rid, []).append(int(tok))
            events.append(
                TokenEvent(rid, tok, done, "finished" if done else "token")
            )
            if done:
                self.metrics.record_finish(queue_depth=self.queue_depth())
                self._trace(rid, _trace.SPAN_FINISH)
                finished_slots.append(slot)
                finished_rids.append(rid)
                closed.append((rid, "finished"))
        if fold_results:
            # Fault point: a decode fold's tokens are harvested (and
            # journaled below) but the step has not returned — mid-decode
            # death with partially-streamed outputs.
            self._fault("fold_boundary")
        with self._lock:
            self._slot_req.update(newly)
            for req in admits:
                self._admitting.discard(req.request_id)
            for slot in finished_slots:
                self._slot_req.pop(slot, None)
            # Purge cancels that raced a same-fold finish: the id left
            # _slot_req above, so the next eviction scan would never see
            # it — without this, a cancel landing while the lock-free
            # engine section ran would pin the id in _cancelled forever
            # and spuriously evict a later request reusing it.
            self._cancelled.difference_update(finished_rids)
            self._migrating.difference_update(finished_rids)
        # Device-seconds attribution: this step's wall time split evenly
        # over the requests that held engine state through it (resident
        # slots + this step's admissions). An estimate by construction —
        # the fold executes all resident slots in one batched dispatch —
        # but it sums exactly to serving wall time, so fleet goodput
        # (tokens per device-second) is conserved.
        wall = time.monotonic() - t0
        participants = set(resident_rids)
        participants.update(req.request_id for req in admits)
        participants.update(fold_tokens)
        participants.update(ev[1].request_id for ev in chunk_events)
        if participants:
            share = wall / len(participants)
            for rid in participants:
                acct = self._acct.get(rid)
                if acct is not None:
                    acct["device_s"] += share
        for rid, outcome in closed:
            self._acct_close(rid, outcome)
        if any(outcome == "finished" for _, outcome in closed):
            # Fault point: the terminal ledger/journal flush happened but
            # the finish events never reach the replica's buffers — the
            # replica RECORDED an outcome the client never saw, so the
            # client-side journal must still classify it incomplete and
            # resubmit (dedup keeps the stream exact).
            self._fault("post_finish_pre_ack")
        # Token accounting must be EXACT (the ledger balances against
        # it): count only admissions that really emitted a first token —
        # chunked admissions return None and their token is counted at
        # prefill completion.
        admit_tokens = sum(
            1 for _, first_tok, _ in (results if admits else [])
            if first_tok is not None
        )
        self.metrics.record_step(
            wall, active,
            emitted + prefilled + admit_tokens, self.queue_depth(),
        )
        return events

    def _finish_prefills(
        self,
        chunk_events: List[Any],
        newly: Dict[int, Any],
        events: List[TokenEvent],
        finished_rids: List[str],
        finished_slots: List[int],
        closed: List[Tuple[str, str]],
        piggyback: bool = False,
    ) -> int:
        """Process completed/advanced prefill chunk events: first-token
        metrics + traces, journal tokens, TokenEvents, write-through,
        and the disaggregated-prefill ship loop. Shared verbatim by the
        separate-dispatch path (prefill_step) and the piggyback path
        (pop_chunk_events after the fused fold)."""
        prefilled = 0
        #: (slot, task, Request): completed prefills whose KV pages
        #: ship to a decode replica instead of decoding here — the
        #: disaggregated-prefill handoff (collected in the loop, engine
        #: work below it so the fold never decodes a shipped slot).
        to_ship: List[Any] = []
        for slot, task, tok, done in chunk_events:
            prefilled += 1
            now = time.monotonic()
            req = newly.get(slot) or self._slot_req.get(slot)
            if req is not None:
                self.metrics.record_first_token(
                    now - req.submitted_at,
                    now - (req.admitted_at or now),
                    task.chunks,
                    task.matched_tokens,
                    len(task.tokens),
                )
                self._trace(
                    task.request_id, _trace.SPAN_FIRST_TOKEN, t=now,
                    ttft_s=round(now - req.submitted_at, 6),
                    chunks=task.chunks,
                    prefix_hit_tokens=task.matched_tokens,
                    # The prefill-mode detail the anatomy ledger surfaces:
                    # piggyback chunks rode inside decode folds, solo
                    # chunks had their own dispatches.
                    mode="piggyback" if piggyback else "solo",
                )
            acct = self._acct.get(task.request_id)
            if acct is not None:
                acct["prefill_chunks"] = task.chunks
                acct["prefix_hit_tokens"] = task.matched_tokens
                acct["emitted_tokens"] += 1
                if req is not None:
                    acct.setdefault("_ttft_s", now - req.submitted_at)
            if self.journal is not None and tok is not None:
                self._jr_tokens.setdefault(
                    task.request_id, []
                ).append(int(tok))
                if req is not None:
                    self._jr_ttft.setdefault(
                        task.request_id, now - req.submitted_at
                    )
            events.append(
                TokenEvent(
                    task.request_id, tok, done,
                    "finished" if done else "token",
                )
            )
            if done:
                self.metrics.record_finish(queue_depth=self.queue_depth())
                self._trace(task.request_id, _trace.SPAN_FINISH)
                finished_rids.append(task.request_id)
                closed.append((task.request_id, "finished"))
                newly.pop(slot, None)
            elif (
                self.kvfleet is not None
                and req is not None
                and req.ship_to is not None
            ):
                # Disaggregated prefill: the first token streamed above
                # (the client's cursor dedups it when the decode
                # replica re-emits the identical stream); the slot's KV
                # pages ship below instead of decoding here.
                to_ship.append((slot, task, req))
                newly.pop(slot, None)
                finished_slots.append(slot)
                finished_rids.append(task.request_id)
        if (
            self.kvstore_writethrough
            and self.kvstore is not None
            and getattr(self.engine, "prefix_blocks", 0)
        ):
            # Write-through: every completed prefill's chain goes to
            # the persistent store so the pages survive this replica's
            # retirement (the prefill pool is the autoscaler's favorite
            # victim). Shipped slots reuse the export below; put errors
            # count loudly in kvstore_write_errors_total, never raise.
            shipped_slots = {s for s, _t, _r in to_ship}
            for slot, task, _tok, _done in chunk_events:
                if slot in shipped_slots:
                    continue
                wt = self.engine.export_prefix_blocks(task.tokens)
                if wt:
                    self.kvstore.put_blocks(wt)
        for slot, task, req in to_ship:
            # Release FIRST (the fold below must not decode a shipped
            # slot; the finished prompt's blocks already entered the
            # pool at prefill completion, so they survive the release
            # as digest-keyed cache pages), then export + ship. A
            # failed ship only costs the decode replica a cold prefill
            # — the client's resubmission carries a fetch hint back to
            # THIS replica, whose pool still holds the pages.
            self.engine.release(slot)
            blocks = (
                self.engine.export_prefix_blocks(task.tokens)
                if getattr(self.engine, "prefix_blocks", 0)
                else []
            )
            if (
                self.kvstore_writethrough
                and self.kvstore is not None
                and blocks
            ):
                self.kvstore.put_blocks(blocks)
            layerwise = bool(getattr(self.kvfleet, "layerwise_ship", False))
            self.kvfleet.ship(req.ship_to, req.request_id, blocks)
            if self.journal is not None:
                # A ship looks like a cancel to a replay of THIS
                # journal (truncation after the recorded first token);
                # the decode replica's journal carries the decode, and
                # the CLIENT journal is what re-drives the request
                # there.
                self.journal.record_cancel(req.request_id, True)
            self.metrics.record_cancel(queue_depth=self.queue_depth())
            self._trace(
                req.request_id, _trace.SPAN_SHIPPED,
                target=req.ship_to, blocks=len(blocks),
                layerwise=layerwise,
            )
            self._event(
                "kv_ship", request_id=req.request_id,
                target=req.ship_to, blocks=len(blocks),
                layerwise=layerwise,
            )
            closed.append((req.request_id, "shipped"))
            events.append(
                TokenEvent(
                    req.request_id, None, True, "shipped",
                    ship_to=req.ship_to,
                    ship_digests=[b[0] for b in blocks],
                )
            )
        return prefilled

    def run_until_idle(self, max_steps: int = 100_000) -> List[TokenEvent]:
        """Drive step() until queue and slots drain (tests, bench)."""
        out: List[TokenEvent] = []
        for _ in range(max_steps):
            if not self.has_work():
                break
            out.extend(self.step())
        return out
