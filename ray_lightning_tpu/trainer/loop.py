"""The worker-side training engine.

Runs inside each worker actor (or in-process for the single-device path):
builds params/optimizer on the mesh, compiles the train/eval steps through
the strategy, iterates epochs with host-side callbacks only at boundaries,
and packages rank-0 results as a WorkerOutput.

This replaces the role PTL's Trainer loop plays for the reference (the
``results = function(...)`` hot loop at ray_launcher.py:297 runs PTL's whole
fit); here the loop is framework-owned and XLA-first: one compiled step per
batch, async dispatch, metrics fetched at epoch/log boundaries to avoid
device->host syncs (SURVEY.md §7 "No mid-step Python").
"""
from __future__ import annotations

import os
import warnings
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_lightning_tpu.launchers.utils import WorkerOutput
from ray_lightning_tpu.utils.seed import reset_seed
from ray_lightning_tpu.utils.state_stream import (
    load_state_stream,
    to_state_stream,
)


@dataclass
class TrainerSpec:
    """Picklable trainer configuration shipped driver -> workers.

    The reference pickles a live PTL Trainer through ``function.__self__``
    (ray_launcher.py:269-288) and reconciles side effects afterward; we
    design the shipped state explicitly instead (SURVEY.md §7 hard parts).
    """

    max_epochs: int = 1
    max_steps: Optional[int] = None
    # Debug: train on a fixed unshuffled slice and validate on the SAME
    # slice (PTL's overfit_batches); int = batches, float = epoch fraction.
    overfit_batches: Optional[Any] = None
    # Debug: enable jax_debug_nans in the worker — any NaN/inf produced by
    # a compiled step re-runs de-optimized and raises at the culprit op
    # (PTL's detect_anomaly analog; costs a per-step sync, debug only).
    detect_anomaly: bool = False
    # Wall-clock budget in seconds (Trainer parses str/timedelta forms).
    # Single-process: checked at every step boundary. Multi-process: checked
    # at collective boundaries (mid-epoch val, epoch end) with a cross-rank
    # consensus so every rank takes the same stop decision — a local
    # per-step clock check could diverge across ranks and deadlock the
    # next collective.
    max_time: Optional[float] = None
    limit_train_batches: Optional[Any] = None  # int or float fraction
    limit_val_batches: Optional[Any] = None
    limit_test_batches: Optional[Any] = None
    limit_predict_batches: Optional[Any] = None
    num_sanity_val_steps: int = 2
    check_val_every_n_epoch: int = 1
    # Mid-epoch validation (PTL semantics): int = every N train batches,
    # float in (0, 1) = that fraction of an epoch. None = epoch end only.
    val_check_interval: Optional[Any] = None
    accumulate_grad_batches: int = 1
    gradient_clip_val: Optional[float] = None
    # Fold K optimizer steps into ONE compiled dispatch (lax.scan inside
    # the executable; Keras-on-TPU's steps_per_execution). Per-step math
    # is unchanged; host-visible cadences (logging, val_check_interval,
    # callbacks, stop checks) quantize to K-step chunk boundaries, and
    # epoch/max_steps tails shorter than K run through the single-step
    # executable so budgets are exact. The win is dispatch amortization:
    # on a high-latency link to the chip, launch round trips stop
    # bounding steps/sec.
    steps_per_execution: int = 1
    log_every_n_steps: int = 50
    enable_checkpointing: bool = True
    default_root_dir: str = "."
    seed: Optional[int] = None
    precision: str = "fp32"
    # EMA of model weights (trainer/ema.py): decay enables the in-step
    # averaged copy riding opt_state; eval_ema evaluates with it.
    ema_decay: Optional[float] = None
    eval_ema: bool = False
    # Sharded (orbax) saves overlap tensorstore writes with the next epoch;
    # the finalization marker still gates restartability (checkpoint_io.py).
    async_checkpointing: bool = False
    # Log the pre-clip global grad norm each step (in-graph reduction).
    log_grad_norm: bool = False
    # Ship gathered optimizer state in the fit output so the driver's
    # save_checkpoint() writes fully-resumable files. Off = skip the
    # ~2x-params gather/transfer for Adam when worker-side ModelCheckpoint
    # is the only checkpoint path.
    ship_optimizer_state: bool = True
    # Print a parameter summary table at fit start (rank 0), PTL's
    # enable_model_summary.
    enable_model_summary: bool = True
    # predict(): accumulate + ship outputs through the rank-0 channel.
    # False = streaming inference (PredictionWriter writes per-rank shards;
    # per-rank memory stays O(1 batch)).
    return_predictions: bool = True
    callbacks: List[Any] = field(default_factory=list)


class TrainingPreempted(RuntimeError):
    """The fit answered a preemption notice (serve.preempt) with
    checkpoint-on-notice: a validated checkpoint was written at the
    step boundary the notice caught, and the loop exited cleanly.
    ``Trainer.fit``'s ``max_restarts`` loop catches this and resumes
    from ``ckpt_path`` bit-exactly, losing at most the one step that
    was in flight — instead of everything since the last periodic
    checkpoint. Picklable across the fabric (a worker-side preemption
    reaches the driver's retry loop as this same type)."""

    def __init__(self, ckpt_path: str, global_step: int = 0) -> None:
        super().__init__(
            f"fit preempted: checkpoint-on-notice written to {ckpt_path} "
            f"at step {global_step}"
        )
        self.ckpt_path = ckpt_path
        self.global_step = int(global_step)

    def __reduce__(self):  # keep attrs across cloudpickle round trips
        return (type(self), (self.ckpt_path, self.global_step))


def _limit(n_batches: Optional[int], limit: Any) -> Optional[int]:
    """None n_batches = a streaming loader (unknown length): int limits
    bound it, fractional limits have nothing to take a fraction OF."""
    if limit is None:
        return n_batches
    if isinstance(limit, float):
        if n_batches is None:
            raise ValueError(
                "fractional batch limits need a sized dataset; streaming "
                "(IterableDataset) loaders have no length — use an int "
                "limit or max_steps"
            )
        return max(1, int(n_batches * limit))
    return int(limit) if n_batches is None else min(n_batches, int(limit))


class TrainingLoop:
    """Executes fit/validate/test/predict for one worker process."""

    def __init__(
        self,
        spec: TrainerSpec,
        module: Any,
        strategy: Any,
        dist_env: Any,
        tune_session: Any = None,
        datamodule: Any = None,
    ) -> None:
        self.spec = spec
        self.module = module
        self.strategy = strategy
        self.dist_env = dist_env
        self.tune_session = tune_session
        self.datamodule = datamodule
        # Trainer-facade state visible to callbacks
        self.current_epoch = 0
        self.global_step = 0
        self.should_stop = False
        self.callback_metrics: Dict[str, Any] = {}
        self.logged_metrics: Dict[str, Any] = {}
        self.state: Dict[str, Any] = {"status": "initializing", "stage": None}
        self.callbacks = list(spec.callbacks)
        # Device state
        self.params = None
        self.opt_state = None
        self._tx = None
        self._rng = None
        self.sanity_checking = False
        # Host mirror of optax.MultiSteps progress (accumulation only):
        # _update_count = inner updates applied (windows + flushes),
        # _mini_host = micro-batches since the last update. Kept in sync
        # deterministically so current_lr never costs a device fetch.
        self._update_count: Optional[int] = None
        self._mini_host = 0

    # -- facade properties used by callbacks ---------------------------
    @property
    def global_rank(self) -> int:
        return self.dist_env.host_rank

    @property
    def world_size(self) -> int:
        return self.dist_env.world_size

    @property
    def default_root_dir(self) -> str:
        return self.spec.default_root_dir

    @property
    def has_validation(self) -> bool:
        return self._val_loader is not None

    @property
    def lightning_module(self) -> Any:  # parity-friendly alias
        return self.module

    # ------------------------------------------------------------------
    def _call_callbacks(self, hook: str, *args: Any) -> None:
        for cb in self.callbacks:
            getattr(cb, hook)(self, self.module, *args)

    def _setup_common(self) -> None:
        import jax

        reset_seed()
        self.module.trainer = self
        self.module.precision = self.spec.precision
        self.strategy.bind_module(self.module)
        seed = self.spec.seed if self.spec.seed is not None else 0
        self._rng = jax.random.PRNGKey(seed)

        source = self.module
        if self.datamodule is not None:
            # Per-node data prep hook, like the reference's worker-side
            # ``prepare_data`` call (ray_launcher.py:290).
            self.datamodule.prepare_data()
            self.datamodule.setup()
            source = self.datamodule
        skw = self.strategy.sampler_kwargs()
        try:
            loader = source.train_dataloader()
        except NotImplementedError:
            loader = None
        if loader is not None and hasattr(loader, "with_sampler"):
            loader = loader.with_sampler(
                num_replicas=skw["num_replicas"], rank=skw["rank"], seed=seed
            )
        self._train_loader = loader
        val = source.val_dataloader()
        if val is not None and hasattr(val, "with_sampler"):
            # Val/test are evaluated un-shuffled (test_ddp.py:179-211
            # semantics) and sharded the same per-host way.
            val = val.with_sampler(
                num_replicas=skw["num_replicas"], rank=skw["rank"], seed=seed
            )
        self._val_loader = val
        if self.spec.overfit_batches:
            # Overfit debugging: same fixed slice for train AND val, no
            # shuffling (order defines the slice). Batch limits were set
            # by the Trainer; only the loader wiring happens here. Val is
            # only redirected when the module HAS a val loop to run.
            if self._train_loader is not None and getattr(
                self._train_loader, "shuffle", False
            ):
                self._train_loader.shuffle = False
                sampler = getattr(self._train_loader, "sampler", None)
                if sampler is not None and hasattr(sampler, "shuffle"):
                    sampler.shuffle = False
            if val is not None:
                self._val_loader = self._train_loader

    def _init_state(self, ckpt_stream: Optional[Any]) -> None:
        import jax

        # Shape probe only — prefetch=0 so no background thread spins up
        # assembling batches that get discarded.
        sample_batch = next(iter(self._train_loader.iter_batches(1, prefetch=0)))
        init_rng, self._rng = jax.random.split(self._rng)
        params = self.module.init_params(init_rng, sample_batch)
        self._tx = self._wrap_optimizer(self._unpack_optimizers())
        opt_state = self._tx.init(params)
        sharded_path = (
            ckpt_stream.get("orbax_path")
            if isinstance(ckpt_stream, dict)
            else None
        )
        if ckpt_stream is not None and sharded_path is None:
            state = load_state_stream(ckpt_stream)
            params = state["params"]
            if "opt_state" in state:
                restored = state["opt_state"]
                expected = jax.tree_util.tree_structure(
                    jax.eval_shape(self._tx.init, params)
                )
                if jax.tree_util.tree_structure(restored) != expected:
                    raise RuntimeError(
                        "checkpointed optimizer state does not match the "
                        "current optimizer: accumulate_grad_batches/"
                        "gradient_clip_val/ema_decay/configure_optimizers "
                        "changed since the checkpoint was written. Resume "
                        "with the same optimizer options, or load params "
                        "only via validate/test/predict(ckpt_path=...)"
                    )
                opt_state = restored
            elif int(state.get("global_step", 0) or 0) > 0:
                warnings.warn(
                    "resuming fit from a checkpoint that carries training "
                    "progress (global_step="
                    f"{state['global_step']}) but no optimizer state — "
                    "Adam moments and any embedded LR schedule restart "
                    "from scratch. Prefer a worker-written checkpoint "
                    "(ModelCheckpoint) or a driver save_checkpoint() taken "
                    "after a fit (which now includes optimizer state).",
                    RuntimeWarning,
                    stacklevel=2,
                )
            self._restore_progress(state)
        self.params = self.strategy.place_params(params)
        self.opt_state = self.strategy.place_opt_state(opt_state, params)
        if sharded_path is not None:
            # Sharded restore: read straight into this topology's
            # shardings (works across different worker counts/mesh shapes).
            from ray_lightning_tpu.trainer.checkpoint_io import (
                OrbaxCheckpointIO,
            )

            restored, meta = OrbaxCheckpointIO().restore(
                sharded_path,
                {"params": self.params, "opt_state": self.opt_state},
            )
            self.params = restored["params"]
            self.opt_state = restored["opt_state"]
            self._restore_progress(meta)
        if self.spec.accumulate_grad_batches > 1:
            # Seed the host mirror from the (possibly restored) MultiSteps
            # counters — one fetch at init, none per step.
            # .ravel()[0]: counters may arrive as 0-d or replicated 1-d
            # arrays; plain int(ndim>0 array) is a NumPy deprecation.
            self._mini_host = int(
                np.asarray(jax.device_get(self.opt_state.mini_step)).ravel()[0]
            )
            self._update_count = int(
                np.asarray(jax.device_get(self.opt_state.gradient_step)).ravel()[0]
            )
            if getattr(self, "_resumed_mid_epoch", False) and self._mini_host:
                # Mid-epoch resume re-runs the epoch from batch 0: keeping
                # the restored partial window would accumulate those
                # batches' gradients a second time into the same update.
                import jax.numpy as jnp
                import optax

                ms = self.opt_state
                self.opt_state = self.strategy.place_opt_state(
                    optax.MultiStepsState(
                        mini_step=jnp.zeros_like(ms.mini_step),
                        gradient_step=ms.gradient_step,
                        inner_opt_state=ms.inner_opt_state,
                        acc_grads=jax.tree_util.tree_map(
                            jnp.zeros_like, ms.acc_grads
                        ),
                        skip_state=ms.skip_state,
                    ),
                    params,
                )
                self._mini_host = 0
        if self.spec.ema_decay:
            # A restored EMA sum only continues correctly under the decay
            # it was accumulated with (stored in the state).
            from ray_lightning_tpu.trainer.ema import find_ema_state

            st = find_ema_state(self.opt_state)
            if st is not None:
                stored = float(np.asarray(jax.device_get(st.decay)).ravel()[0])
                # The state stores float32; compare at that precision.
                if abs(stored - float(np.float32(self.spec.ema_decay))) > 1e-7:
                    raise RuntimeError(
                        f"checkpoint EMA was accumulated with decay "
                        f"{stored}, but this Trainer has ema_decay="
                        f"{self.spec.ema_decay}; resume with the same value"
                    )

    def _unpack_optimizers(self) -> Any:
        """Unpack ``configure_optimizers()`` return forms.

        Accepted (Lightning's dict convention, adapted to optax — the
        schedule lives INSIDE the transform, so the extra entry is for
        monitoring only):

        - ``optax.GradientTransformation``
        - ``{"optimizer": tx, "lr_schedule": step -> lr}``
        - ``(tx, lr_schedule)``
        """
        from ray_lightning_tpu.trainer.module import unpack_optimizers

        opt, self._lr_schedule = unpack_optimizers(
            self.module.configure_optimizers()
        )
        return opt

    @property
    def current_lr(self) -> Optional[float]:
        """Learning rate the NEXT optimizer update will use, from the
        module's declared ``lr_schedule`` (None when not declared).

        optax applies ``sched(update_count)`` with a 0-based count, so the
        next update after ``global_step`` micro-batches uses index
        ``global_step // K`` (one update per K micro-batches under
        ``accumulate_grad_batches=K`` / ``optax.MultiSteps``) — the same
        next-update convention PTL's LearningRateMonitor reports after
        ``scheduler.step()``.
        """
        from ray_lightning_tpu.trainer.module import schedule_lr

        # With accumulation the host mirror counts ACTUAL inner updates
        # (full windows + epoch-end partial-window flushes, both of which
        # advance the embedded schedule).
        return schedule_lr(
            getattr(self, "_lr_schedule", None),
            global_step=self.global_step,
            update_count=getattr(self, "_update_count", None),
        )

    def _wrap_optimizer(self, tx: Any) -> Any:
        """Apply Trainer-level optimizer options around the module's optax
        transform — both stay inside the one compiled step:

        - ``gradient_clip_val``: global-norm clip (PTL's default
          ``gradient_clip_algorithm="norm"``) chained before the update.
        - ``accumulate_grad_batches=K``: ``optax.MultiSteps`` accumulates K
          micro-batch grads on device and applies one update every K-th
          step; grads are averaged, so K micro-batches == one K-times-larger
          batch. ``global_step`` keeps counting micro-batches. A partial
          window left at epoch end is flushed (PTL applies an optimizer step
          on the last batch regardless of accumulation phase) — see
          ``_flush_accumulation``.
        """
        import optax

        if self.spec.gradient_clip_val:
            tx = optax.chain(
                optax.clip_by_global_norm(float(self.spec.gradient_clip_val)),
                tx,
            )
        if self.spec.ema_decay:
            from ray_lightning_tpu.trainer.ema import params_ema

            # After the optimizer so the EMA absorbs post-update weights;
            # inside _inner_tx so accumulation flushes update it too.
            tx = optax.chain(tx, params_ema(float(self.spec.ema_decay)))
        self._inner_tx = tx  # pre-MultiSteps transform, used by the flush
        if self.spec.accumulate_grad_batches > 1:
            tx = optax.MultiSteps(
                tx, every_k_schedule=int(self.spec.accumulate_grad_batches)
            )
        return tx

    def _flush_accumulation(self) -> None:
        """Apply any partially-accumulated gradient window at epoch end.

        ``MultiStepsState.acc_grads`` holds the running MEAN over the
        micro-batches seen so far, so applying the inner transform to it is
        exactly the update those micro-batches deserve — no zero-padding
        dilution, matching PTL's last-batch-forces-a-step semantics.
        """
        if self.spec.accumulate_grad_batches <= 1:
            return
        import jax

        # The host mirror tracks mini_step exactly (incremented per step,
        # reset at window/flush) — no device sync needed here.
        if self._mini_host == 0:
            return
        self._mini_host = 0
        self._update_count += 1
        if getattr(self, "_flush_step", None) is None:
            import jax.numpy as jnp
            import optax

            inner_tx = self._inner_tx
            strategy = self.strategy

            def flush(params, ms):
                updates, inner2 = inner_tx.update(
                    ms.acc_grads, ms.inner_opt_state, params
                )
                params2 = optax.apply_updates(params, updates)
                params2 = jax.lax.with_sharding_constraint(
                    params2, strategy.param_sharding(params2)
                )
                new_ms = optax.MultiStepsState(
                    mini_step=jnp.zeros_like(ms.mini_step),
                    gradient_step=ms.gradient_step + 1,
                    inner_opt_state=inner2,
                    acc_grads=jax.tree_util.tree_map(
                        jnp.zeros_like, ms.acc_grads
                    ),
                    skip_state=ms.skip_state,
                )
                new_ms = jax.lax.with_sharding_constraint(
                    new_ms, strategy.opt_sharding(new_ms, params2)
                )
                return params2, new_ms

            self._flush_step = jax.jit(flush, donate_argnums=(0, 1))
        self.params, self.opt_state = self._flush_step(
            self.params, self.opt_state
        )

    def _restore_progress(self, state: Dict[str, Any]) -> None:
        # A checkpoint saved mid-epoch (val_check_interval save, or a
        # max_steps/should_stop break) resumes by re-running that epoch —
        # re-trained batches beat silently skipping the epoch's remainder.
        bump = 0 if state.get("mid_epoch") else 1
        self._resumed_mid_epoch = bool(state.get("mid_epoch"))
        rb = int(state.get("resume_batch") or 0)
        self._resume_batch = 0
        if rb and state.get("mid_epoch"):
            # Checkpoint-on-notice (preemption): continue the SAME epoch
            # at the exact next batch — the loader stream is
            # deterministic given set_epoch + the sampler seed, so
            # skipping the trained prefix reproduces the uninterrupted
            # run bit-for-bit. The partial grad-accumulation window is
            # KEPT (no MultiSteps reset: no batch is re-accumulated).
            self._resume_batch = rb
            self._resumed_mid_epoch = False
        self.current_epoch = int(state.get("epoch", -1)) + bump
        self.global_step = int(state.get("global_step", 0))
        for cb in self.callbacks:
            cb_state = state.get("callbacks", {}).get(type(cb).__name__)
            if cb_state:
                cb.load_state_dict(cb_state)

    # ------------------------------------------------------------------
    def save_checkpoint(self, path: str, sharded: bool = False) -> None:
        """Write a checkpoint.

        Default: rank 0 gathers full state into a state-stream file (the
        reference's wire format, SURVEY.md §3.4). ``sharded=True``: every
        process writes its own shards via orbax — no gather, scales with
        GSPMD/ZeRO state (call from ALL ranks).
        """
        events = getattr(self, "_events", None)  # None outside a fit
        if events is not None:
            events.record(
                "trainer", "checkpoint", path=str(path), sharded=sharded,
                epoch=self.current_epoch, step=self.global_step,
            )
        if sharded:
            from ray_lightning_tpu.trainer.checkpoint_io import (
                OrbaxCheckpointIO,
            )

            meta = {
                "epoch": self.current_epoch,
                "mid_epoch": not getattr(self, "_epoch_complete", True),
                "global_step": self.global_step,
                "callbacks": {
                    type(cb).__name__: cb.state_dict() for cb in self.callbacks
                },
            }
            rb = getattr(self, "_preempt_resume_batch", None)
            if rb:
                meta["resume_batch"] = int(rb)
            if getattr(self, "_sharded_io", None) is None:
                from ray_lightning_tpu.trainer.checkpoint_io import (
                    AsyncOrbaxCheckpointIO,
                )

                self._sharded_io = (
                    AsyncOrbaxCheckpointIO()
                    if self.spec.async_checkpointing
                    else OrbaxCheckpointIO()
                )
            self._sharded_io.save(
                path,
                {"params": self.params, "opt_state": self.opt_state},
                meta,
                is_rank_zero=self.global_rank == 0,
            )
            return
        # checkpoint_state's gathers are collective under multi-process
        # sharding — every rank must run them; only rank 0 writes. (For
        # plain-device_get strategies non-zero ranks skip the gather.)
        if self.global_rank != 0 and not self.strategy.gather_is_collective:
            return
        state = self.checkpoint_state()
        if self.global_rank != 0:
            return
        stream = to_state_stream(state)
        from ray_lightning_tpu.utils.state_stream import state_stream_to_file

        state_stream_to_file(stream, path)

    @property
    def gather_is_collective(self) -> bool:
        """Do checkpoint-state gathers require every rank (see Strategy)?"""
        return bool(getattr(self.strategy, "gather_is_collective", False))

    def finalize_checkpoints(self) -> None:
        """Drain any in-flight async sharded save (no-op otherwise).

        Callbacks call this before deleting checkpoint directories that
        could still be mid-write. The explicit barrier makes the cross-rank
        ordering guaranteed by THIS call — not inherited from orbax's
        wait_until_finished internals — so rank 0 can only reach a
        directory deletion after every rank's writes are durable.
        """
        if getattr(self, "_sharded_io", None) is not None:
            self._sharded_io.finalize()
            self.strategy.barrier("finalize_checkpoints")

    def checkpoint_state(self) -> Dict[str, Any]:
        state = {
            "params": self.strategy.gather_state(self.params),
            "opt_state": self.strategy.gather_state(self.opt_state),
            "epoch": self.current_epoch,
            "mid_epoch": not getattr(self, "_epoch_complete", True),
            "global_step": self.global_step,
            "callbacks": {
                type(cb).__name__: cb.state_dict() for cb in self.callbacks
            },
        }
        rb = getattr(self, "_preempt_resume_batch", None)
        if rb:
            # Checkpoint-on-notice only: the exact epoch position for a
            # continue-the-epoch resume (see _restore_progress).
            state["resume_batch"] = int(rb)
        return state

    # ------------------------------------------------------------------
    def _preempt_pending(self, synced: bool) -> bool:
        """Has a preemption notice landed on this process
        (serve.preempt)? ``synced=True`` reaches a cross-rank consensus
        (any preempted rank stops everyone — the gang checkpoints and
        exits as a unit) and is a collective, like
        :meth:`_out_of_time`."""
        from ray_lightning_tpu.serve.preempt import peek_state

        st = peek_state()
        local = bool(st and st.get("pending"))
        if not synced:
            return local
        import jax

        if jax.process_count() == 1:
            return local
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(np.asarray(local))
        return bool(np.any(flags))

    def _preempt_exit(self, resume_batch: Optional[int]) -> None:
        """Checkpoint-on-notice: write a VALIDATED resume checkpoint at
        this step boundary, then exit the fit cleanly via
        :class:`TrainingPreempted` (which ``Trainer.fit``'s
        ``max_restarts`` loop catches and resumes from bit-exactly).

        ``resume_batch`` — batches of the current epoch already trained
        — rides the checkpoint so the resume continues the epoch at the
        exact next batch (the loader stream is deterministic given
        ``set_epoch`` + the sampler seed) instead of the re-run-the-epoch
        semantics periodic mid-epoch checkpoints use; any partial
        grad-accumulation window is likewise kept, not reset. None =
        the epoch just completed (resume starts the next one). The
        checkpoint name sorts into the ``last*`` resume group, so the
        restart scan picks it over older rolling checkpoints.
        """
        cb = next(
            (c for c in self.callbacks if hasattr(c, "best_model_path")),
            None,
        )
        d = getattr(cb, "dirpath", None) if cb is not None else None
        if not d:
            d = os.path.join(self.spec.default_root_dir, "checkpoints")
        os.makedirs(d, exist_ok=True)
        path = os.path.join(
            d, f"last-preempt-step{self.global_step:08d}.ckpt"
        )
        self._events.record(
            "trainer", "fit_preempt_checkpoint", level="warn",
            path=path, step=self.global_step, epoch=self.current_epoch,
            resume_batch=int(resume_batch or 0),
        )
        self._preempt_resume_batch = (
            int(resume_batch) if resume_batch else None
        )
        try:
            self.save_checkpoint(path)
        finally:
            self._preempt_resume_batch = None
        if self.global_rank == 0:
            # VALIDATED: an unreadable file must raise here (crash
            # semantics, resume from an older checkpoint) — never hand
            # the retry loop a checkpoint that cannot load.
            with open(path, "rb") as f:
                load_state_stream(f.read())
        tel = getattr(self, "telemetry", None)
        if tel is not None:
            tel.fit_done = True  # the fit-stall watchdog stands down
        self.state = {"status": "preempted", "stage": "fit"}
        raise TrainingPreempted(path, self.global_step)

    # ------------------------------------------------------------------
    def _out_of_time(self, synced: bool) -> bool:
        """Has the fit's wall-clock budget expired?

        ``synced=True`` reaches a cross-rank consensus (any rank out of
        time stops everyone) and may only be called at points every rank
        reaches together — it is a collective. ``synced=False`` is a pure
        local clock read, safe anywhere but only used to stop when this
        process is the whole world.
        """
        if getattr(self, "_fit_deadline", None) is None:
            return False
        import time as _time

        local = _time.monotonic() >= self._fit_deadline
        if not synced:
            return local
        import jax

        if jax.process_count() == 1:
            return local
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(np.asarray(local))
        return bool(np.any(flags))

    # ------------------------------------------------------------------
    def _anomaly_guard(self):
        """Own jax_debug_nans for the duration of one run (detect_anomaly).

        Worker-side: the compiled steps run here. With detect_anomaly,
        NaN/inf in any jitted output re-runs the computation de-optimized
        and raises at the producing op. try/finally restoration covers the
        raise itself — the feature's primary outcome is an exception, and
        leaking the de-optimizing flag into the caller's process (or
        clobbering a user-set one) would outlive the run.
        """
        import contextlib

        @contextlib.contextmanager
        def guard():
            import jax

            prev = bool(jax.config.jax_debug_nans)
            jax.config.update(
                "jax_debug_nans", bool(self.spec.detect_anomaly)
            )
            try:
                yield
            finally:
                jax.config.update("jax_debug_nans", prev)

        return guard()

    def run_fit(self, ckpt_stream: Optional[bytes] = None) -> Optional[WorkerOutput]:
        with self._anomaly_guard():
            try:
                return self._run_fit_impl(ckpt_stream)
            except (SystemExit, KeyboardInterrupt):
                raise
            except TrainingPreempted:
                # Not a crash: the checkpoint-on-notice already ran and
                # its own typed event fired — no fit_exception, no
                # flight-recorder bundle.
                raise
            except BaseException as exc:
                # Forensics BEFORE the raise unwinds: a structured event
                # plus a rate-limited flight-recorder bundle (metrics,
                # event tail, all-thread stacks) so a crashed fit leaves
                # a black box, not just a traceback. crash_dump never
                # raises — it must not mask the real error.
                from ray_lightning_tpu.obs.blackbox import crash_dump
                from ray_lightning_tpu.obs.events import get_event_log

                get_event_log().record(
                    "trainer", "fit_exception", level="error",
                    error=f"{type(exc).__name__}: {exc}"[:300],
                    epoch=self.current_epoch, step=self.global_step,
                )
                crash_dump(f"fit_exception:{type(exc).__name__}")
                raise
            finally:
                wd = getattr(self, "_watchdog", None)
                if wd is not None:
                    wd.stop()
                    self._watchdog = None

    def _run_fit_impl(
        self, ckpt_stream: Optional[bytes] = None
    ) -> Optional[WorkerOutput]:
        import jax
        import time as _time

        self.state = {"status": "running", "stage": "fit"}
        # Observability: per-step breakdown (data wait / compiled step /
        # drain) + compile events into the process registry; throughput
        # (tokens/s, MFU) lands at fit end. A few monotonic() reads per
        # dispatched chunk — noise next to a compiled step.
        from ray_lightning_tpu.obs.events import get_event_log
        from ray_lightning_tpu.obs.jaxmon import install_compile_listener
        from ray_lightning_tpu.obs.telemetry import TrainTelemetry

        install_compile_listener()
        self.telemetry = TrainTelemetry()
        self._events = get_event_log()
        self._events.record(
            "trainer", "fit_start",
            max_epochs=self.spec.max_epochs, resume_step=self.global_step,
        )
        # Opt-in fit-stall watchdog (obs.health): RLT_TRAIN_WATCHDOG_S=N
        # flags (event + rate-limited black-box bundle) a fit that
        # records no optimizer step for N seconds. Off by default — the
        # driver cannot distinguish a giant compile from a hang without
        # an operator-chosen budget.
        self._watchdog = None
        try:
            wd_s = float(os.environ.get("RLT_TRAIN_WATCHDOG_S", "0") or 0)
        except ValueError:
            wd_s = 0.0
        if wd_s > 0:
            from ray_lightning_tpu.obs import blackbox as obs_blackbox
            from ray_lightning_tpu.obs import health as obs_health

            wd = obs_health.Watchdog(
                interval_s=max(0.25, min(wd_s / 4.0, 5.0)),
                events=self._events,
                on_unhealthy=lambda comp, rep: obs_blackbox.crash_dump(
                    f"unhealthy:{comp}"
                ),
            )
            wd.add_check(
                obs_health.fit_stall_check(self.telemetry, wd_s)
            )
            self._watchdog = wd.start()
        self._fit_deadline = (
            _time.monotonic() + self.spec.max_time
            if self.spec.max_time is not None
            else None
        )
        # Per-step clock reads may STOP the loop only when this process is
        # the whole world; multi-process stops ride consensus boundaries.
        self._time_check_per_step = (
            self._fit_deadline is not None and jax.process_count() == 1
        )
        # Preemption checkpoint-on-notice (serve.preempt): single-process
        # fits answer the notice at the very next chunk boundary;
        # multi-process fits at the same consensus boundaries max_time
        # uses (mid-epoch val, epoch end), so every rank writes the same
        # checkpoint and takes the same exit.
        self._preempt_per_step = jax.process_count() == 1
        self._setup_common()
        if self._train_loader is None:
            raise RuntimeError("fit requires train_dataloader()")
        self._init_state(ckpt_stream)
        fold = max(1, int(self.spec.steps_per_execution))
        train_step = self.strategy.compile_train_step(
            self.module,
            self._tx,
            log_grad_norm=self.spec.log_grad_norm,
            fold_steps=fold,
            # Chunks arrive as ONE stacked (K, batch, ...) transfer from
            # the staging pipeline (stage_batches(stack=K)) — a folded
            # chunk costs a single H2D round trip, not K.
            fold_stacked=True,
        )
        # Tail chunks (epoch remainder, max_steps cap) shorter than the
        # fold run through the plain executable; jit compiles lazily, so
        # an epoch divisible by the fold never pays this compile.
        single_step = (
            train_step
            if fold == 1
            else self.strategy.compile_train_step(
                self.module, self._tx, log_grad_norm=self.spec.log_grad_norm
            )
        )
        val_step = (
            self.strategy.compile_eval_step(self.module, "val")
            if self._val_loader is not None
            else None
        )

        if self.spec.enable_model_summary and self.global_rank == 0:
            import sys

            from ray_lightning_tpu.utils.summary import summarize_params

            # stderr: stdout is a data channel for CLI generate / bench
            # JSON pipelines; diagnostics must not interleave into it.
            print(summarize_params(self.params), file=sys.stderr, flush=True)
        self.module.on_fit_start()
        self._call_callbacks("on_fit_start")
        mult = self.strategy.batch_multiplier

        # Pre-train sanity validation (PTL's num_sanity_val_steps): run a few
        # val batches so a broken eval path fails BEFORE a long train epoch.
        # Metrics are discarded and ``sanity_checking`` gates Tune reports
        # (tune/callbacks.py guard; reference tune.py:113-114). Skipped on
        # resume — the restored run already validated.
        if (
            val_step is not None
            and self.spec.num_sanity_val_steps
            and self.current_epoch == 0
            and self.global_step == 0
        ):
            self.sanity_checking = True
            saved_cb = dict(self.callback_metrics)
            saved_logged = dict(self.logged_metrics)
            try:
                self._run_eval_epoch(
                    val_step,
                    self._val_loader,
                    "sanity",
                    # PTL convention: -1 means run the FULL val set as sanity.
                    max_batches=(
                        None
                        if self.spec.num_sanity_val_steps < 0
                        else self.spec.num_sanity_val_steps
                    ),
                )
                self._call_callbacks("on_validation_end")
            finally:
                self.callback_metrics = saved_cb
                self.logged_metrics = saved_logged
                self.sanity_checking = False

        stop = False
        start_epoch = self.current_epoch
        for epoch in range(start_epoch, self.spec.max_epochs):
            if stop or self.should_stop:
                break
            self.current_epoch = epoch
            self._epoch_complete = False  # checkpoints saved mid-epoch
            # (val_check_interval) must resume by RE-RUNNING this epoch,
            # not skipping its remaining batches.
            self._train_loader.set_epoch(epoch)
            self._events.record(
                "trainer", "epoch_start", epoch=epoch, step=self.global_step
            )
            self.module.on_train_epoch_start(epoch)
            self._call_callbacks("on_train_epoch_start")

            n_batches = _limit(
                self._train_loader.num_batches(mult), self.spec.limit_train_batches
            )
            # Per-step device scalars buffer only until the next
            # log_every_n_steps boundary, where they drain into host float
            # lists — live device buffers stay O(log interval), not
            # O(steps), so 100k-step epochs don't pin 100k live scalars
            # for one giant end-of-epoch fetch.
            pending_logs: List[Tuple[Dict[str, Any], int]] = []
            epoch_host_vals: Dict[str, List[float]] = {}

            def _drain_logs() -> Dict[str, float]:
                """Fetch buffered device scalars (one device_get), append
                to the epoch's host accumulators, return the LATEST step's
                host values (what on_train_batch_end logs). Entries are
                ``(logs, n)``: a folded dispatch contributes one entry of
                n stacked per-step scalars."""
                if not pending_logs:
                    return {}
                fetched = jax.device_get(pending_logs)
                pending_logs.clear()
                last: Dict[str, float] = {}
                for d, n in fetched:
                    for k, v in d.items():
                        vals = np.asarray(v).reshape(n)
                        epoch_host_vals.setdefault(k, []).extend(
                            float(x) for x in vals
                        )
                    last = {
                        k: float(np.asarray(v).reshape(n)[-1])
                        for k, v in d.items()
                    }
                return last
            # Device staging pipeline: host batch assembly (loader prefetch
            # thread) -> H2D transfer (stager pool) -> step dispatch, all
            # overlapped with device compute.
            import itertools

            # Mid-epoch validation cadence (PTL's val_check_interval):
            # int = every N batches; float fraction = that share of the
            # epoch's batches.
            vci = self.spec.val_check_interval
            vci_from_float = False
            if isinstance(vci, float) and vci == 1.0:
                vci = None  # PTL: 1.0 == once per epoch (the default path)
            elif vci is not None and 0 < float(vci) < 1:
                vci_from_float = True
                if n_batches is None:
                    raise ValueError(
                        "float val_check_interval needs a sized dataset; "
                        "streaming (IterableDataset) loaders have no "
                        "length — use an int interval"
                    )
                vci = max(1, int(n_batches * float(vci)))
            elif vci is not None:
                vci = int(vci)
                if n_batches is not None and vci > n_batches > 0:
                    raise ValueError(
                        f"val_check_interval ({vci}) exceeds the number of "
                        f"training batches per epoch ({n_batches}); use a "
                        "smaller interval or a float epoch fraction"
                    )
            if vci is not None and fold > 1 and int(vci) % fold:
                if vci_from_float:
                    # A fraction promises a cadence, not an exact count:
                    # quantize to the nearest chunk boundary (docs/api.md
                    # 'cadences quantize to chunk boundaries'), clamped to
                    # the epoch so rounding UP can't push the cadence past
                    # the last batch and silently disable mid-epoch val.
                    vci = max(fold, round(int(vci) / fold) * fold)
                    if n_batches is not None and vci > n_batches >= fold:
                        vci = (n_batches // fold) * fold
                else:
                    raise ValueError(
                        f"val_check_interval ({vci}) must be a multiple of "
                        f"steps_per_execution ({fold}): the host only sees "
                        "chunk boundaries, so an unaligned int interval "
                        "would silently validate late (float fractions "
                        "quantize instead)"
                    )
            if (
                fold > 1
                and n_batches is not None
                and fold > n_batches > 0
                and not getattr(self, "_fold_warned", False)
            ):
                from ray_lightning_tpu.utils.rank_zero import rank_zero_warn

                self._fold_warned = True  # epoch-invariant; warn once
                rank_zero_warn(
                    f"steps_per_execution ({fold}) exceeds the batches per "
                    f"epoch ({n_batches}); every chunk is an epoch tail, so "
                    "no dispatch is ever folded — lower it to at most the "
                    "epoch length to get the amortization"
                )
            # Mid-epoch vals obey the same epoch cadence as epoch-end ones.
            val_epoch = (epoch + 1) % self.spec.check_val_every_n_epoch == 0
            last_val_step = -1

            # Exact-batch resume after checkpoint-on-notice: skip the
            # batches the preempted attempt already trained and continue
            # the epoch where it stopped (batch_idx stays epoch-absolute
            # so val cadences and epoch-end checks are unchanged).
            skip = 0
            if epoch == start_epoch:
                skip = int(getattr(self, "_resume_batch", 0) or 0)
                self._resume_batch = 0
            # Bound the epoch's batch pull by the step budget so the
            # stacked staging below is budget-exact: a folded chunk can
            # never overshoot max_steps (the tail arrives as singles).
            n_iter = None if n_batches is None else max(0, n_batches - skip)
            if self.spec.max_steps is not None:
                remaining = max(0, self.spec.max_steps - self.global_step)
                n_iter = (
                    remaining if n_iter is None else min(n_iter, remaining)
                )
                if remaining == 0:
                    stop = True
            staged = self.strategy.stage_batches(
                itertools.islice(
                    self._train_loader.iter_batches(mult),
                    skip,
                    None if n_iter is None else skip + n_iter,
                ),
                # Depth counts STAGING UNITS (a whole stacked chunk when
                # folding): 3 keeps one executing + two in flight without
                # multiplying in-flight buffers by the fold.
                depth=3,
                # stack=K: K host batches leave the host as ONE
                # (K, batch, ...) transfer; epoch tails shorter than K
                # arrive as singles for the single-step executable.
                stack=fold if fold > 1 else 0,
            )
            batch_idx = skip - 1
            # Explicit iterator so each chunk's wall time splits into the
            # three host-observable segments (obs.telemetry): data wait
            # (blocking on the staged pipeline — where device compute
            # surfaces under async dispatch), the step call (dispatch),
            # and the drain (log fetch, callbacks, mid-epoch val).
            stream = iter(() if stop else staged)
            try:
                while True:
                    t_pull = _time.monotonic()
                    try:
                        item = next(stream)
                    except StopIteration:
                        break
                    t_fetch = _time.monotonic()
                    n_chunk, payload = item if fold > 1 else (1, item)
                    start_step = self.global_step
                    if n_chunk > 1:
                        self.params, self.opt_state, logs = train_step(
                            self.params,
                            self.opt_state,
                            payload,
                            self._rng,
                            start_step,
                        )
                        pending_logs.append((logs, n_chunk))  # no sync here
                    else:
                        self.params, self.opt_state, logs = single_step(
                            self.params,
                            self.opt_state,
                            payload,
                            self._rng,
                            start_step,
                        )
                        pending_logs.append((logs, 1))
                    t_dispatch = _time.monotonic()
                    batch_idx += n_chunk
                    self.global_step += n_chunk
                    if self._update_count is not None:
                        self._mini_host += n_chunk
                        self._update_count += (
                            self._mini_host // self.spec.accumulate_grad_batches
                        )
                        self._mini_host %= self.spec.accumulate_grad_batches
                    if (
                        # Crossed a log boundary within this chunk (for
                        # fold=1 this is exactly `global_step % N == 0`).
                        self.global_step // self.spec.log_every_n_steps
                        != start_step // self.spec.log_every_n_steps
                        # Streaming epochs (n_batches None) have no known
                        # final batch; the post-loop drain covers the tail.
                        or (n_batches is not None and batch_idx == n_batches - 1)
                    ):
                        host_logs = _drain_logs()
                        self.logged_metrics.update(host_logs)
                        self._call_callbacks("on_train_batch_end", host_logs, batch_idx)
                    if (
                        val_step is not None
                        and vci
                        and val_epoch
                        and (batch_idx + 1) % vci == 0
                    ):
                        if (
                            n_batches is not None
                            and batch_idx == n_batches - 1
                            and self._mini_host == 0
                        ):
                            # Final batch, nothing left to flush: any
                            # checkpoint this val writes is epoch-complete.
                            self._epoch_complete = True
                        self._run_eval_epoch(val_step, self._val_loader, "val")
                        self._call_callbacks("on_validation_end")
                        last_val_step = self.global_step
                        # Every rank just finished the same val epoch: a
                        # safe point for the max_time consensus check
                        # (and the multi-process preemption consensus).
                        if self._out_of_time(synced=True):
                            self.should_stop = True
                        if not self._preempt_per_step and (
                            self._preempt_pending(synced=True)
                        ):
                            self._preempt_exit(batch_idx + 1)
                    self.telemetry.record_chunk(
                        n_chunk,
                        data_wait=t_fetch - t_pull,
                        step=t_dispatch - t_fetch,
                        drain=_time.monotonic() - t_dispatch,
                    )
                    if self._preempt_per_step and self._preempt_pending(
                        synced=False
                    ):
                        # Consume the notice NOW: a validated checkpoint
                        # at this exact step boundary, then a clean exit
                        # the max_restarts loop resumes from bit-exactly.
                        self._preempt_exit(batch_idx + 1)
                    if (
                        (
                            self.spec.max_steps is not None
                            and self.global_step >= self.spec.max_steps
                        )
                        or self.should_stop
                        or (self._time_check_per_step and self._out_of_time(False))
                    ):
                        # should_stop: a mid-epoch val's EarlyStopping must
                        # end training NOW, not at the epoch boundary —
                        # stopping inside very long epochs is the point of
                        # val_check_interval.
                        stop = True
                        break
            finally:
                staged.close()

            # Apply any partial grad-accumulation window before val sees
            # (and checkpoints capture) the epoch's params — but only when
            # the epoch ran all its batches: PTL's flush is a
            # last-batch-of-epoch semantic, so a max_steps stop that landed
            # ON the final batch still flushes, while an earlier stop must
            # not advance params past the requested step budget.
            flushed = False
            if not stop or (
                n_batches is not None and batch_idx == n_batches - 1
            ):
                flushed = self._mini_host > 0  # flush will change params
                self._flush_accumulation()
                self._epoch_complete = True

            # Drain any steps since the last boundary (early max_steps/
            # should_stop breaks), then reduce the epoch means on host.
            _drain_logs()
            if epoch_host_vals:
                epoch_means = {
                    k: float(np.mean(vals))
                    for k, vals in epoch_host_vals.items()
                }
                self.callback_metrics.update(epoch_means)
                # _step-forked keys, like PTL's `loss_step`/`loss_epoch`
                # metric fidelity the reference asserts (test_ddp.py:326-352)
                self.callback_metrics.update(
                    {f"{k}_epoch": v for k, v in epoch_means.items()}
                )

            if (
                val_step is not None
                and val_epoch
                # A mid-epoch val that landed exactly on the final batch
                # already validated these params — unless the accumulation
                # flush just changed them.
                and (last_val_step != self.global_step or flushed)
                # A callback-requested stop means stop NOW — don't pay a
                # final val epoch on the way out (max_steps stops keep it:
                # the budgeted run still wants its terminal metrics).
                and not self.should_stop
            ):
                self._run_eval_epoch(val_step, self._val_loader, "val")
                self._call_callbacks("on_validation_end")

            self.module.on_train_epoch_end(epoch, dict(self.callback_metrics))
            self._call_callbacks("on_train_epoch_end")
            self._events.record(
                "trainer", "epoch_end", epoch=epoch, step=self.global_step
            )
            # Epoch end is the multi-process max_time boundary (and catches
            # budget expiry during the val epoch in any topology).
            if self._out_of_time(synced=True):
                self.should_stop = True
            if not self._preempt_per_step and self._preempt_pending(
                synced=True
            ):
                # Epoch-complete exit: resume starts the NEXT epoch.
                self._preempt_exit(None)

        self._record_fit_throughput(mult)
        self.telemetry.fit_done = True  # the fit-stall watchdog stands down
        self._events.record(
            "trainer", "fit_end", epochs=self.current_epoch + 1,
            step=self.global_step,
        )
        self.state = {"status": "finished", "stage": "fit"}
        self.module.params = self.params
        self.module.on_fit_end()
        self._call_callbacks("on_fit_end")
        # Drain any in-flight async save (collective: every rank) so the
        # last checkpoint is finalized before workers exit.
        self.finalize_checkpoints()
        self.strategy.teardown_worker()
        return self._collect_rank_zero_results(results=None)

    def _record_fit_throughput(self, mult: int) -> None:
        """Tokens/s + MFU into the telemetry when the module's shape is
        known (duck-typed: ``batch_size`` + ``config.max_seq``, i.e. LM
        modules). MFU additionally needs a known chip peak
        (utils/flops); on CPU it is omitted, never fabricated."""
        tel = getattr(self, "telemetry", None)
        if tel is None or tel.wall_s <= 0 or tel.steps == 0:
            return
        bs = getattr(self.module, "batch_size", None)
        seq = getattr(getattr(self.module, "config", None), "max_seq", None)
        if not bs or not seq:
            return
        tokens = int(bs) * max(1, int(mult)) * int(seq) * tel.steps
        fpt = peak = None
        if self.params is not None:
            import jax

            from ray_lightning_tpu.obs.telemetry import (
                flops_per_token,
                peak_flops_total,
            )

            n_params = sum(
                int(np.prod(np.shape(x)))
                for x in jax.tree_util.tree_leaves(self.params)
            )
            cfg = self.module.config
            n_layer = getattr(cfg, "n_layer", None)
            d_model = getattr(cfg, "d_model", None)
            if n_layer and d_model:
                fpt = flops_per_token(n_params, n_layer, d_model, int(seq))
                devs = jax.local_devices()
                if devs:
                    peak = peak_flops_total(
                        devs[0].device_kind, jax.device_count()
                    )
        tel.record_throughput(tokens, tel.wall_s, fpt, peak)

    def _ema_params(self) -> Optional[Any]:
        """Debias-corrected EMA weights from opt_state (None when EMA is
        off, no update has run, or opt_state is absent — eval-only restores
        ship params alone)."""
        if not self.spec.ema_decay or self.opt_state is None:
            return None
        from ray_lightning_tpu.trainer.ema import ema_params

        return ema_params(self.opt_state, float(self.spec.ema_decay))

    def _eval_params(self) -> Any:
        """Weights the eval/predict steps should see: the EMA copy when
        ``eval_ema`` is set, else the live params.

        In standalone validate/test/predict the EMA arrives from the
        checkpoint (module-state ``ema_params`` or the resume-format
        ``opt_state``) or the module's own recovered copy; asking for
        ``eval_ema`` with no EMA anywhere is an error, not a silent
        live-weights eval. During fit, a zero-update EMA (sanity val)
        falls back to live weights.
        """
        if not self.spec.eval_ema:
            return self.params
        ema = self._ema_params()
        if ema is None and getattr(self, "_eval_ema_src", None) is not None:
            ema = self.strategy.place_params(self._eval_ema_src)
        if ema is not None:
            return ema
        if self.spec.ema_decay and self.opt_state is not None:
            # Fit-time EMA pending its first update (sanity val): live
            # weights ARE the average so far.
            return self.params
        raise RuntimeError(
            "eval_ema=True but no EMA weights are available (fit with "
            "ema_decay=... first, or evaluate a checkpoint that carries "
            "the average; sharded eval-only restores don't materialize "
            "optimizer state, so use a state-stream checkpoint)"
        )

    def _run_eval_epoch(
        self,
        eval_step,
        loader,
        prefix: str,
        max_batches: Optional[int] = None,
    ) -> Dict[str, float]:
        import jax

        events = getattr(self, "_events", None)  # None outside a fit
        if events is not None:
            events.record(
                "trainer", "eval_epoch", stage=prefix,
                epoch=self.current_epoch, step=self.global_step,
            )
        mult = self.strategy.batch_multiplier
        limit = (
            self.spec.limit_test_batches
            if prefix == "test"
            else self.spec.limit_val_batches
        )
        n_batches = _limit(loader.num_batches(mult), limit)
        if max_batches is not None:
            n_batches = (
                max_batches if n_batches is None else min(n_batches, max_batches)
            )
        if n_batches is None and not getattr(self, "_warned_stream_eval", False):
            # Train epochs over unbounded streams are boundable with
            # max_steps; an eval epoch has no such brake.
            self._warned_stream_eval = True
            warnings.warn(
                "evaluating over a streaming (IterableDataset) loader with "
                "no batch limit: the eval epoch runs until the stream "
                "ends — set limit_val_batches/limit_test_batches (int) if "
                "the stream is unbounded",
                RuntimeWarning,
                stacklevel=2,
            )
        # Each step returns (per-key masked sums, real-sample count) — device
        # scalars, fetched once at the end. The weighted combine makes epoch
        # metrics exact on non-divisible datasets (padding rows carry zero
        # weight), matching the reference's exact-value contract
        # (test_ddp.py:326-352) without dynamic tail shapes.
        all_pairs: List[Any] = []
        # (batch, mask) tuples are one pytree: the stager transfers both in
        # the same overlapped H2D pipeline as the train path. islice bounds
        # the HOST iterator so the stager never prefetches (and transfers)
        # batches past the cutoff.
        import itertools

        # Eval folding (steps_per_execution): masked (sums, count) pairs
        # accumulate associatively, so scanning K eval batches in one
        # dispatch preserves the epoch means (up to fp32 summation order;
        # see compile_folded_eval_step) — pure dispatch amortization, no
        # cadence caveats. Folded executables cache per compiled eval
        # step (one per loop lifetime; shape-polymorphic in the fold).
        fold = max(1, int(self.spec.steps_per_execution))
        folded = None
        if fold > 1:
            cache = getattr(self, "_folded_eval_cache", None)
            if cache is None:
                cache = self._folded_eval_cache = {}
            folded = cache.get(eval_step)
            if folded is None:
                folded = cache[eval_step] = (
                    self.strategy.compile_folded_eval_step(eval_step)
                )
        staged = self.strategy.stage_batches(
            itertools.islice(
                loader.iter_batches(mult, with_mask=True), n_batches
            ),
            stack=fold if folded is not None else 0,
        )
        eval_params = self._eval_params()
        try:
            if folded is not None:
                for n, payload in staged:
                    step_fn = folded if n > 1 else eval_step
                    all_pairs.append(
                        step_fn(eval_params, payload[0], payload[1])
                    )
            else:
                for batch, gmask in staged:
                    all_pairs.append(eval_step(eval_params, batch, gmask))
        finally:
            staged.close()
        if not all_pairs:
            return {}
        fetched = jax.device_get(all_pairs)
        total = sum(float(count) for _, count in fetched)
        keys = fetched[0][0].keys()
        means = {
            k: float(sum(float(sums[k]) for sums, _ in fetched) / max(total, 1.0))
            for k in keys
        }
        self.callback_metrics.update(means)
        self.logged_metrics.update(means)
        if prefix in ("val", "validate"):
            self.module.on_validation_epoch_end(means)
        return means

    def run_evaluate(
        self, stage: str, ckpt_stream: Optional[bytes] = None
    ) -> Optional[WorkerOutput]:
        with self._anomaly_guard():
            return self._run_evaluate_impl(stage, ckpt_stream)

    def _run_evaluate_impl(
        self, stage: str, ckpt_stream: Optional[bytes] = None
    ) -> Optional[WorkerOutput]:
        self.state = {"status": "running", "stage": stage}
        self._setup_common()
        source = self.datamodule if self.datamodule is not None else self.module
        loader = (
            self._val_loader
            if stage in ("val", "validate")
            else source.test_dataloader()
        )
        if loader is not None and hasattr(loader, "with_sampler") and stage not in ("val", "validate"):
            skw = self.strategy.sampler_kwargs()
            loader = loader.with_sampler(
                num_replicas=skw["num_replicas"], rank=skw["rank"], seed=0
            )
        if loader is None:
            raise RuntimeError(f"{stage} requires a dataloader")
        self._restore_or_adopt(ckpt_stream)
        eval_step = self.strategy.compile_eval_step(self.module, stage)
        metrics = self._run_eval_epoch(eval_step, loader, stage)
        self.state = {"status": "finished", "stage": stage}
        self.strategy.teardown_worker()
        return self._collect_rank_zero_results(results=[metrics])

    def run_predict(
        self, ckpt_stream: Optional[bytes] = None
    ) -> Optional[WorkerOutput]:
        with self._anomaly_guard():
            return self._run_predict_impl(ckpt_stream)

    def _run_predict_impl(
        self, ckpt_stream: Optional[bytes] = None
    ) -> Optional[WorkerOutput]:
        self.state = {"status": "running", "stage": "predict"}
        self._setup_common()
        source = self.datamodule if self.datamodule is not None else self.module
        loader = source.predict_dataloader()
        if loader is not None and hasattr(loader, "with_sampler"):
            skw = self.strategy.sampler_kwargs()
            loader = loader.with_sampler(
                num_replicas=skw["num_replicas"], rank=skw["rank"], seed=0
            )
        if loader is None:
            raise RuntimeError("predict requires predict_dataloader()")
        self._restore_or_adopt(ckpt_stream)
        predict_step = self.strategy.compile_eval_step(self.module, "predict")
        import jax

        import itertools

        mult = self.strategy.batch_multiplier
        n_batches = _limit(
            loader.num_batches(mult), self.spec.limit_predict_batches
        )
        keep = self.spec.return_predictions
        # on_predict_end receives THIS RANK's predictions (PTL's
        # write_on_epoch_end contract): accumulate the local shards only
        # when some callback actually overrides the hook, independent of
        # whether the full set rides the rank-0 return channel.
        from ray_lightning_tpu.trainer.callbacks import Callback as _CB

        wants_end = any(
            type(cb).on_predict_end is not _CB.on_predict_end
            for cb in self.callbacks
            if isinstance(cb, _CB)
        )
        preds = []
        local_preds = []
        own_rows = None
        eval_params = self._eval_params()
        for bi, (host_batch, host_mask) in enumerate(
            itertools.islice(
                loader.iter_batches(mult, with_mask=True), n_batches
            )
        ):
            batch = self.strategy.make_global_batch(host_batch)
            gmask = self.strategy.make_global_batch(host_mask)
            out, mask = jax.device_get(predict_step(eval_params, batch, gmask))
            # Trim wrap-around padding rows so predictions line up 1:1 with
            # the dataset (mask comes back replicated alongside preds).
            mask = np.asarray(mask).astype(bool)
            if own_rows is None or len(own_rows) != len(mask):
                own_rows = self._owner_rows(gmask)
            # Callbacks receive THIS process's disjoint share of the rows
            # (PredictionWriter shards then partition the dataset exactly
            # once across ranks); the rank-0 result channel still carries
            # the full set when predictions are kept.
            local = jax.tree_util.tree_map(
                lambda p: np.asarray(p)[own_rows & mask], out
            )
            self._call_callbacks("on_predict_batch_end", local, bi)
            if wants_end:
                local_preds.append(local)
            # return_predictions=False: the full prediction dies here —
            # per-rank memory stays O(1 batch) (or O(local shard) with an
            # epoch-end consumer) and nothing crosses the rank-0 result
            # channel (the callbacks above already consumed it, e.g. a
            # PredictionWriter streaming shards to disk).
            if keep:
                preds.append(
                    local
                    if bool(own_rows.all())
                    else jax.tree_util.tree_map(
                        lambda p: np.asarray(p)[mask], out
                    )
                )
        self._call_callbacks(
            "on_predict_end", local_preds if wants_end else None
        )
        self.state = {"status": "finished", "stage": "predict"}
        self.strategy.teardown_worker()
        return self._collect_rank_zero_results(results=preds if keep else None)

    @staticmethod
    def _owner_rows(gmask: Any) -> "np.ndarray":
        """Boolean mask of global batch rows THIS process canonically owns.

        Derived from the assembled mask array's own sharding
        (``devices_indices_map``), so it makes no assumption about mesh
        device ordering; rows replicated across processes (model axes
        spanning hosts) go to the lowest-index owner. The per-process masks
        partition [0, G) exactly — PredictionWriter shards are disjoint and
        complete by construction.
        """
        import jax

        g = gmask.shape[0]
        if jax.process_count() == 1:
            return np.ones(g, dtype=bool)
        owner = np.full(g, np.iinfo(np.int32).max, dtype=np.int32)
        for d, idx in gmask.sharding.devices_indices_map(gmask.shape).items():
            sl = idx[0]
            owner[sl] = np.minimum(owner[sl], d.process_index)
        return owner == jax.process_index()

    def _restore_or_adopt(self, ckpt_stream: Optional[Any]) -> None:
        """Load params from a checkpoint (stream bytes or sharded orbax
        directory marker) or adopt the module's own."""
        sharded_path = (
            ckpt_stream.get("orbax_path")
            if isinstance(ckpt_stream, dict)
            else None
        )
        if sharded_path is not None:
            # Need placed abstract params to restore into; init a fresh tree
            # for shapes, then read the checkpoint over it.
            import jax

            sample_batch = next(
                iter(self._train_or_any_loader().iter_batches(1, prefetch=0))
            )
            init_rng, self._rng = jax.random.split(self._rng)
            params = self.module.init_params(init_rng, sample_batch)
            placed = self.strategy.place_params(params)
            from ray_lightning_tpu.trainer.checkpoint_io import (
                OrbaxCheckpointIO,
            )

            # On-disk tree also carries opt_state — eval only needs params,
            # so restore partially rather than materialising optimizer
            # shards we'd immediately drop.
            restored, _ = OrbaxCheckpointIO().restore(
                sharded_path, {"params": placed}, partial=True
            )
            self.params = restored["params"]
            return
        if ckpt_stream is not None:
            state = load_state_stream(ckpt_stream)
            params = state["params"] if "params" in state else state
            if isinstance(state, dict):
                if state.get("ema_params") is not None:
                    self._eval_ema_src = state["ema_params"]
                elif self.spec.eval_ema and "opt_state" in state:
                    # Resume-format checkpoints carry the EMA inside the
                    # optimizer state; debiasing materializes a full
                    # param-sized copy, so only do it when eval will
                    # actually read it.
                    from ray_lightning_tpu.trainer.ema import ema_params

                    self._eval_ema_src = ema_params(state["opt_state"])
        elif self.module.params is not None:
            params = self.module.params
            self._eval_ema_src = self.module.ema_params
        else:
            raise RuntimeError(
                "no parameters available: fit first, or pass ckpt_path"
            )
        self.params = self.strategy.place_params(params)

    def _train_or_any_loader(self) -> Any:
        """A loader usable as an init-shape probe (train if defined, else
        val/test/predict)."""
        if self._train_loader is not None:
            return self._train_loader
        source = self.datamodule if self.datamodule is not None else self.module
        for name in ("val_dataloader", "test_dataloader", "predict_dataloader"):
            loader = getattr(source, name, lambda: None)()
            if loader is not None:
                return loader
        raise RuntimeError("no dataloader available to probe init shapes")

    def _gathered_module_state_stream(self) -> Optional[bytes]:
        """Gather module state on EVERY rank; serialize on rank 0 only.

        ``gather_state`` is a jitted all-gather — under multi-process
        sharding (ZeRO/GSPMD spanning hosts) it is a collective that every
        rank must enter. For plain-device_get strategies (DP/ring) the
        non-zero ranks skip the gather entirely: participating would only
        copy full state to host and discard it.
        """
        if self.params is None:
            return None
        if self.global_rank != 0 and not self.strategy.gather_is_collective:
            return None
        module_state = dict(self.module.state_dict())
        module_state["params"] = self.strategy.gather_state(self.params)
        ema_dev = self._ema_params()
        if ema_dev is not None:
            module_state["ema_params"] = self.strategy.gather_state(ema_dev)
        elif getattr(self, "_eval_ema_src", None) is not None:
            # Eval-only run restored the average from a checkpoint:
            # re-ship it (already host-side) so recovery keeps it.
            module_state["ema_params"] = self._eval_ema_src
        if (
            self.opt_state is not None
            and self.state.get("stage") == "fit"
            and self.spec.ship_optimizer_state
        ):
            # Ship optimizer state so the driver's save_checkpoint()
            # writes resumable files (Adam moments + embedded LR
            # schedule continue instead of silently restarting).
            module_state["opt_state"] = self.strategy.gather_state(
                self.opt_state
            )
        if self.global_rank != 0:
            return None
        return to_state_stream(module_state)

    # ------------------------------------------------------------------
    def _collect_rank_zero_results(self, results: Any) -> Optional[WorkerOutput]:
        """Package rank-0 state for the driver (the reference's
        ``_collect_rank_zero_results``, ray_launcher.py:312-349: rank!=0
        returns None; weights go host-side as bytes; metrics cross as
        numpy).

        The state gathers run on EVERY rank before the rank gate:
        ``gather_state`` is a jitted all-gather, which under multi-process
        sharding (ZeRO/GSPMD spanning hosts) is a collective — a
        rank-0-only call would deadlock waiting for peers that already
        moved on.
        """
        state_stream = self._gathered_module_state_stream()
        if self.global_rank != 0:
            return None
        best_model_path = None
        callback_states: Dict[str, Any] = {}
        for cb in self.callbacks:
            callback_states[type(cb).__name__] = cb.state_dict()
            if hasattr(cb, "best_model_path") and cb.best_model_path:
                best_model_path = cb.best_model_path
        trainer_state = dict(
            self.state,
            epoch=self.current_epoch,
            global_step=self.global_step,
            update_count=self._update_count,
        )
        if self.state.get("stage") == "fit":
            # Whether the fit stopped mid-epoch (max_steps/should_stop):
            # the driver records it so its save_checkpoint() files resume
            # with the same re-run-the-epoch semantics as worker-written
            # checkpoints (incl. the MultiSteps window reset).
            trainer_state["mid_epoch"] = not getattr(
                self, "_epoch_complete", True
            )
            if getattr(self, "telemetry", None) is not None:
                # Step-time breakdown + compile events + throughput; the
                # driver surfaces it as trainer.state["telemetry"].
                trainer_state["telemetry"] = self.telemetry.snapshot()
        return WorkerOutput(
            best_model_path=best_model_path,
            state_stream=state_stream,
            trainer_state=dict(
                trainer_state,
                # Evaluated HERE because the worker owns a live backend;
                # the driver must not init one (on TPU hosts the chips
                # belong to worker processes — driver init would bind them).
                current_lr=self.current_lr,
            ),
            results=results,
            callback_metrics={
                k: np.asarray(v) for k, v in self.callback_metrics.items()
            },
            logged_metrics={
                k: np.asarray(v) for k, v in self.logged_metrics.items()
            },
            callback_states=callback_states,
        )
