"""Learning-rate range test (PTL's ``Tuner.lr_find`` analog).

Short exponential LR sweep (Smith, "Cyclical Learning Rates", 2015): one
jitted update per step with the LR ramping from ``min_lr`` to ``max_lr``,
loss recorded per step, early-stopped on divergence. The suggestion is
the LR at the steepest descent of the smoothed curve — the classic
pick-one-below-the-cliff heuristic.

Runs single-process on the default backend (a range test is a probe, not
a training run); the chosen LR then feeds any strategy's real fit.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, List, Optional

import numpy as np


@dataclasses.dataclass
class LRFindResult:
    lrs: List[float]
    losses: List[float]  # smoothed
    raw_losses: List[float]
    suggestion: Optional[float]

    def suggestion_or(self, default: float) -> float:
        return self.suggestion if self.suggestion is not None else default


def lr_find(
    module: Any,
    min_lr: float = 1e-6,
    max_lr: float = 1.0,
    num_steps: int = 100,
    optimizer: Optional[Callable[[Any], Any]] = None,
    smooth: float = 0.05,
    divergence_factor: float = 4.0,
    seed: int = 0,
) -> LRFindResult:
    """Sweep the LR exponentially over ``num_steps`` minibatches.

    Args:
      module: a TPUModule (uses its ``train_dataloader`` and
        ``training_step``; params re-initialized from ``seed`` — the
        probe never touches ``module.params``).
      optimizer: ``schedule -> optax transform``; default ``optax.adam``.
        Pass the same family you will train with (the useful range is
        optimizer-dependent).
      smooth: EMA coefficient for the loss curve the heuristics read.
      divergence_factor: stop once the smoothed loss exceeds this multiple
        of its best value (the cliff).

    Returns an :class:`LRFindResult`; ``suggestion`` is None when the
    curve never descends (raise ``max_lr`` or fix the model).
    """
    import jax
    import optax

    if not (0 < min_lr < max_lr):
        raise ValueError(f"need 0 < min_lr < max_lr, got {min_lr}, {max_lr}")
    if num_steps < 2:
        raise ValueError("num_steps must be >= 2")

    ratio = max_lr / min_lr

    def schedule(step):
        import jax.numpy as jnp

        frac = jnp.asarray(step, jnp.float32) / float(num_steps - 1)
        return jnp.asarray(min_lr, jnp.float32) * jnp.power(
            jnp.asarray(ratio, jnp.float32), frac
        )

    tx = (optimizer or optax.adam)(schedule)
    loader = module.train_dataloader()
    rng = jax.random.PRNGKey(seed)
    init_rng, step_rng = jax.random.split(rng)
    batches = loader.iter_batches(1, prefetch=0)
    first = next(iter(loader.iter_batches(1, prefetch=0)))
    params = module.init_params(init_rng, first)
    opt_state = tx.init(params)

    @jax.jit
    def step_fn(params, opt_state, batch, rng):
        def loss_fn(p):
            loss, _ = module.training_step(p, batch, rng)
            return loss

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    lrs: List[float] = []
    raw: List[float] = []
    smoothed: List[float] = []
    ema = None
    best = math.inf
    step = 0
    while step < num_steps:
        try:
            batch = next(batches)
        except StopIteration:
            batches = loader.iter_batches(1, prefetch=0)  # cycle epochs
            continue
        params, opt_state, loss = step_fn(params, opt_state, batch, step_rng)
        loss = float(np.asarray(loss))
        lr_now = float(np.asarray(schedule(step)))
        if not math.isfinite(loss):
            break  # past the cliff: NaN/inf ends the sweep
        ema = loss if ema is None else smooth * loss + (1 - smooth) * ema
        lrs.append(lr_now)
        raw.append(loss)
        smoothed.append(ema)
        best = min(best, ema)
        if ema > divergence_factor * best and step > 1:
            break
        step += 1

    suggestion = None
    if len(smoothed) >= 4:
        grads = np.gradient(np.asarray(smoothed))
        # Skip the first few warmup points; require an actual descent.
        lo = min(3, len(grads) - 1)
        idx = lo + int(np.argmin(grads[lo:]))
        if grads[idx] < 0:
            suggestion = lrs[idx]
    return LRFindResult(
        lrs=lrs, losses=smoothed, raw_losses=raw, suggestion=suggestion
    )

