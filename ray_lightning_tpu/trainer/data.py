"""Data loading with distributed sharding semantics.

The reference injects ``DistributedSampler(num_replicas=world_size,
rank=global_rank)`` kwargs into PTL's dataloaders
(/root/reference/ray_lightning/ray_ddp.py:315-324; behavior pinned by
test_ddp.py:179-211: train shuffled, val/test not, correct replica/rank).

TPU twist: one worker process owns several chips, so sharding happens at two
levels — the sampler shards the *dataset* across host processes, and the
global-batch array is sharded across *chips* by GSPMD when the loop builds a
globally-sharded ``jax.Array`` from each host's local slice
(``jax.make_array_from_process_local_data``). ``DataLoader.batch_size`` is
the per-chip microbatch, matching the reference's per-worker semantics.
"""
from __future__ import annotations

import math
from typing import Any, Iterator, Optional, Sequence, Tuple

import numpy as np


class Dataset:
    """Minimal map-style dataset protocol: __len__ + __getitem__."""

    def __len__(self) -> int:
        raise NotImplementedError

    def __getitem__(self, idx: int) -> Any:
        raise NotImplementedError


class ArrayDataset(Dataset):
    """Dataset over parallel numpy arrays (features, labels, ...)."""

    def __init__(self, *arrays: np.ndarray) -> None:
        assert arrays and all(len(a) == len(arrays[0]) for a in arrays)
        self.arrays = tuple(np.asarray(a) for a in arrays)

    def __len__(self) -> int:
        return len(self.arrays[0])

    def __getitem__(self, idx):
        item = tuple(a[idx] for a in self.arrays)
        return item if len(item) > 1 else item[0]


class DistributedSampler:
    """Deterministic shard of dataset indices for one replica.

    Pads by wrap-around so every replica sees the same number of samples
    (same contract as torch's DistributedSampler, which the reference relies
    on for equal step counts across ranks).
    """

    def __init__(
        self,
        dataset_len: int,
        num_replicas: int = 1,
        rank: int = 0,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ) -> None:
        if rank >= num_replicas:
            raise ValueError(f"rank {rank} >= num_replicas {num_replicas}")
        self.dataset_len = dataset_len
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        if drop_last and dataset_len % num_replicas:
            self.num_samples = dataset_len // num_replicas
        else:
            self.num_samples = math.ceil(dataset_len / num_replicas)
        self.total_size = self.num_samples * num_replicas

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def indices(self) -> np.ndarray:
        if self.shuffle:
            g = np.random.default_rng(self.seed + self.epoch)
            idx = g.permutation(self.dataset_len)
        else:
            idx = np.arange(self.dataset_len)
        if not self.drop_last and len(idx) < self.total_size:
            extra = self.total_size - len(idx)
            idx = np.concatenate([idx, idx[:extra]])
        else:
            idx = idx[: self.total_size]
        return idx[self.rank : self.total_size : self.num_replicas]


class DataLoader:
    """Batching spec over a dataset.

    Constructed by the user with per-chip ``batch_size``; the worker loop
    injects distributed sampling (``use_distributed_sampler`` semantics of
    the reference) and the per-host batch multiplier before iteration.
    """

    def __init__(
        self,
        dataset: Dataset | Sequence,
        batch_size: int = 1,
        shuffle: bool = False,
        drop_last: bool = False,
        seed: int = 0,
        collate_fn: Optional[Any] = None,
    ) -> None:
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self.collate_fn = collate_fn
        # Injected by the worker loop (distributed_sampler_kwargs analog).
        self.sampler: Optional[DistributedSampler] = None

    def with_sampler(self, num_replicas: int, rank: int, seed: int) -> "DataLoader":
        loader = DataLoader(
            self.dataset,
            batch_size=self.batch_size,
            shuffle=self.shuffle,
            drop_last=self.drop_last,
            seed=self.seed,
            collate_fn=self.collate_fn,
        )
        loader.sampler = DistributedSampler(
            len(self.dataset),
            num_replicas=num_replicas,
            rank=rank,
            shuffle=self.shuffle,
            seed=seed,
            drop_last=self.drop_last,
        )
        return loader

    def set_epoch(self, epoch: int) -> None:
        if self.sampler is not None:
            self.sampler.set_epoch(epoch)

    def _collate(self, items: list) -> Any:
        if self.collate_fn is not None:
            return self.collate_fn(items)
        first = items[0]
        if isinstance(first, tuple):
            return tuple(
                np.stack([np.asarray(it[j]) for it in items]) for j in range(len(first))
            )
        return np.stack([np.asarray(it) for it in items])

    def iter_batches(self, batch_multiplier: int = 1) -> Iterator[Any]:
        """Yield host-level batches of ``batch_size * batch_multiplier``.

        ``batch_multiplier`` is the number of local chips this host feeds;
        GSPMD then splits the array across them.
        """
        if self.sampler is not None:
            idx = self.sampler.indices()
        else:
            if self.shuffle:
                g = np.random.default_rng(self.seed)
                idx = g.permutation(len(self.dataset))
            else:
                idx = np.arange(len(self.dataset))
        bs = self.batch_size * batch_multiplier
        n_full = len(idx) // bs
        remainder = len(idx) - n_full * bs
        for b in range(n_full):
            sel = idx[b * bs : (b + 1) * bs]
            yield self._collate([self.dataset[int(i)] for i in sel])
        if remainder and not self.drop_last:
            # Pad the tail batch by wrap-around so its leading dim stays
            # divisible across chips (static shapes for XLA). np.resize
            # cycles the index list, covering shards smaller than one batch.
            sel = idx[n_full * bs :]
            pad = np.resize(idx, bs - len(sel))
            sel = np.concatenate([sel, pad])
            yield self._collate([self.dataset[int(i)] for i in sel])

    def num_batches(self, batch_multiplier: int = 1) -> int:
        n = (
            self.sampler.num_samples
            if self.sampler is not None
            else len(self.dataset)
        )
        bs = self.batch_size * batch_multiplier
        return n // bs if self.drop_last else math.ceil(n / bs)

    def __iter__(self) -> Iterator[Any]:
        return self.iter_batches(1)

    def __len__(self) -> int:
        return self.num_batches(1)
