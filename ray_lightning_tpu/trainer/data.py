"""Data loading with distributed sharding semantics.

The reference injects ``DistributedSampler(num_replicas=world_size,
rank=global_rank)`` kwargs into PTL's dataloaders
(/root/reference/ray_lightning/ray_ddp.py:315-324; behavior pinned by
test_ddp.py:179-211: train shuffled, val/test not, correct replica/rank).

TPU twist: one worker process owns several chips, so sharding happens at two
levels — the sampler shards the *dataset* across host processes, and the
global-batch array is sharded across *chips* by GSPMD when the loop builds a
globally-sharded ``jax.Array`` from each host's local slice
(``jax.make_array_from_process_local_data``). ``DataLoader.batch_size`` is
the per-chip microbatch, matching the reference's per-worker semantics.
"""
from __future__ import annotations

import math
import os
from typing import Any, Dict, Iterator, Optional, Sequence, Tuple

import numpy as np


class Dataset:
    """Minimal map-style dataset protocol: __len__ + __getitem__."""

    def __len__(self) -> int:
        raise NotImplementedError

    def __getitem__(self, idx: int) -> Any:
        raise NotImplementedError


class IterableDataset:
    """Streaming dataset protocol: ``__iter__`` yields items; no length.

    For sources that don't fit the map-style contract — unbounded streams,
    network readers, on-the-fly generators. Distributed contract: the
    DataLoader STRIDES the stream (item ``i`` goes to replica
    ``i % num_replicas``), so every host must construct an identical
    iterator; shuffling belongs at the source (``shuffle=True`` on the
    loader is rejected — there is nothing to index-permute).

    Batch-shape contract (XLA static shapes): in the train path a partial
    tail batch is DROPPED; in the eval path it is padded by repeating the
    last item with the validity mask False, so masked eval metrics stay
    exact on non-divisible streams.
    """

    def __iter__(self) -> Iterator[Any]:
        raise NotImplementedError


def _is_torch_iterable(dataset: Any) -> bool:
    """True for ``torch.utils.data.IterableDataset`` WITHOUT importing
    torch: migration interop only applies when the user already has torch
    loaded (a framework-side import would add seconds of cold start and a
    hard dependency the TPU path doesn't need)."""
    import sys

    torch = sys.modules.get("torch")
    if torch is None:
        return False
    try:
        return isinstance(dataset, torch.utils.data.IterableDataset)
    except AttributeError:  # torch without torch.utils.data loaded
        return False


class ArrayDataset(Dataset):
    """Dataset over parallel numpy arrays (features, labels, ...)."""

    def __init__(self, *arrays: np.ndarray) -> None:
        assert arrays and all(len(a) == len(arrays[0]) for a in arrays)
        self.arrays = tuple(np.asarray(a) for a in arrays)

    def __len__(self) -> int:
        return len(self.arrays[0])

    def __getitem__(self, idx):
        item = tuple(a[idx] for a in self.arrays)
        return item if len(item) > 1 else item[0]


class TokenBinDataset(Dataset):
    """Memory-mapped token corpus: flat binary file(s) of token ids.

    The standard LLM-pretraining on-disk format (nanoGPT/llm.c style):
    fixed-width unsigned ints, no framing. ``path`` may be one file or a
    directory of ``*.bin`` shards (sorted by name, treated as one corpus;
    windows never straddle shard boundaries). Items are overlapping
    ``seq_len + 1``-token windows (``stride`` tokens apart, default
    non-overlapping), returned as int32 — the (input, shifted-target) pair
    GPT-style modules train on. Maps are opened lazily PER PROCESS and
    dropped on pickle, so the dataset ships to worker actors as paths +
    shapes, and each worker pages only the windows it actually touches —
    a 100 GB corpus costs no RAM up front on any host.
    """

    def __init__(
        self,
        path: str,
        seq_len: int,
        dtype: str = "uint16",
        stride: int = 0,
    ) -> None:
        self.path = path
        self.seq_len = int(seq_len)
        self.dtype = np.dtype(dtype)
        self.stride = int(stride) or self.seq_len
        if os.path.isdir(path):
            self.files = sorted(
                os.path.join(path, n)
                for n in os.listdir(path)
                if n.endswith(".bin")
            )
            if not self.files:
                raise ValueError(f"{path}: no *.bin shards found")
        else:
            self.files = [path]

        def windows(f: str) -> int:
            n_tokens = os.path.getsize(f) // self.dtype.itemsize
            return max(0, (n_tokens - self.seq_len - 1) // self.stride + 1)

        self._file_windows = [windows(f) for f in self.files]
        # Cumulative offsets for global-index -> (shard, local) mapping.
        self._cum = np.cumsum([0] + self._file_windows)
        self._len = int(self._cum[-1])
        if self._len == 0:
            raise ValueError(
                f"{path}: no shard holds one {self.seq_len + 1}-token window"
            )
        self._mms: Dict[int, np.memmap] = {}

    def _map(self, fi: int) -> np.memmap:
        if fi not in self._mms:
            self._mms[fi] = np.memmap(
                self.files[fi], dtype=self.dtype, mode="r"
            )
        return self._mms[fi]

    def __len__(self) -> int:
        return self._len

    def __getitem__(self, idx: int) -> np.ndarray:
        if not 0 <= idx < self._len:
            raise IndexError(idx)
        fi = int(np.searchsorted(self._cum, idx, side="right")) - 1
        start = (idx - int(self._cum[fi])) * self.stride
        return np.asarray(
            self._map(fi)[start : start + self.seq_len + 1], dtype=np.int32
        )

    def gather_batch(self, sel: Any) -> np.ndarray:
        """Assemble ``[len(sel), seq_len + 1]`` int32 windows in one pass.

        The DataLoader's whole-batch fast path: indices are grouped by
        shard and each group runs through the native window gather
        (utils/native.py) — the memmap page faults and the uint16->int32
        widen happen off the GIL, so corpus IO overlaps device compute
        instead of serializing behind the per-item ``__getitem__`` loop.
        """
        from ray_lightning_tpu.utils.native import gather_windows

        sel = np.ascontiguousarray(sel, dtype=np.int64)
        out = np.empty((len(sel), self.seq_len + 1), dtype=np.int32)
        if not len(sel):
            return out
        if sel.min() < 0 or sel.max() >= self._len:
            bad = sel[(sel < 0) | (sel >= self._len)][0]
            raise IndexError(bad)
        fis = np.searchsorted(self._cum, sel, side="right") - 1
        for fi in np.unique(fis):
            mask = fis == fi
            starts = (sel[mask] - int(self._cum[fi])) * self.stride
            out[mask] = gather_windows(
                self._map(int(fi)), starts, self.seq_len + 1, np.int32
            )
        return out

    def __getstate__(self):
        # mmap handles are process-local; re-open lazily on the worker.
        state = dict(self.__dict__)
        state["_mms"] = {}
        return state


def write_token_bin(path: str, tokens: Any, dtype: str = "uint16") -> str:
    """Write a token id sequence as a TokenBinDataset-compatible flat file."""
    arr = np.asarray(tokens)
    dt = np.dtype(dtype)
    info = np.iinfo(dt)
    if arr.min() < info.min or arr.max() > info.max:
        raise ValueError(
            f"token ids [{arr.min()}, {arr.max()}] don't fit dtype {dtype}"
        )
    arr.astype(dt).ravel().tofile(path)
    return path


class DistributedSampler:
    """Deterministic shard of dataset indices for one replica.

    Pads by wrap-around so every replica sees the same number of samples
    (same contract as torch's DistributedSampler, which the reference relies
    on for equal step counts across ranks).
    """

    def __init__(
        self,
        dataset_len: int,
        num_replicas: int = 1,
        rank: int = 0,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ) -> None:
        if rank >= num_replicas:
            raise ValueError(f"rank {rank} >= num_replicas {num_replicas}")
        self.dataset_len = dataset_len
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        if drop_last and dataset_len % num_replicas:
            self.num_samples = dataset_len // num_replicas
        else:
            self.num_samples = math.ceil(dataset_len / num_replicas)
        self.total_size = self.num_samples * num_replicas

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def indices(self) -> np.ndarray:
        return self.indices_and_mask()[0]

    def indices_and_mask(self) -> Tuple[np.ndarray, np.ndarray]:
        """This replica's indices plus a validity mask.

        ``mask[i]`` is False for wrap-around padding entries — each real
        sample is True on exactly one replica, so masked reductions over all
        replicas count every dataset element exactly once (what makes eval
        metrics exact on non-divisible datasets; the reference gets this from
        torch's real tail batches, test_ddp.py:326-352).
        """
        if self.shuffle:
            g = np.random.default_rng(self.seed + self.epoch)
            idx = g.permutation(self.dataset_len)
        else:
            idx = np.arange(self.dataset_len)
        mask = np.ones(len(idx), dtype=bool)
        if not self.drop_last and len(idx) < self.total_size:
            extra = self.total_size - len(idx)
            idx = np.concatenate([idx, np.resize(idx, extra)])
            mask = np.concatenate([mask, np.zeros(extra, dtype=bool)])
        else:
            idx = idx[: self.total_size]
            mask = mask[: self.total_size]
        sl = slice(self.rank, self.total_size, self.num_replicas)
        return idx[sl], mask[sl]


class DataLoader:
    """Batching spec over a dataset.

    Constructed by the user with per-chip ``batch_size``; the worker loop
    injects distributed sampling (``use_distributed_sampler`` semantics of
    the reference) and the per-host batch multiplier before iteration.
    """

    def __init__(
        self,
        dataset: Dataset | IterableDataset | Sequence,
        batch_size: int = 1,
        shuffle: bool = False,
        drop_last: bool = False,
        seed: int = 0,
        collate_fn: Optional[Any] = None,
    ) -> None:
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self.collate_fn = collate_fn
        # torch interop (docs/migration.md): a torch map-style Dataset
        # already satisfies the __len__/__getitem__ protocol (CPU tensors
        # collate via np.asarray); torch IterableDatasets must be routed
        # onto the streaming path or len() below would raise.
        self._iterable = isinstance(dataset, IterableDataset) or _is_torch_iterable(
            dataset
        )
        if self._iterable and shuffle:
            raise ValueError(
                "shuffle=True is undefined for IterableDataset: there are "
                "no indices to permute — shuffle at the stream source"
            )
        # Injected by the worker loop (distributed_sampler_kwargs analog).
        self.sampler: Optional[DistributedSampler] = None
        # Stream sharding (IterableDataset): (num_replicas, rank) stride.
        self._stride: Optional[Tuple[int, int]] = None

    def with_sampler(self, num_replicas: int, rank: int, seed: int) -> "DataLoader":
        loader = DataLoader(
            self.dataset,
            batch_size=self.batch_size,
            shuffle=self.shuffle,
            drop_last=self.drop_last,
            seed=self.seed,
            collate_fn=self.collate_fn,
        )
        if self._iterable:
            # Streams shard by striding: item i -> replica i % num_replicas
            # (every host runs the same iterator, keeps its residue class).
            loader._stride = (num_replicas, rank)
            return loader
        loader.sampler = DistributedSampler(
            len(self.dataset),
            num_replicas=num_replicas,
            rank=rank,
            shuffle=self.shuffle,
            seed=seed,
            drop_last=self.drop_last,
        )
        return loader

    def set_epoch(self, epoch: int) -> None:
        if self.sampler is not None:
            self.sampler.set_epoch(epoch)

    def _collate(self, items: list) -> Any:
        if self.collate_fn is not None:
            return self.collate_fn(items)
        first = items[0]
        if isinstance(first, tuple):
            return tuple(
                np.stack([np.asarray(it[j]) for it in items]) for j in range(len(first))
            )
        return np.stack([np.asarray(it) for it in items])

    def _gather(self, sel: np.ndarray) -> Any:
        """Assemble one batch for the row indices ``sel``.

        ArrayDataset fast path: whole-batch native row gather (GIL released,
        csrc/rltnative.cpp) instead of a per-item Python loop — this is what
        makes the prefetch thread actually overlap with device compute.
        """
        # Exact-type gate: a subclass may override __getitem__, which the
        # whole-batch native gather would silently bypass.
        if self.collate_fn is None and type(self.dataset) is ArrayDataset:
            from ray_lightning_tpu.utils.native import gather_rows

            outs = tuple(gather_rows(a, sel) for a in self.dataset.arrays)
            return outs if len(outs) > 1 else outs[0]
        if self.collate_fn is None and type(self.dataset) is TokenBinDataset:
            # Same exact-type gate: whole-batch shard-grouped window
            # gather with the GIL released (memmap IO + dtype widen).
            return self.dataset.gather_batch(sel)
        return self._collate([self.dataset[int(i)] for i in sel])

    def _iter_selections(
        self, batch_multiplier: int
    ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """Yield (row indices, validity mask) per batch. Mask is False for
        padding rows (sampler wrap-around + tail-batch wrap-around)."""
        if self.sampler is not None:
            idx, valid = self.sampler.indices_and_mask()
        else:
            if self.shuffle:
                g = np.random.default_rng(self.seed)
                idx = g.permutation(len(self.dataset))
            else:
                idx = np.arange(len(self.dataset))
            valid = np.ones(len(idx), dtype=bool)
        bs = self.batch_size * batch_multiplier
        n_full = len(idx) // bs
        remainder = len(idx) - n_full * bs
        for b in range(n_full):
            yield idx[b * bs : (b + 1) * bs], valid[b * bs : (b + 1) * bs]
        if remainder and not self.drop_last:
            # Pad the tail batch by wrap-around so its leading dim stays
            # divisible across chips (static shapes for XLA). np.resize
            # cycles the index list, covering shards smaller than one batch.
            sel = idx[n_full * bs :]
            pad = np.resize(idx, bs - len(sel))
            yield (
                np.concatenate([sel, pad]),
                np.concatenate(
                    [valid[n_full * bs :], np.zeros(len(pad), dtype=bool)]
                ),
            )

    def _iter_stream_batches(
        self, batch_multiplier: int, with_mask: bool
    ) -> Iterator[Any]:
        """Batch a (possibly strided) IterableDataset stream.

        SPMD invariant: every replica MUST emit the same number of
        batches (each batch is assembled collectively by
        ``make_array_from_process_local_data``; a rank with one extra
        batch deadlocks the others). Batches are therefore aligned to
        stride GROUPS of ``batch_size * num_replicas`` global items —
        replica r yields its k-th batch only once the whole group is
        known complete, and the tail handling is count-identical on
        every rank: dropped for training (no mask to hide padding rows
        from gradients), one padded+masked batch for eval (exact masked
        reductions).
        """
        bs = self.batch_size * batch_multiplier
        num_replicas, rank = self._stride if self._stride else (1, 0)
        group = bs * num_replicas
        buffer: list = []
        last_item: Any = None
        n_total = 0
        yielded = 0
        for i, item in enumerate(iter(self.dataset)):
            n_total = i + 1
            if i % num_replicas == rank:
                buffer.append(item)
                last_item = item
            if n_total % group == 0:
                batch = self._collate(buffer[:bs])
                buffer = buffer[bs:]
                yielded += 1
                if with_mask:
                    yield batch, np.ones(bs, dtype=bool)
                else:
                    yield batch
        leftover = n_total % group
        if leftover and not self.drop_last and with_mask:
            # Every rank emits exactly one padded tail batch (leftover > 0
            # is a GLOBAL fact, so the count stays equal) with its real
            # rows — possibly zero of them — marked in the mask.
            if last_item is None:
                raise ValueError(
                    f"stream yielded {n_total} items for {num_replicas} "
                    "replicas: at least one replica saw nothing, so it "
                    "cannot shape a padded eval batch — provide at least "
                    "num_replicas items"
                )
            mask = np.zeros(bs, dtype=bool)
            mask[: len(buffer)] = True
            buffer = buffer + [last_item] * (bs - len(buffer))
            yielded += 1
            yield self._collate(buffer), mask
        if yielded == 0:
            if getattr(self, "_stream_saw_items", False):
                raise RuntimeError(
                    "IterableDataset produced no items on re-iteration: "
                    "__iter__ must return a FRESH iterator per epoch (a "
                    "one-shot generator was exhausted by a previous epoch "
                    "or the init-shape probe)"
                )
            raise ValueError(
                f"stream produced {n_total} items — fewer than one "
                f"global batch (batch_size*batch_multiplier*replicas = "
                f"{group}); shrink batch_size or provide more items"
            )
        self._stream_saw_items = True

    def iter_batches(
        self,
        batch_multiplier: int = 1,
        prefetch: Optional[int] = None,
        with_mask: bool = False,
    ) -> Iterator[Any]:
        """Yield host-level batches of ``batch_size * batch_multiplier``.

        ``batch_multiplier`` is the number of local chips this host feeds;
        GSPMD then splits the array across them. ``prefetch`` > 0 assembles
        up to that many batches ahead in a background thread (default: 2
        when the native gather is available, else synchronous).
        ``with_mask=True`` yields ``(batch, validity_mask)`` pairs, where the
        bool mask marks real (non-padding) rows — the eval path uses it for
        exact masked metric reductions.
        """
        if prefetch is None:
            from ray_lightning_tpu.utils.native import native_available

            prefetch = 2 if native_available() else 0

        def batches() -> Iterator[Any]:
            if self._iterable:
                yield from self._iter_stream_batches(batch_multiplier, with_mask)
                return
            for sel, mask in self._iter_selections(batch_multiplier):
                batch = self._gather(sel)
                yield (batch, mask) if with_mask else batch

        if prefetch <= 0:
            yield from batches()
            return

        import queue as queue_mod
        import threading

        q: "queue_mod.Queue" = queue_mod.Queue(maxsize=prefetch)
        stop = threading.Event()
        SENTINEL = object()

        def producer() -> None:
            try:
                for batch in batches():
                    while not stop.is_set():
                        try:
                            q.put(batch, timeout=0.1)
                            break
                        except queue_mod.Full:
                            continue
                    if stop.is_set():
                        return
                payload: Any = SENTINEL
            except BaseException as exc:  # noqa: BLE001 - reraise in consumer
                payload = exc
            while not stop.is_set():
                try:
                    q.put(payload, timeout=0.1)
                    return
                except queue_mod.Full:
                    continue

        t = threading.Thread(target=producer, name="rlt-prefetch", daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is SENTINEL:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()

    def num_batches(self, batch_multiplier: int = 1) -> Optional[int]:
        """Batches per epoch — None for streaming (IterableDataset)
        loaders, whose length is unknown until exhaustion."""
        if self._iterable:
            return None
        n = (
            self.sampler.num_samples
            if self.sampler is not None
            else len(self.dataset)
        )
        bs = self.batch_size * batch_multiplier
        return n // bs if self.drop_last else math.ceil(n / bs)

    def __iter__(self) -> Iterator[Any]:
        return self.iter_batches(1)

    def __len__(self) -> int:
        n = self.num_batches(1)
        if n is None:
            raise TypeError("streaming DataLoader has no length")
        return n
