"""TPUModule: the user-facing model abstraction (Lightning-module analog).

The reference delegates the module contract to PyTorch Lightning's
``LightningModule``; this framework is standalone, so it defines its own —
designed functionally for XLA: the hot-path methods (``training_step`` etc.)
are *pure functions of (params, batch, rng)* that get traced once under jit
and compiled for the device mesh. Host-side hooks run only at step/epoch
boundaries, never inside the compiled step (SURVEY.md §7 "No mid-step
Python").

Test-model equivalents of the reference's fixtures (BoringModel,
LightningMNISTClassifier, XORModel — /root/reference/ray_lightning/tests/
utils.py:28-210) live in ``ray_lightning_tpu.models``.
"""
from __future__ import annotations

from typing import Any, Dict, Iterable, Optional, Tuple

import jax


def unpack_optimizers(opt: Any) -> Tuple[Any, Optional[Any]]:
    """Normalize ``configure_optimizers()`` return forms.

    Returns ``(transform, lr_schedule_or_None)``. Accepted forms: a bare
    ``optax.GradientTransformation``, ``{"optimizer": tx, "lr_schedule":
    fn}``, or ``(tx, fn)``. The schedule entry is monitoring-only (optax
    embeds schedules inside the transform); a bare GradientTransformation —
    itself a NamedTuple of two callables — is NOT treated as the tuple form.
    """
    if isinstance(opt, dict):
        # Accept PTL's actual key name too — a ported module returning
        # "lr_scheduler" should get monitoring, not silent None. A PTL
        # scheduler OBJECT (not a step->lr callable) can't be evaluated;
        # treat it as undeclared rather than crashing current_lr.
        sched = opt.get("lr_schedule", opt.get("lr_scheduler"))
        return opt["optimizer"], sched if callable(sched) else None
    if type(opt) is tuple and len(opt) == 2:
        if callable(opt[1]):
            return opt
        # e.g. PTL's `return [optimizer], [scheduler]` — fail here with the
        # accepted shapes rather than deep in tx.init.
        raise TypeError(
            "configure_optimizers returned a 2-tuple whose second element "
            "is not a step->lr callable. Accepted forms: an optax "
            "GradientTransformation, {'optimizer': tx, 'lr_schedule': fn}, "
            "or (tx, fn)."
        )
    return opt, None


def schedule_lr(
    sched: Any,
    *,
    global_step: int,
    update_count: Optional[int] = None,
    accumulate_grad_batches: int = 1,
) -> Optional[float]:
    """Evaluate a declared lr schedule at the next-update index.

    Single source of truth for ``TrainingLoop.current_lr`` and the driver
    ``Trainer.current_lr`` mirror: prefer the exact inner-update count
    (windows + epoch-end flushes) when known; otherwise approximate with
    ``global_step // accumulate_grad_batches``.
    """
    if sched is None:
        return None
    if update_count is not None:
        return float(sched(update_count))
    k = max(1, int(accumulate_grad_batches))
    return float(sched(global_step // k))


class TPUModule:
    """Base class for user models.

    Required overrides:
      - ``init_params(rng, batch) -> params``: build the initial parameter
        pytree (e.g. ``self.model.init(rng, batch[0])`` for a flax module).
      - ``training_step(params, batch, rng) -> (loss, logs)``: pure, traced
        under jit. ``logs`` is a flat dict of scalar jnp arrays. The loss must
        be the mean over the *local* batch shard; global averaging across the
        data axis is inserted by the strategy/XLA.
      - ``configure_optimizers() -> optax.GradientTransformation``. May
        also return ``{"optimizer": tx, "lr_schedule": step -> lr}`` (or
        ``(tx, lr_schedule)``): optax schedules live inside the transform,
        so the extra entry just declares the schedule for monitoring
        (``LearningRateMonitor``, ``trainer.current_lr``).
      - ``train_dataloader() -> DataLoader``

    Optional: ``validation_step``, ``test_step``, ``predict_step``
    (pure), ``val_dataloader``, ``test_dataloader``, ``predict_dataloader``,
    and host-side hooks ``on_fit_start/on_train_epoch_start/
    on_train_epoch_end/on_validation_epoch_end/on_fit_end``.

    Instances must be cloudpickle-able: they are shipped driver -> worker
    through the fabric object store, like the reference ships the
    LightningModule via ``ray.put`` (ray_launcher.py:232-237).
    """

    def __init__(self) -> None:
        self.params: Any = None  # populated after fit()/restore
        self.ema_params: Any = None  # populated when Trainer(ema_decay=...)
        self.opt_state: Any = None  # gathered optimizer state after fit()
        self.trainer: Any = None  # back-reference set by Trainer

    # ------------------------------------------------------------------
    # Required
    # ------------------------------------------------------------------
    def init_params(self, rng: jax.Array, batch: Any) -> Any:
        raise NotImplementedError

    def training_step(
        self, params: Any, batch: Any, rng: jax.Array
    ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        raise NotImplementedError

    def configure_optimizers(self) -> Any:
        raise NotImplementedError

    def train_dataloader(self) -> Any:
        raise NotImplementedError

    # ------------------------------------------------------------------
    # Optional steps (pure, jit-traced)
    # ------------------------------------------------------------------
    def validation_step(self, params: Any, batch: Any) -> Dict[str, jax.Array]:
        raise NotImplementedError

    def test_step(self, params: Any, batch: Any) -> Dict[str, jax.Array]:
        # Default: reuse the validation logic under test/ keys.
        return self.validation_step(params, batch)

    def predict_step(self, params: Any, batch: Any) -> Any:
        raise NotImplementedError

    def val_dataloader(self) -> Optional[Any]:
        return None

    def test_dataloader(self) -> Optional[Any]:
        return None

    def predict_dataloader(self) -> Optional[Any]:
        return None

    # ------------------------------------------------------------------
    # Host-side hooks (step/epoch boundaries only)
    # ------------------------------------------------------------------
    def on_fit_start(self) -> None: ...

    def on_fit_end(self) -> None: ...

    def on_train_epoch_start(self, epoch: int) -> None: ...

    def on_train_epoch_end(self, epoch: int, metrics: Dict[str, float]) -> None: ...

    def on_validation_epoch_end(self, metrics: Dict[str, float]) -> None: ...

    # ------------------------------------------------------------------
    # State (mirrors state_dict/load_state_dict usage in the reference's
    # result recovery, ray_launcher.py:362-370)
    # ------------------------------------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        return {"params": self.params}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.params = state["params"]
        # Unconditional: a state without an average CLEARS any stale one
        # from a previous fit (eval-only round-trips re-ship the average
        # through the worker output, so it survives those).
        self.ema_params = state.get("ema_params")
        # Fit outputs carry gathered optimizer state so the driver's
        # save_checkpoint() writes files that resume with momentum intact.
        self.opt_state = state.get("opt_state")


class DataModule:
    """Optional container bundling dataloaders (LightningDataModule analog)."""

    def prepare_data(self) -> None:
        """Called once per node before dataloaders (download datasets here).

        Equivalent of the hook the reference invokes via
        ``trainer._data_connector.prepare_data()`` in each worker
        (ray_launcher.py:290).
        """

    def setup(self, stage: Optional[str] = None) -> None: ...

    def train_dataloader(self) -> Any:
        raise NotImplementedError

    def val_dataloader(self) -> Optional[Any]:
        return None

    def test_dataloader(self) -> Optional[Any]:
        return None

    def predict_dataloader(self) -> Optional[Any]:
        return None
