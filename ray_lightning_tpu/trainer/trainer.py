"""Driver-side Trainer: the user entrypoint.

``Trainer(strategy=RayTPUStrategy(num_workers=N)).fit(module)`` reproduces
the reference's user surface (README.md:57-62) with a standalone trainer:
with a distributed strategy, work is launched onto fabric actors and rank-0
results are recovered into this process (ray_launcher.py:351-379 analog);
with no strategy, the same TrainingLoop runs in-process on the local
devices — the baseline path.
"""
from __future__ import annotations

import os
import tempfile
import time
import warnings
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_lightning_tpu.parallel.env import DistEnv
from ray_lightning_tpu.strategies.base import SingleDeviceStrategy, Strategy
from ray_lightning_tpu.trainer.loop import TrainerSpec, TrainingLoop
from ray_lightning_tpu.utils.seed import seed_everything


def _parse_max_time(value: Any) -> Optional[float]:
    """Normalize a max_time spec to seconds (None passes through)."""
    import datetime

    if value is None:
        return None
    if isinstance(value, datetime.timedelta):
        seconds = value.total_seconds()
    elif isinstance(value, dict):
        seconds = datetime.timedelta(**value).total_seconds()
    elif isinstance(value, str):
        parts = value.split(":")
        if len(parts) not in (3, 4) or not all(
            p.strip().isdigit() for p in parts
        ):
            raise ValueError(
                "max_time string must be 'DD:HH:MM:SS' or 'HH:MM:SS', "
                f"got {value!r}"
            )
        nums = [int(p) for p in parts]
        if len(nums) == 3:
            nums = [0] + nums
        d, h, m, s = nums
        seconds = float(((d * 24 + h) * 60 + m) * 60 + s)
    elif isinstance(value, (int, float)) and not isinstance(value, bool):
        seconds = float(value)
    else:
        raise ValueError(
            "max_time must be seconds, a timedelta, a timedelta kwargs "
            f"dict, or a 'DD:HH:MM:SS' string, got {type(value).__name__}"
        )
    if seconds <= 0:
        raise ValueError(f"max_time must be positive, got {seconds}s")
    return seconds


class Trainer:
    def __init__(
        self,
        max_epochs: int = 1,
        max_steps: Optional[int] = None,
        max_time: Optional[Any] = None,
        fast_dev_run: Any = False,
        strategy: Optional[Strategy] = None,
        callbacks: Optional[List[Any]] = None,
        limit_train_batches: Optional[Any] = None,
        limit_val_batches: Optional[Any] = None,
        limit_test_batches: Optional[Any] = None,
        limit_predict_batches: Optional[Any] = None,
        num_sanity_val_steps: int = 2,
        check_val_every_n_epoch: int = 1,
        overfit_batches: Optional[Any] = None,
        detect_anomaly: bool = False,
        val_check_interval: Optional[Any] = None,
        accumulate_grad_batches: int = 1,
        gradient_clip_val: Optional[float] = None,
        steps_per_execution: int = 1,
        log_every_n_steps: int = 50,
        enable_checkpointing: bool = True,
        enable_model_summary: bool = True,
        default_root_dir: Optional[str] = None,
        seed: Optional[int] = None,
        precision: str = "fp32",
        max_restarts: int = 0,
        ema_decay: Optional[float] = None,
        eval_ema: bool = False,
        async_checkpointing: bool = False,
        log_grad_norm: bool = False,
        ship_optimizer_state: bool = True,
    ) -> None:
        self.max_epochs = max_epochs
        self.max_steps = max_steps
        # Wall-clock fit budget (PTL's Trainer(max_time=...)): seconds,
        # datetime.timedelta, a {"days"/"hours"/...} dict, or a
        # "DD:HH:MM:SS" / "HH:MM:SS" string. With max_restarts > 0 the
        # budget applies per attempt (each restart re-enters the loop).
        self.max_time = _parse_max_time(max_time)
        self.strategy = strategy
        self.callbacks = list(callbacks or [])
        # PTL's fast_dev_run: touch every code path in one tiny run —
        # N batches (True = 1) of train/val/test/predict, a single
        # epoch, no sanity val, no checkpointing. The wiring smoke test
        # the reference leans on (fast_dev_run=True throughout its
        # sharded suite, /root/reference/ray_lightning/tests/
        # test_ddp_sharded.py:37-71).
        self.fast_dev_run = fast_dev_run
        if fast_dev_run:
            if not isinstance(fast_dev_run, (bool, int)):
                raise ValueError(
                    f"fast_dev_run must be True or a positive int, got "
                    f"{fast_dev_run!r}"
                )
            n = 1 if fast_dev_run is True else int(fast_dev_run)
            if n < 1:
                raise ValueError(
                    f"fast_dev_run must be True or a positive int, got "
                    f"{fast_dev_run!r}"
                )
            if overfit_batches is not None:
                raise ValueError(
                    "fast_dev_run and overfit_batches are mutually "
                    "exclusive debug modes; pass one or the other"
                )
            # PTL semantics: every other budget/cadence is silently
            # overridden by the smoke run (max_steps, limit_*, val
            # cadences, max_time) — the flag's promise is 'run N batches
            # of everything right now', not config arbitration.
            # self.max_epochs/max_steps were assigned above; override
            # both the attributes and the locals consumed below.
            self.max_epochs = max_epochs = 1
            self.max_steps = max_steps = n
            limit_train_batches = n
            limit_val_batches = n
            limit_test_batches = n
            limit_predict_batches = n
            num_sanity_val_steps = 0
            enable_checkpointing = False
            # The one-epoch run must still touch the val path (the whole
            # point), whatever cadence the config carried (PTL resets
            # both under fast_dev_run).
            check_val_every_n_epoch = 1
            val_check_interval = None
            self.max_time = None
            # PTL disables checkpointing, early stopping, and loggers
            # outright under fast_dev_run — including user-supplied ones
            # (a 1-batch run must not early-stop on a missing monitor or
            # leave logger artifacts on disk).
            from ray_lightning_tpu.trainer.callbacks import (
                CSVLogger,
                EarlyStopping,
                ModelCheckpoint,
                TensorBoardLogger,
            )

            drop = (ModelCheckpoint, EarlyStopping, CSVLogger,
                    TensorBoardLogger)
            self.callbacks = [
                cb for cb in self.callbacks if not isinstance(cb, drop)
            ]
        self.limit_train_batches = limit_train_batches
        self.limit_val_batches = limit_val_batches
        self.limit_test_batches = limit_test_batches
        self.limit_predict_batches = limit_predict_batches
        self.num_sanity_val_steps = num_sanity_val_steps
        self.check_val_every_n_epoch = check_val_every_n_epoch
        # PTL's overfit_batches: train AND validate on the same fixed
        # unshuffled slice (int batches / float fraction). It subsumes the
        # train/val batch limits, so mixing them is a config error.
        if overfit_batches is not None:
            v = float(overfit_batches)
            if v <= 0 or (isinstance(overfit_batches, float) and v > 1):
                raise ValueError(
                    "overfit_batches must be a positive int (batches) or a "
                    f"float in (0, 1] (fraction), got {overfit_batches!r}"
                )
            if limit_train_batches is not None or limit_val_batches is not None:
                raise ValueError(
                    "overfit_batches replaces limit_train_batches/"
                    "limit_val_batches; pass one or the other"
                )
            self.limit_train_batches = overfit_batches
            self.limit_val_batches = overfit_batches
        self.overfit_batches = overfit_batches
        self.detect_anomaly = bool(detect_anomaly)
        if val_check_interval is not None:
            import math

            v = float(val_check_interval)
            is_float = isinstance(val_check_interval, float)
            if (
                not math.isfinite(v)
                or v <= 0
                or (is_float and v > 1)
                or (not is_float and v != int(v))
            ):
                raise ValueError(
                    "val_check_interval must be a positive int (batches) or "
                    "a float in (0, 1] (epoch fraction; 1.0 = epoch end), "
                    f"got {val_check_interval!r}"
                )
        self.val_check_interval = val_check_interval
        self.accumulate_grad_batches = accumulate_grad_batches
        self.gradient_clip_val = gradient_clip_val
        if int(steps_per_execution) < 1:
            raise ValueError(
                f"steps_per_execution must be >= 1, got {steps_per_execution}"
            )
        self.steps_per_execution = int(steps_per_execution)
        self.log_every_n_steps = log_every_n_steps
        self.enable_checkpointing = enable_checkpointing
        self.enable_model_summary = bool(enable_model_summary)
        self.default_root_dir = default_root_dir or os.path.join(
            tempfile.gettempdir(), "rlt_runs"
        )
        # Lightning semantics: enable_checkpointing adds a default
        # ModelCheckpoint when the user supplied none; False means no
        # implicit checkpointing (explicit callbacks still run).
        self.max_restarts = int(max_restarts)
        if ema_decay is not None and not 0.0 < float(ema_decay) < 1.0:
            raise ValueError(f"ema_decay must be in (0, 1), got {ema_decay}")
        # eval_ema without ema_decay stays legal: standalone validate/test
        # can source the average from a checkpoint that carries one; the
        # loop raises if no EMA exists anywhere (never a silent live-weight
        # eval).
        self.ema_decay = ema_decay
        self.eval_ema = bool(eval_ema)
        self.async_checkpointing = bool(async_checkpointing)
        self.log_grad_norm = bool(log_grad_norm)
        # Ship gathered opt_state in fit outputs (driver save_checkpoint
        # resumability); turn off to skip the ~2x-params transfer when only
        # worker-side ModelCheckpoint files are used.
        self.ship_optimizer_state = bool(ship_optimizer_state)
        if enable_checkpointing and not any(
            hasattr(cb, "best_model_path") for cb in self.callbacks
        ):
            from ray_lightning_tpu.trainer.callbacks import ModelCheckpoint

            # Fault-tolerant fits resume from the newest checkpoint, so the
            # implicit callback keeps a rolling "last.ckpt" when restarts
            # are enabled (a user-supplied callback's config is respected).
            self.callbacks.append(
                ModelCheckpoint(save_last=self.max_restarts > 0)
            )
        self.seed = seed_everything(seed)
        self.precision = precision
        # Post-run state (restored from rank-0 worker output)
        self.callback_metrics: Dict[str, Any] = {}
        self.logged_metrics: Dict[str, Any] = {}
        self.state: Dict[str, Any] = {"status": "initialized", "stage": None}
        self.current_epoch = 0
        self.global_step = 0
        self._mid_epoch = False  # did the last fit stop mid-epoch?
        self._update_count: Optional[int] = None
        self._recovered_lr: Optional[float] = None
        self._module: Any = None

    # ------------------------------------------------------------------
    def _make_spec(self) -> TrainerSpec:
        return TrainerSpec(
            max_epochs=self.max_epochs,
            max_steps=self.max_steps,
            max_time=self.max_time,
            limit_train_batches=self.limit_train_batches,
            limit_val_batches=self.limit_val_batches,
            limit_test_batches=self.limit_test_batches,
            limit_predict_batches=self.limit_predict_batches,
            num_sanity_val_steps=self.num_sanity_val_steps,
            check_val_every_n_epoch=self.check_val_every_n_epoch,
            overfit_batches=self.overfit_batches,
            detect_anomaly=self.detect_anomaly,
            val_check_interval=self.val_check_interval,
            accumulate_grad_batches=self.accumulate_grad_batches,
            gradient_clip_val=self.gradient_clip_val,
            steps_per_execution=self.steps_per_execution,
            log_every_n_steps=self.log_every_n_steps,
            enable_checkpointing=self.enable_checkpointing,
            enable_model_summary=self.enable_model_summary,
            default_root_dir=self.default_root_dir,
            seed=self.seed,
            precision=self.precision,
            ema_decay=self.ema_decay,
            eval_ema=self.eval_ema,
            async_checkpointing=self.async_checkpointing,
            log_grad_norm=self.log_grad_norm,
            ship_optimizer_state=self.ship_optimizer_state,
            return_predictions=getattr(self, "_return_predictions", True),
            callbacks=self.callbacks,
        )

    @property
    def lightning_module(self) -> Any:
        return self._module

    @property
    def current_lr(self) -> Optional[float]:
        """Learning rate the next optimizer update would use, from the
        module's declared ``lr_schedule`` (None when not declared).

        After a run this returns the value the rank-0 WORKER evaluated
        (shipped in the fit output; eval-only runs report None), so reading
        it never initializes a jax backend in the driver — on TPU hosts
        the chips belong to worker processes and a driver backend init
        would try to bind them. Before ANY run, the property evaluates the
        schedule locally (pre-run introspection on a dev box) — that path
        does touch the default backend.
        """
        recovered = getattr(self, "_recovered_lr", None)
        if recovered is not None:
            return recovered
        if self.state.get("stage") is not None:
            # A run happened and shipped no lr (no declared schedule, or an
            # eval-only stage): answer without touching a backend.
            return None
        if self._module is None:
            return None
        sched = getattr(self, "_lr_sched_cache", False)
        if sched is False:  # unpack once; configure_optimizers is user code
            from ray_lightning_tpu.trainer.module import unpack_optimizers

            _, sched = unpack_optimizers(self._module.configure_optimizers())
            self._lr_sched_cache = sched
        from ray_lightning_tpu.trainer.module import schedule_lr

        return schedule_lr(
            sched,
            global_step=self.global_step,
            update_count=getattr(self, "_update_count", None),
            accumulate_grad_batches=self.accumulate_grad_batches,
        )

    @property
    def ema_params(self) -> Optional[Any]:
        """EMA weights recovered from the fit (None when ema_decay unset)."""
        return getattr(self._module, "ema_params", None)

    @property
    def checkpoint_callback(self) -> Optional[Any]:
        for cb in self.callbacks:
            if hasattr(cb, "best_model_path"):
                return cb
        return None

    # ------------------------------------------------------------------
    def _run(
        self,
        stage: str,
        module: Any,
        datamodule: Any = None,
        ckpt_path: Optional[str] = None,
        ckpt_stream: Optional[Any] = None,
    ) -> Any:
        self._module = module
        self._lr_sched_cache: Any = False  # re-unpack for the new module
        if stage == "fit":
            # A failed fit must not leave the PREVIOUS module's lr behind.
            self._recovered_lr = None
        module.trainer = self
        if ckpt_path == "last":
            ckpt_path = self._resolve_last_ckpt()
        elif ckpt_path == "best":
            ckpt_path = self._resolve_best_ckpt()
        if ckpt_stream is None:
            ckpt_stream = self._read_ckpt(ckpt_path)
        prev_opt_state = getattr(module, "opt_state", None)
        if self.strategy is None or isinstance(self.strategy, SingleDeviceStrategy):
            output = self._run_in_process(stage, module, datamodule, ckpt_stream)
        else:
            launcher = self.strategy._configure_launcher(self)
            output = launcher.launch(
                stage, module, datamodule=datamodule, ckpt_stream=ckpt_stream
            )
        result = self._recover_results_in_main_process(output, module)
        if (
            stage != "fit"
            and ckpt_stream is None
            and getattr(module, "opt_state", None) is None
        ):
            # Eval outputs never carry opt_state and load_state_dict clears
            # it; an eval WITHOUT a checkpoint leaves params untouched, so
            # the fit's gathered optimizer state is still consistent — keep
            # it resumable via save_checkpoint(). (An eval that DID load a
            # checkpoint replaced params; the stale opt_state stays
            # cleared.)
            module.opt_state = prev_opt_state
        return result

    def _run_in_process(
        self, stage: str, module: Any, datamodule: Any, ckpt_stream: Optional[bytes]
    ) -> Any:
        strategy = SingleDeviceStrategy()
        dist_env = DistEnv()
        strategy.setup_worker(dist_env)
        loop = TrainingLoop(
            self._make_spec(), module, strategy, dist_env, datamodule=datamodule
        )
        if stage == "fit":
            return loop.run_fit(ckpt_stream)
        if stage in ("validate", "test"):
            return loop.run_evaluate(stage, ckpt_stream)
        return loop.run_predict(ckpt_stream)

    @staticmethod
    def _read_ckpt(ckpt_path: Optional[str]) -> Optional[Any]:
        if ckpt_path is None:
            return None
        from ray_lightning_tpu.trainer.checkpoint_io import (
            is_sharded_checkpoint,
        )

        if is_sharded_checkpoint(ckpt_path):
            # Sharded (orbax) checkpoints are restored inside the workers
            # against the live mesh; ship the path, not bytes. Requires the
            # directory to be reachable from every host (shared FS), like
            # the reference's best_model_path contract (SURVEY.md §5).
            return {"orbax_path": os.path.abspath(ckpt_path)}
        import fsspec

        with fsspec.open(ckpt_path, "rb") as f:
            return f.read()

    # ------------------------------------------------------------------
    def fit(
        self,
        module: Any,
        datamodule: Any = None,
        ckpt_path: Optional[str] = None,
    ) -> "Trainer":
        """Run the fit stage; with ``max_restarts > 0``, worker-group
        failures (a dead actor mid-fit) relaunch the group and resume from
        the newest on-disk checkpoint (or the original ``ckpt_path``/scratch
        when none was written yet), and a PREEMPTED fit (the loop's
        checkpoint-on-notice wrote a validated checkpoint at the step
        boundary the notice caught, then exited cleanly) resumes from
        exactly that checkpoint — bit-exact, losing at most the one step
        that was in flight. Checkpoints must be reachable from the
        driver — true on single-host fits and shared filesystems; the
        reference gets the same property from Ray Tune's trial-level
        restore rather than the trainer (SURVEY.md §5 failure detection).
        Every restart is observable: ``fit_restarting`` / ``fit_resume``
        typed events and the ``rlt_train_fit_restarts_total{cause=}``
        counter, next to the serving plane's recovery events.
        """
        from ray_lightning_tpu.fabric.core import ActorDiedError
        from ray_lightning_tpu.trainer.loop import TrainingPreempted

        fit_started = time.time()
        attempts = self.max_restarts
        ckpt_data: Optional[Any] = None  # pre-read payload for retries
        while True:
            try:
                self._run("fit", module, datamodule, ckpt_path, ckpt_data)
                return self
            except (ActorDiedError, TrainingPreempted) as exc:
                if attempts <= 0:
                    raise
                attempts -= 1
                preempted = isinstance(exc, TrainingPreempted)
                cause = "preempted" if preempted else "actor_died"
                self._record_fit_restart(cause, exc, attempts)
                resume, resume_data = self._restart_checkpoint(fit_started)
                warnings.warn(
                    (
                        "fit preempted (checkpoint-on-notice saved); "
                        if preempted
                        else f"worker died mid-fit ({exc}); "
                    )
                    + f"restarting ({attempts} restart(s) left) from "
                    f"{resume or ckpt_path or 'scratch'}",
                    RuntimeWarning,
                    stacklevel=2,
                )
                if preempted:
                    # The notice is consumed: this retry stands in for
                    # the replacement process (a real reclamation kills
                    # this one regardless — then the NEXT fit, in the
                    # fresh process, resumes from the same checkpoint).
                    from ray_lightning_tpu.serve.preempt import (
                        reset_monitor,
                    )

                    reset_monitor()
                if resume is not None:
                    # Reuse the validation read — no second read+unpickle.
                    ckpt_path, ckpt_data = resume, resume_data
                else:
                    ckpt_data = None  # fall back to original ckpt_path
                self._record_fit_resume(
                    cause, resume or ckpt_path or "scratch"
                )

    def _record_fit_restart(
        self, cause: str, exc: BaseException, restarts_left: int
    ) -> None:
        """Typed observability for the fit retry loop: training
        recoveries must show up in /events and doctor bundles exactly
        like serving recoveries do (not just a warnings.warn)."""
        from ray_lightning_tpu.obs.events import get_event_log
        from ray_lightning_tpu.obs.registry import get_registry

        get_registry().counter(
            "rlt_train_fit_restarts_total",
            "Mid-fit restarts performed by the Trainer.fit retry loop",
        ).inc(1, cause=cause)
        get_event_log().record(
            "trainer", "fit_restarting", level="warn", cause=cause,
            error=f"{type(exc).__name__}: {exc}"[:300],
            restarts_left=restarts_left,
        )

    @staticmethod
    def _record_fit_resume(cause: str, ckpt: str) -> None:
        from ray_lightning_tpu.obs.events import get_event_log

        get_event_log().record(
            "trainer", "fit_resume", cause=cause, ckpt=str(ckpt),
        )

    def _ckpt_search_dirs(self) -> List[str]:
        cb = self.checkpoint_callback
        dirs = []
        if cb is not None and getattr(cb, "dirpath", None):
            dirs.append(cb.dirpath)
        dirs.append(os.path.join(self.default_root_dir, "checkpoints"))
        return dirs

    @staticmethod
    def _ckpt_candidates(d: str) -> List[Tuple[str, float]]:
        """(path, mtime) checkpoint candidates in a directory; entries that
        vanish between listdir and stat (a concurrent prune) are skipped
        rather than crashing the scan."""
        from ray_lightning_tpu.trainer.checkpoint_io import (
            is_sharded_checkpoint,
        )

        out = []
        if not os.path.isdir(d):
            return out
        for name in os.listdir(d):
            p = os.path.join(d, name)
            if not (name.endswith(".ckpt") or is_sharded_checkpoint(p)):
                continue
            try:
                out.append((p, os.path.getmtime(p)))
            except OSError:
                continue
        return out

    def _resolve_best_ckpt(self) -> str:
        """Resolve ``ckpt_path="best"`` (PTL convention): the checkpoint
        callback's best_model_path from the monitored metric."""
        cb = self.checkpoint_callback
        best = getattr(cb, "best_model_path", "") if cb is not None else ""
        if best and os.path.exists(best):
            return best
        raise FileNotFoundError(
            'ckpt_path="best" needs a ModelCheckpoint with a recorded '
            "best_model_path (fit with a monitored metric first)"
        )

    def _resolve_last_ckpt(self) -> str:
        """Resolve ``ckpt_path="last"`` (PTL convention): the checkpoint
        callback's rolling last path, else the newest LOADABLE checkpoint
        in its dir / the default checkpoints dir (an unfinalized dir left
        by a crashed async save falls through to the next newest)."""
        cb = self.checkpoint_callback
        last = getattr(cb, "last_model_path", "") if cb is not None else ""
        if last and os.path.exists(last):
            return last  # may be stale (restored from another run's dir)
        path, _ = self._validated_ckpt_scan(min_mtime=None)
        if path is None:
            raise FileNotFoundError(
                "ckpt_path='last': no loadable checkpoint found in "
                f"{self._ckpt_search_dirs()} (fit with checkpointing "
                "enabled first)"
            )
        return path

    def _restart_checkpoint(
        self, fit_started: float
    ) -> Tuple[Optional[str], Optional[Any]]:
        """Newest LOADABLE checkpoint written by THIS fit (mtime after the
        fit started — a shared checkpoint dir may hold files from earlier,
        unrelated runs whose param trees don't match)."""
        return self._validated_ckpt_scan(min_mtime=fit_started - 1.0)

    def _validated_ckpt_scan(
        self, min_mtime: Optional[float]
    ) -> Tuple[Optional[str], Optional[Any]]:
        """Newest loadable checkpoint across the search dirs. Prefers the
        rolling ``last`` checkpoint; a candidate that fails validation
        (e.g. a save in flight when a worker died, or a sharded dir
        missing its finalizing meta file) falls through to the next newest
        instead of aborting. Returns ``(path, read_payload)`` so callers
        don't read + unpickle a second time."""
        from ray_lightning_tpu.trainer.checkpoint_io import _META_FILE

        for d in self._ckpt_search_dirs():
            candidates = [
                (p, m)
                for p, m in self._ckpt_candidates(d)
                if min_mtime is None or m >= min_mtime
            ]
            if not candidates:
                continue
            last = [
                pm
                for pm in candidates
                if os.path.basename(pm[0]).startswith("last")
            ]
            rest = [pm for pm in candidates if pm not in last]
            newest_first = sorted(last, key=lambda t: t[1], reverse=True)
            newest_first += sorted(rest, key=lambda t: t[1], reverse=True)
            ordered = [p for p, _ in newest_first]
            for path in ordered:
                try:
                    data = self._read_ckpt(path)
                    from ray_lightning_tpu.utils.state_stream import (
                        load_state_stream,
                    )

                    if isinstance(data, bytes):
                        load_state_stream(data)  # full unpickle check
                    else:
                        # Sharded dir: orbax renames the state tree into
                        # place atomically, and meta.ckpt is written (also
                        # atomically) only after that finishes — so a
                        # loadable meta file marks a finalized checkpoint.
                        with open(os.path.join(path, _META_FILE), "rb") as f:
                            load_state_stream(f.read())
                except Exception as exc:  # noqa: BLE001 - fall to older ckpt
                    warnings.warn(
                        f"skipping unreadable checkpoint {path}: {exc}",
                        RuntimeWarning,
                        stacklevel=2,
                    )
                    continue
                return path, data
        return None, None

    def validate(
        self, module: Any, datamodule: Any = None, ckpt_path: Optional[str] = None
    ) -> List[Dict[str, float]]:
        return self._run("validate", module, datamodule, ckpt_path)

    def test(
        self, module: Any, datamodule: Any = None, ckpt_path: Optional[str] = None
    ) -> List[Dict[str, float]]:
        return self._run("test", module, datamodule, ckpt_path)

    def predict(
        self,
        module: Any,
        datamodule: Any = None,
        ckpt_path: Optional[str] = None,
        return_predictions: bool = True,
    ) -> Optional[List[Any]]:
        """Run inference. ``return_predictions=False`` (PTL semantics)
        skips accumulating/shipping outputs entirely — pair it with a
        ``PredictionWriter`` so each rank streams its shard to disk and
        per-rank memory stays bounded at pod scale."""
        self._return_predictions = return_predictions
        try:
            return self._run("predict", module, datamodule, ckpt_path)
        finally:
            self._return_predictions = True

    # ------------------------------------------------------------------
    def _recover_results_in_main_process(self, output: Any, module: Any) -> Any:
        """Restore rank-0 worker results into this process (the reference's
        ``_recover_results_in_main_process``, ray_launcher.py:351-379)."""
        if output is None:
            return None
        if output.state_stream is not None:
            from ray_lightning_tpu.utils.state_stream import load_state_stream

            state = load_state_stream(output.state_stream)
            module.load_state_dict(state)
        self.state = dict(output.trainer_state)
        epoch = int(self.state.pop("epoch", 0))
        step = int(self.state.pop("global_step", 0))
        uc = self.state.pop("update_count", None)
        me = self.state.pop("mid_epoch", None)
        if self.state.get("stage") == "fit":
            # Only fits advance training progress: a validate/test/predict
            # after a fit must not clobber the fit's counters (its loop
            # legitimately reports epoch=0/step=0), or save_checkpoint()
            # would write resume metadata that restarts from scratch.
            self.current_epoch = epoch
            self.global_step = step
            # Actual optimizer-update count under accumulation (windows +
            # epoch-end flushes) — None when accumulation is off.
            self._update_count = None if uc is None else int(uc)
            self._mid_epoch = bool(me)
        lr = self.state.pop("current_lr", None)
        if lr is not None or self.state.get("stage") == "fit":
            # Fits always reset (plain transforms legitimately have no lr);
            # eval stages never carry one, so they preserve the fit's value.
            self._recovered_lr = None if lr is None else float(lr)
        # Metrics cross the boundary as numpy and are re-exposed as floats
        # (reference re-tensorizes at ray_launcher.py:374-379).
        self.callback_metrics = {
            k: float(np.asarray(v)) for k, v in output.callback_metrics.items()
        }
        self.logged_metrics = {
            k: float(np.asarray(v)) for k, v in output.logged_metrics.items()
        }
        # Sync driver-side callback objects (best_model_path etc.,
        # ray_launcher.py:357-360).
        for cb in self.callbacks:
            cb_state = output.callback_states.get(type(cb).__name__)
            if cb_state:
                cb.load_state_dict(cb_state)
        return output.results

    # ------------------------------------------------------------------
    def save_checkpoint(self, path: str) -> None:
        """Save the current module params from the driver."""
        if self._module is None or self._module.params is None:
            raise RuntimeError("nothing to checkpoint: fit first")
        from ray_lightning_tpu.utils.state_stream import (
            state_stream_to_file,
            to_state_stream,
        )

        state = {
            "params": self._module.params,
            "epoch": self.current_epoch,
            "global_step": self.global_step,
            # Same re-run-the-epoch resume semantics as worker-written
            # checkpoints (incl. the MultiSteps partial-window reset).
            "mid_epoch": self._mid_epoch,
            "callbacks": {
                type(cb).__name__: cb.state_dict() for cb in self.callbacks
            },
        }
        if getattr(self._module, "opt_state", None) is not None:
            # Fit outputs ship gathered optimizer state back; including it
            # makes this file fully resumable (momentum + LR schedule).
            state["opt_state"] = self._module.opt_state
        if getattr(self._module, "ema_params", None) is not None:
            state["ema_params"] = self._module.ema_params  # serves eval_ema
        state_stream_to_file(to_state_stream(state), path)
