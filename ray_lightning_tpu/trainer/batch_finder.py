"""Batch-size finder (PTL's ``Tuner.scale_batch_size`` analog).

Probes how large a per-step batch the device can take by compiling and
running the module's real jitted update at a ramp of candidate sizes,
catching XLA's RESOURCE_EXHAUSTED at compile or execute time. Two things
are TPU-specific here:

- OOM is a *compile-or-first-run* event (static shapes: if one step fits,
  every step fits), so ``steps_per_trial`` can stay tiny and the probe is
  cheap — there is no fragmentation drift to chase across an epoch.
- On TPU the largest-fitting batch is often NOT the fastest point: past
  MXU saturation steps/s stops improving while the batch keeps growing.
  Each trial therefore also measures samples/s, and the result carries a
  ``throughput_optimal`` size next to the Lightning-style ``largest``.

Probe batches are synthesized by row-tiling the loader's first batch, so
the sweep never depends on the dataset being big enough to fill the
candidate size. Like :mod:`.lr_finder`, this runs single-process on the
default backend — it is a probe, not a training run; the chosen size then
feeds any strategy's real fit.
"""
from __future__ import annotations

import dataclasses
import gc
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

_OOM_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "Out of memory",
    "out of memory",
    "OOM",
    "Allocation failure",
)


def _is_oom(exc: BaseException) -> bool:
    if isinstance(exc, MemoryError):
        return True
    msg = str(exc)
    return any(m in msg for m in _OOM_MARKERS)


def _tile_rows(arr: Any, n: int) -> np.ndarray:
    """Row-tile ``arr`` along axis 0 to exactly ``n`` rows (wrapping)."""
    a = np.asarray(arr)
    if a.ndim == 0:
        raise ValueError("batch leaves must have a leading batch axis")
    return a[np.arange(n) % a.shape[0]]


@dataclasses.dataclass
class ScaleBatchSizeResult:
    sizes: List[int]  # every size probed, in order
    samples_per_sec: Dict[int, float]  # successful sizes only
    largest: Optional[int]  # biggest size that fit (Lightning's answer)
    throughput_optimal: Optional[int]  # fastest samples/s among fits
    failed_at: Optional[int]  # first size that OOMed (None: never)

    @property
    def suggestion(self) -> Optional[int]:
        return self.largest

    def suggestion_or(self, default: int) -> int:
        return self.largest if self.largest is not None else default


def scale_batch_size(
    module: Any,
    mode: str = "power",
    init_val: int = 2,
    max_trials: int = 25,
    steps_per_trial: int = 3,
    max_val: Optional[int] = None,
    optimizer: Optional[Callable[..., Any]] = None,
    seed: int = 0,
) -> ScaleBatchSizeResult:
    """Find the largest (and fastest) batch the device can step.

    Args:
      module: a TPUModule; its ``train_dataloader`` supplies one template
        batch and ``training_step`` defines the probed computation.
        ``module.params`` is never touched.
      mode: ``"power"`` doubles from ``init_val`` until failure;
        ``"binsearch"`` additionally bisects between the last fit and the
        first failure for a tighter answer.
      max_trials: cap on total probe steps (each trial is one compile).
      max_val: optional hard ceiling (e.g. the real dataset size, or a
        global-batch constraint from the mesh's data axis).
      optimizer: ``optax`` transform factory probed against (default
        ``optax.adam(1e-3)``) — optimizer state is part of the memory
        footprint, so probe with the family you will train with.

    Returns a :class:`ScaleBatchSizeResult`. ``largest`` is None when even
    ``init_val`` does not fit.
    """
    import jax
    import optax

    if mode not in ("power", "binsearch"):
        raise ValueError(f"mode must be 'power' or 'binsearch', got {mode!r}")
    if init_val < 1:
        raise ValueError("init_val must be >= 1")

    tx = optimizer(1e-3) if optimizer is not None else optax.adam(1e-3)
    loader = module.train_dataloader()
    template = next(iter(loader.iter_batches(1, prefetch=0)))
    rng = jax.random.PRNGKey(seed)
    init_rng, step_rng = jax.random.split(rng)

    def probe(bs: int) -> Optional[float]:
        """samples/s at ``bs``, or None on OOM. Non-OOM errors propagate."""
        batch = jax.tree_util.tree_map(lambda x: _tile_rows(x, bs), template)

        @jax.jit
        def step_fn(params, opt_state, b, r):
            def loss_fn(p):
                loss, _ = module.training_step(p, b, r)
                return loss

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        try:
            params = module.init_params(init_rng, batch)
            opt_state = tx.init(params)
            # Warmup = compile + first execute; OOM surfaces here.
            params, opt_state, loss = step_fn(params, opt_state, batch, step_rng)
            jax.block_until_ready(loss)
            t0 = time.perf_counter()
            for _ in range(steps_per_trial):
                params, opt_state, loss = step_fn(params, opt_state, batch, step_rng)
            jax.block_until_ready(loss)
            dt = max(time.perf_counter() - t0, 1e-9)
            return bs * steps_per_trial / dt
        except Exception as exc:  # noqa: BLE001 - OOM classification below
            if _is_oom(exc):
                return None
            raise
        finally:
            # Drop the probe's device buffers before the next (bigger) try.
            del batch
            gc.collect()

    sizes: List[int] = []
    rates: Dict[int, float] = {}
    failed_at: Optional[int] = None
    largest: Optional[int] = None

    bs = init_val if max_val is None else min(init_val, max_val)
    trials = 0
    while trials < max_trials:
        sizes.append(bs)
        trials += 1
        rate = probe(bs)
        if rate is None:
            failed_at = bs
            break
        rates[bs] = rate
        largest = bs
        if max_val is not None and bs >= max_val:
            break
        # Clamp the ramp so the ceiling ITSELF gets probed (a plain
        # doubling would skip e.g. max_val=48 after 32 and return a
        # smaller batch than the cap the caller asked about).
        bs = bs * 2 if max_val is None else min(bs * 2, max_val)

    if mode == "binsearch" and failed_at is not None and largest is not None:
        lo, hi = largest, failed_at
        while trials < max_trials and hi - lo > max(1, lo // 8):
            mid = (lo + hi) // 2
            sizes.append(mid)
            trials += 1
            rate = probe(mid)
            if rate is None:
                hi = mid
                failed_at = mid
            else:
                rates[mid] = rate
                lo = mid
                largest = max(largest, mid)

    throughput_optimal = (
        max(rates, key=lambda k: rates[k]) if rates else None
    )
    return ScaleBatchSizeResult(
        sizes=sizes,
        samples_per_sec={k: round(v, 3) for k, v in rates.items()},
        largest=largest,
        throughput_optimal=throughput_optimal,
        failed_at=failed_at,
    )
