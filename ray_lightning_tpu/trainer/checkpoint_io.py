"""Checkpoint IO backends: byte-stream (wire format) and orbax (sharded).

The reference has exactly one checkpoint wire format — rank-0 state_dict ->
bytes -> driver (SURVEY.md §3.4), which this framework reproduces as the
state-stream (utils/state_stream.py). That format requires gathering the
full state onto one host, which stops scaling once GSPMD/ZeRO shards the
optimizer across hosts (SURVEY.md §7 "checkpoint of sharded state").

OrbaxCheckpointIO is the sharded alternative: every process writes only its
addressable shards through orbax/tensorstore, and restore reads directly
into the target topology's shardings — including a *different* device count
or mesh shape than the save ran on (the reference asserts resume with a
different worker count works, test_ddp_sharded.py:118-137; here that falls
out of resharding-on-restore).

Layout of a sharded checkpoint directory:
    <path>/state/...   orbax pytree of {"params", "opt_state"}
    <path>/meta.ckpt   state-stream with {epoch, global_step, callbacks}
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional, Tuple

from ray_lightning_tpu.utils.state_stream import (
    load_state_stream,
    state_stream_to_file,
    to_state_stream,
)

_STATE_SUBDIR = "state"
_META_FILE = "meta.ckpt"


def is_sharded_checkpoint(path: str) -> bool:
    return os.path.isdir(os.path.join(path, _STATE_SUBDIR))


class OrbaxCheckpointIO:
    """Sharded save/restore via orbax (tensorstore under the hood)."""

    def save(
        self,
        path: str,
        state: Dict[str, Any],
        meta: Dict[str, Any],
        is_rank_zero: bool = True,
    ) -> None:
        """Write device-sharded ``state`` (every process participates) and,
        on rank zero, the host-side ``meta`` stream."""
        import orbax.checkpoint as ocp

        path = os.path.abspath(path)
        # Unfinalize a reused path (rolling "last") for the whole write:
        # orbax renames the new state tree into place atomically, so a
        # crash between that rename and the meta rewrite would otherwise
        # leave new state under the PREVIOUS save's meta — which resume
        # logic would accept as finalized with off-by-one progress.
        if is_rank_zero:
            try:
                os.remove(os.path.join(path, _META_FILE))
            except OSError:
                pass
        ckptr = ocp.StandardCheckpointer()
        try:
            ckptr.save(os.path.join(path, _STATE_SUBDIR), state, force=True)
            ckptr.wait_until_finished()
        finally:
            ckptr.close()
        if is_rank_zero:
            state_stream_to_file(
                to_state_stream(meta), os.path.join(path, _META_FILE)
            )

    def finalize(self) -> None:
        """No-op for the synchronous IO (see AsyncOrbaxCheckpointIO)."""

    def restore(
        self,
        path: str,
        placed_state: Dict[str, Any],
        partial: bool = False,
    ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """Read into the shardings of ``placed_state`` (arrays land sharded
        on the *current* mesh, whatever topology wrote them).

        ``partial=True`` restores only the keys present in ``placed_state``
        even when the on-disk tree has more (e.g. eval-only restore of
        ``params`` from a checkpoint that also carries ``opt_state`` —
        mirroring the reference's test-without-fit path,
        test_ddp_sharded.py:118-137).
        """
        import jax
        import orbax.checkpoint as ocp

        path = os.path.abspath(path)

        def as_abstract(x: Any) -> Any:
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)

        abstract = jax.tree_util.tree_map(as_abstract, placed_state)
        state_dir = os.path.join(path, _STATE_SUBDIR)
        if partial:
            ckptr = ocp.Checkpointer(ocp.PyTreeCheckpointHandler())
            restore_args = ocp.checkpoint_utils.construct_restore_args(
                abstract
            )
            try:
                pytree_restore = ocp.args.PyTreeRestore(
                    item=abstract,
                    restore_args=restore_args,
                    partial_restore=True,
                )
            except TypeError:
                # Older orbax: no partial_restore kwarg; an (empty)
                # transforms dict is its spelling of "restore only the
                # item's keys, ignore the rest of the on-disk tree".
                pytree_restore = ocp.args.PyTreeRestore(
                    item=abstract,
                    restore_args=restore_args,
                    transforms={},
                )
            restore_kwargs = {"args": pytree_restore}
        else:
            ckptr = ocp.StandardCheckpointer()
            restore_kwargs = {"target": abstract}
        try:
            restored = ckptr.restore(state_dir, **restore_kwargs)
        finally:
            ckptr.close()
        meta_path = os.path.join(path, _META_FILE)
        meta: Dict[str, Any] = {}
        if os.path.exists(meta_path):
            with open(meta_path, "rb") as f:
                meta = load_state_stream(f.read())
        elif not partial:
            # Eval-only (partial) restores discard meta; warn only when the
            # caller will actually consume progress state.
            import warnings

            warnings.warn(
                f"sharded checkpoint at {path} has no {_META_FILE}; "
                "epoch/global_step/callback progress will reset to 0 "
                "(was the checkpoint copied without its meta file, or "
                "written on a non-shared filesystem?)",
                stacklevel=2,
            )
        return restored, meta


class AsyncOrbaxCheckpointIO(OrbaxCheckpointIO):
    """Sharded save that overlaps tensorstore writes with training.

    ``StandardCheckpointer.save`` is async under the hood: it returns once
    device shards are snapshotted to host, and the filesystem writes run in
    a background thread. The synchronous IO immediately blocks on
    ``wait_until_finished``; this one defers that to ``finalize()`` —
    called before the NEXT save (at most one save in flight) and at fit
    end — so an epoch of compute hides the write latency.

    Crash-consistency is unchanged: ``meta.ckpt`` (the finalization marker
    the restart scanner requires) is only written inside ``finalize()``,
    after the state tree is fully on disk. A process killed mid-write
    leaves an unfinalized directory that resume logic already skips.
    """

    def __init__(self) -> None:
        self._pending: Optional[Tuple[Any, str, bytes, bool]] = None

    def save(
        self,
        path: str,
        state: Dict[str, Any],
        meta: Dict[str, Any],
        is_rank_zero: bool = True,
    ) -> None:
        import orbax.checkpoint as ocp

        self.finalize()  # at most one save in flight
        path = os.path.abspath(path)
        # Unfinalize the reused path for the (now epoch-long) write window;
        # same reasoning as the sync save, bigger window.
        if is_rank_zero:
            try:
                os.remove(os.path.join(path, _META_FILE))
            except OSError:
                pass
        ckptr = ocp.StandardCheckpointer()
        try:
            ckptr.save(os.path.join(path, _STATE_SUBDIR), state, force=True)
        except BaseException:
            ckptr.close()  # don't leak the async machinery on dispatch failure
            raise
        self._pending = (ckptr, path, to_state_stream(meta), is_rank_zero)

    def finalize(self) -> None:
        """Block until the in-flight save (if any) is durable, then write
        the meta marker. Every rank must call this (the orbax save is
        collective); rank 0 writes the marker."""
        if self._pending is None:
            return
        ckptr, path, meta_stream, is_rank_zero = self._pending
        self._pending = None
        try:
            ckptr.wait_until_finished()
        finally:
            ckptr.close()
        if is_rank_zero:
            state_stream_to_file(meta_stream, os.path.join(path, _META_FILE))


def average_checkpoints(paths, out_path=None, keys=("params",)):
    """Average parameter trees across state-stream checkpoints.

    The "model soup" / checkpoint-SWA utility: element-wise mean of the
    listed checkpoints' ``params`` (and any other ``keys`` whose trees
    match), with the FIRST checkpoint's remaining state (progress
    counters, callbacks) carried over. Floating leaves are averaged in
    float64 and cast back; non-float leaves must be identical across
    inputs (they are carried, not averaged).

    Args:
      paths: two or more state-stream checkpoint files.
      out_path: when given, the averaged state is written there.
    Returns the averaged state dict.
    """
    import jax
    import numpy as np

    from ray_lightning_tpu.utils.state_stream import (
        load_state_stream,
        state_stream_to_file,
        to_state_stream,
    )

    paths = list(paths)
    if len(paths) < 2:
        raise ValueError("average_checkpoints needs at least two inputs")
    states = []
    for p in paths:
        if is_sharded_checkpoint(p):
            raise ValueError(
                f"{p} is a sharded (orbax) directory; restore it to a "
                "state-stream file first (validate/save_checkpoint)"
            )
        with open(p, "rb") as f:
            states.append(load_state_stream(f.read()))
    out = dict(states[0])
    for key in keys:
        trees = [s[key] for s in states if key in s]
        if not trees:
            continue
        if len(trees) != len(states):
            raise ValueError(
                f"checkpoint key {key!r} present in only {len(trees)} of "
                f"{len(states)} inputs"
            )
        structs = {jax.tree_util.tree_structure(t) for t in trees}
        if len(structs) > 1:
            raise ValueError(
                f"checkpoint trees under {key!r} have different structures"
            )

        def _avg(*leaves):
            first = np.asarray(leaves[0])
            if not np.issubdtype(first.dtype, np.floating):
                for other in leaves[1:]:
                    if not np.array_equal(first, np.asarray(other)):
                        raise ValueError(
                            "non-float leaves differ across checkpoints; "
                            "only float parameters can be averaged"
                        )
                return first
            acc = np.mean(
                [np.asarray(x, np.float64) for x in leaves], axis=0
            )
            return acc.astype(first.dtype)

        out[key] = jax.tree_util.tree_map(_avg, *trees)
    if out_path is not None:
        state_stream_to_file(to_state_stream(out), out_path)
    return out
