"""Callbacks: host-side hooks at step/epoch boundaries.

The reference reuses PTL callbacks (ModelCheckpoint/EarlyStopping are
exercised by test_ddp.py:241-247,289-308); this framework defines its own,
with the TPU-specific constraint that callbacks run *between* compiled steps
— they can read aggregated metrics (already on host) but never reach inside
the jitted step. Checkpoint IO is rank-0 only, mirroring the reference's
rank-zero discipline (ray_ddp.py:169).
"""
from __future__ import annotations

import math
import os
from typing import Any, Dict, Optional

import numpy as np


class Callback:
    def on_fit_start(self, trainer: Any, module: Any) -> None: ...

    def on_train_epoch_start(self, trainer: Any, module: Any) -> None: ...

    def on_train_batch_end(
        self, trainer: Any, module: Any, logs: Dict[str, float], batch_idx: int
    ) -> None: ...

    def on_train_epoch_end(self, trainer: Any, module: Any) -> None: ...

    def on_validation_end(self, trainer: Any, module: Any) -> None: ...

    def on_fit_end(self, trainer: Any, module: Any) -> None: ...

    def on_predict_batch_end(
        self, trainer: Any, module: Any, prediction: Any, batch_idx: int
    ) -> None: ...

    def on_predict_end(
        self, trainer: Any, module: Any, predictions: Any
    ) -> None: ...

    def state_dict(self) -> Dict[str, Any]:
        return {}

    def load_state_dict(self, state: Dict[str, Any]) -> None: ...


def _remove_checkpoint(path: str) -> None:
    """Delete a checkpoint file or sharded checkpoint directory."""
    import shutil

    try:
        if os.path.isdir(path):
            shutil.rmtree(path)
        else:
            os.remove(path)
    except OSError:
        pass


def _metric_value(trainer: Any, monitor: str) -> Optional[float]:
    val = trainer.callback_metrics.get(monitor)
    if val is None:
        return None
    return float(np.asarray(val))


class ModelCheckpoint(Callback):
    """Save the training state each validation/epoch end; track the best.

    Files are state-stream checkpoints (utils/state_stream.py) containing
    params + optimizer state + loop counters, so resume restores exactly.
    ``best_model_path`` propagates to the driver in the worker output, like
    the reference's (ray_launcher.py:319-321, :357-360).
    """

    def __init__(
        self,
        dirpath: Optional[str] = None,
        filename: str = "epoch={epoch}-step={step}",
        monitor: Optional[str] = None,
        mode: str = "min",
        save_top_k: int = 1,
        save_last: bool = False,
        save_sharded: bool = False,
    ) -> None:
        assert mode in ("min", "max")
        self.save_sharded = save_sharded
        self.dirpath = dirpath
        self.filename = filename
        self.monitor = monitor
        self.mode = mode
        self.save_top_k = save_top_k
        self.save_last = save_last
        self.best_model_path: str = ""
        self.best_model_score: Optional[float] = None
        self.last_model_path: str = ""
        self._saved: list[tuple[float, str]] = []

    def _is_better(self, score: float) -> bool:
        if self.best_model_score is None:
            return True
        if self.mode == "min":
            return score < self.best_model_score
        return score > self.best_model_score

    def on_validation_end(self, trainer: Any, module: Any) -> None:
        # PTL semantics: the pre-train sanity pass must not checkpoint —
        # its metrics are discarded, so a "best" score from 2 sanity batches
        # would pin best_model_path at untrained params.
        if getattr(trainer, "sanity_checking", False):
            return
        self._save(trainer, module)

    def on_train_epoch_end(self, trainer: Any, module: Any) -> None:
        # Only save here when there is no val loop (val end already saved).
        if not trainer.has_validation:
            self._save(trainer, module)

    def _save(self, trainer: Any, module: Any) -> None:
        if self.save_top_k == 0:
            return
        if (
            trainer.global_rank != 0
            and not self.save_sharded
            and not getattr(trainer, "gather_is_collective", False)
        ):
            # Plain-device_get strategies: nothing for non-zero ranks to
            # do. (Collective gathers need every rank below.)
            return
        dirpath = self.dirpath or os.path.join(trainer.default_root_dir, "checkpoints")
        os.makedirs(dirpath, exist_ok=True)
        name = self.filename.format(epoch=trainer.current_epoch, step=trainer.global_step)
        if self.save_sharded:
            # Directory checkpoint; every rank writes its shards (the
            # orbax save is collective), rank 0 keeps the bookkeeping.
            path = os.path.join(dirpath, name)
            trainer.save_checkpoint(path, sharded=True)
            if self.save_last:
                last = os.path.join(dirpath, "last")
                trainer.save_checkpoint(last, sharded=True)
                self.last_model_path = last
            if self.monitor is not None and self.save_top_k >= 0:
                # Pruning may delete the save just dispatched (worst
                # score). EVERY rank must drain its async writes BEFORE
                # rank 0 rmtree's — only rank 0 reaches _prune, so a
                # drain there would leave ranks >0 writing into a
                # deleted directory. (No-monitor mode prunes only the
                # PREVIOUS save, which the next dispatch already
                # finalized — full overlap is kept there.)
                getattr(trainer, "finalize_checkpoints", lambda: None)()
            if trainer.global_rank != 0:
                return
        else:
            # EVERY rank enters save_checkpoint: its state gather is a
            # collective under multi-process sharding (a rank-0-only call
            # deadlocks); rank 0 alone writes bytes and keeps bookkeeping.
            path = os.path.join(dirpath, name + ".ckpt")
            trainer.save_checkpoint(path)
            last = None
            if self.save_last:
                last = os.path.join(dirpath, "last.ckpt")
                trainer.save_checkpoint(last)
            if trainer.global_rank != 0:
                return
            if last:
                self.last_model_path = last
        score = _metric_value(trainer, self.monitor) if self.monitor else None
        if self.monitor is None:
            # No monitor: latest checkpoint is "best" (Lightning behavior)
            # and the previous one is pruned so only save_top_k remain.
            # (prev predates the save that just ran, so with async IO it
            # was finalized when this save started — safe to delete.)
            prev = self.best_model_path
            self.best_model_path = path
            if (
                self.save_top_k == 1
                and prev
                and prev != path
                and os.path.exists(prev)
            ):
                _remove_checkpoint(prev)
        elif score is not None and not math.isnan(score):
            if self._is_better(score):
                self.best_model_score = score
                self.best_model_path = path
            self._saved.append((score, path))
            self._prune(trainer)
        # (Non-sharded save_last happens above, before the rank gate — the
        # collective gather needs every rank.)

    def _prune(self, trainer: Any = None) -> None:
        # Deletion targets are always durable here: the monitored sharded
        # path drains every rank's async writes in _save before rank 0
        # gets this far.
        if self.save_top_k < 0:
            return
        reverse = self.mode == "max"
        self._saved.sort(key=lambda t: t[0], reverse=reverse)
        while len(self._saved) > self.save_top_k:
            _, path = self._saved.pop()
            if path != self.best_model_path and os.path.exists(path):
                _remove_checkpoint(path)

    def state_dict(self) -> Dict[str, Any]:
        return {
            "best_model_path": self.best_model_path,
            "best_model_score": self.best_model_score,
            "last_model_path": self.last_model_path,
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.best_model_path = state.get("best_model_path", "")
        self.best_model_score = state.get("best_model_score")
        self.last_model_path = state.get("last_model_path", "")


class EarlyStopping(Callback):
    """Stop training when a monitored metric stops improving.

    PTL-parity knobs beyond patience: ``stopping_threshold`` stops as soon
    as the metric is at least this good (the goal is reached),
    ``divergence_threshold`` stops as soon as it is at least this BAD (the
    run is unrecoverable), and ``check_finite`` stops on NaN/inf instead
    of skipping the reading.
    """

    def __init__(
        self,
        monitor: str = "val_loss",
        patience: int = 3,
        mode: str = "min",
        min_delta: float = 0.0,
        stopping_threshold: Optional[float] = None,
        divergence_threshold: Optional[float] = None,
        check_finite: bool = False,
    ) -> None:
        assert mode in ("min", "max")
        self.monitor = monitor
        self.patience = patience
        self.mode = mode
        self.min_delta = abs(min_delta)
        self.stopping_threshold = stopping_threshold
        self.divergence_threshold = divergence_threshold
        self.check_finite = check_finite
        self.wait = 0
        self.best: Optional[float] = None

    def _improved(self, score: float) -> bool:
        if self.best is None:
            return True
        if self.mode == "min":
            return score < self.best - self.min_delta
        return score > self.best + self.min_delta

    def _beats(self, score: float, threshold: float) -> bool:
        return score <= threshold if self.mode == "min" else score >= threshold

    def on_validation_end(self, trainer: Any, module: Any) -> None:
        if getattr(trainer, "sanity_checking", False):
            return  # discarded sanity metrics must not seed best/wait
        score = _metric_value(trainer, self.monitor)
        if score is None:
            return
        if not math.isfinite(score):
            if self.check_finite:
                trainer.should_stop = True
            return
        if self.stopping_threshold is not None and self._beats(
            score, self.stopping_threshold
        ):
            trainer.should_stop = True
            return
        if self.divergence_threshold is not None and not self._beats(
            score, self.divergence_threshold
        ):
            trainer.should_stop = True
            return
        if self._improved(score):
            self.best = score
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                trainer.should_stop = True

    def state_dict(self) -> Dict[str, Any]:
        return {"wait": self.wait, "best": self.best}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.wait = state.get("wait", 0)
        self.best = state.get("best")


class TPUStatsCallback(Callback):
    """Per-epoch wall time and device memory stats, averaged across hosts.

    TPU-native answer to the reference's example-level ``CUDACallback``
    (examples/ray_ddp_sharded_example.py:16-46), which measured epoch time and
    peak CUDA memory. Uses ``device.memory_stats()`` where the PJRT backend
    exposes it.

    ``flops_per_step`` — model FLOPs per EXECUTED training step, i.e. per
    micro-batch across all workers (e.g. ``6 * n_params *
    tokens_per_global_micro_batch`` for a transformer; with
    ``accumulate_grad_batches`` each micro-batch still runs a full
    fwd+bwd, so this is the honest compute unit) — additionally reports
    per-epoch MFU against the published bf16 peak of ALL the run's chips
    (``trainer.world_size``; ``utils/flops.py``). Skipped on devices with
    no known peak (CPU).
    """

    def __init__(
        self, verbose: bool = True, flops_per_step: Optional[float] = None
    ) -> None:
        self.flops_per_step = flops_per_step
        self.verbose = verbose
        self.epoch_times: list[float] = []
        self.peak_memory: list[float] = []
        self.mfu: list[float] = []
        self.steps_per_sec: list[float] = []
        self._t0 = 0.0
        self._step0 = 0

    @staticmethod
    def _fence(trainer: Any) -> None:
        # Drain in-flight device work so the timer is honest. effects_barrier
        # alone is NOT enough: it only waits for effectful ops, while the
        # loop's async step dispatches can still be queued — blocking on the
        # live params fences the real computation stream.
        import jax

        if getattr(trainer, "params", None) is not None:
            jax.block_until_ready(trainer.params)
        jax.effects_barrier()

    def on_train_epoch_start(self, trainer: Any, module: Any) -> None:
        import time

        self._fence(trainer)
        self._t0 = time.perf_counter()
        self._step0 = trainer.global_step

    def on_train_epoch_end(self, trainer: Any, module: Any) -> None:
        import time

        import jax

        self._fence(trainer)
        dt = time.perf_counter() - self._t0
        self.epoch_times.append(dt)
        steps_done = trainer.global_step - self._step0
        if dt > 0 and steps_done > 0:
            # Per-host step rate; a user-facing throughput number without
            # extra syncs (the fence above already paid the only one).
            sps = steps_done / dt
            self.steps_per_sec.append(sps)
            trainer.callback_metrics["steps_per_sec"] = sps
        peak = 0.0
        for dev in jax.local_devices():
            try:
                stats = dev.memory_stats() or {}
                peak = max(peak, float(stats.get("peak_bytes_in_use", 0)))
            except Exception:  # noqa: BLE001 - CPU backend has no stats
                pass
        self.peak_memory.append(peak)
        mfu = None
        if self.flops_per_step and dt > 0:
            from ray_lightning_tpu.utils.flops import peak_flops_for

            devs = jax.local_devices()
            peak_fl = peak_flops_for(devs[0].device_kind) if devs else None
            if peak_fl:
                # flops_per_step covers the GLOBAL micro-batch, so the
                # denominator is the peak of every chip in the run, not
                # just this process's.
                chips = max(
                    int(getattr(trainer, "world_size", 0) or 0), len(devs)
                )
                steps = trainer.global_step - self._step0
                mfu = (steps * float(self.flops_per_step) / dt) / (
                    peak_fl * chips
                )
                self.mfu.append(mfu)
                trainer.callback_metrics["mfu"] = mfu
        if self.verbose and trainer.global_rank == 0:
            print(
                f"[epoch {trainer.current_epoch}] time {dt:.3f}s"
                + (f", peak device mem {peak / 2**20:.1f} MiB" if peak else "")
                + (f", MFU {mfu:.3f}" if mfu is not None else "")
            )

    def state_dict(self) -> Dict[str, Any]:
        # Measurements ride the callback-state sync back to the driver.
        return {
            "epoch_times": self.epoch_times,
            "peak_memory": self.peak_memory,
            "mfu": self.mfu,
            "steps_per_sec": self.steps_per_sec,
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.epoch_times = list(state.get("epoch_times", []))
        self.peak_memory = list(state.get("peak_memory", []))
        self.mfu = list(state.get("mfu", []))
        self.steps_per_sec = list(state.get("steps_per_sec", []))


class JaxProfilerCallback(Callback):
    """Capture a ``jax.profiler`` trace for selected training epochs.

    TPU-native profiling (SURVEY.md §5 tracing): writes TensorBoard-loadable
    traces (XLA ops, fusion, HBM transfers, ICI collectives) under
    ``dirpath/plugins/profile``. Runs on worker rank 0 only; epoch 1 by
    default — epoch 0 is dominated by compilation.

    View with: ``tensorboard --logdir <dirpath>`` (Profile tab), or feed the
    ``.trace.json.gz`` to Perfetto.
    """

    def __init__(
        self,
        dirpath: str = "jax_trace",
        epochs: tuple = (1,),
        create_perfetto_trace: bool = False,
    ) -> None:
        self.dirpath = dirpath
        self.epochs = tuple(epochs)
        self.create_perfetto_trace = create_perfetto_trace
        self.trace_dirs: list[str] = []
        self._active = False

    def on_train_epoch_start(self, trainer: Any, module: Any) -> None:
        if trainer.global_rank != 0 or trainer.current_epoch not in self.epochs:
            return
        import jax

        os.makedirs(self.dirpath, exist_ok=True)
        # Fence so the trace contains only this epoch's work.
        TPUStatsCallback._fence(trainer)
        jax.profiler.start_trace(
            self.dirpath, create_perfetto_trace=self.create_perfetto_trace
        )
        self._active = True

    def on_train_epoch_end(self, trainer: Any, module: Any) -> None:
        if not self._active:
            return
        import jax

        TPUStatsCallback._fence(trainer)
        jax.profiler.stop_trace()
        self._active = False
        self.trace_dirs.append(self.dirpath)

    def state_dict(self) -> Dict[str, Any]:
        return {"trace_dirs": self.trace_dirs}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.trace_dirs = list(state.get("trace_dirs", []))


class LearningRateMonitor(Callback):
    """Log the schedule-driven learning rate as a ``lr`` metric.

    Works with modules whose ``configure_optimizers`` declares a schedule
    (``{"optimizer": tx, "lr_schedule": fn}`` or ``(tx, fn)`` — see
    ``TPUModule.configure_optimizers``). optax embeds schedules inside the
    gradient transform, so this reads the declared ``step -> lr`` callable at
    the loop's current optimizer-update index; no device sync. PTL-parity
    for the ``LearningRateMonitor`` users attach to the reference's Trainer.
    """

    def __init__(self, key: str = "lr") -> None:
        self.key = key

    def on_train_batch_end(
        self, trainer: Any, module: Any, logs: Dict[str, float], batch_idx: int
    ) -> None:
        lr = getattr(trainer, "current_lr", None)
        if lr is not None:
            trainer.logged_metrics[self.key] = lr
            # Also publish to callback_metrics here so epoch-end consumers
            # (CSVLogger, ModelCheckpoint monitors) see this epoch's lr
            # regardless of their position in the callbacks list.
            trainer.callback_metrics[self.key] = lr

    def on_train_epoch_end(self, trainer: Any, module: Any) -> None:
        lr = getattr(trainer, "current_lr", None)
        if lr is not None:
            trainer.callback_metrics[self.key] = lr


class TensorBoardLogger(Callback):
    """Scalar metrics to TensorBoard event files (rank 0 only).

    The PTL-style logger reference users attach for dashboards; pairs
    with ``JaxProfilerCallback``, whose traces land in the same
    TensorBoard UI. Per-step train metrics are written at the
    ``log_every_n_steps`` cadence (the host values the loop already
    fetched — no extra device syncs); validation metrics at each val end.
    Requires the ``tensorboard`` package (present in this image); raises
    a clear ImportError otherwise.
    """

    def __init__(
        self, dirpath: Optional[str] = None, name: str = "tb"
    ) -> None:
        try:
            from tensorboard.summary.writer.event_file_writer import (  # noqa: F401
                EventFileWriter,
            )
        except ImportError as exc:  # pragma: no cover - baked into image
            raise ImportError(
                "TensorBoardLogger needs the 'tensorboard' package"
            ) from exc
        self.dirpath = dirpath
        self.name = name
        self._writer: Any = None
        self._log_dir: Optional[str] = None

    @property
    def log_dir(self) -> Optional[str]:
        """Directory holding the event file (resolved at fit start)."""
        return self._log_dir

    def _ensure_writer(self, trainer: Any) -> Any:
        if self._writer is None:
            from tensorboard.summary.writer.event_file_writer import (
                EventFileWriter,
            )

            base = self.dirpath or os.path.join(
                trainer.default_root_dir, "tensorboard"
            )
            self._log_dir = os.path.join(base, self.name)
            os.makedirs(self._log_dir, exist_ok=True)
            self._writer = EventFileWriter(self._log_dir)
        return self._writer

    def _write_scalars(
        self, trainer: Any, metrics: Dict[str, Any], step: int
    ) -> None:
        import time

        from tensorboard.compat.proto.event_pb2 import Event
        from tensorboard.compat.proto.summary_pb2 import Summary

        values = []
        for k, v in metrics.items():
            try:
                values.append(
                    Summary.Value(tag=k, simple_value=float(np.asarray(v)))
                )
            except (TypeError, ValueError):
                continue
        if not values:
            return
        self._ensure_writer(trainer).add_event(
            Event(
                wall_time=time.time(), step=step, summary=Summary(value=values)
            )
        )

    def on_train_batch_end(
        self, trainer: Any, module: Any, logs: Dict[str, float], batch_idx: int
    ) -> None:
        if trainer.global_rank == 0 and logs:
            self._write_scalars(trainer, logs, trainer.global_step)

    def on_validation_end(self, trainer: Any, module: Any) -> None:
        if trainer.global_rank != 0 or getattr(
            trainer, "sanity_checking", False
        ):
            return
        # "val_loss" and namespaced forms like "ptl/val_loss" — but NOT
        # train metrics that merely contain the substring (eval_loss,
        # interval_loss).
        val = {
            k: v
            for k, v in trainer.callback_metrics.items()
            if k.split("/")[-1].startswith("val")
        }
        self._write_scalars(trainer, val, trainer.global_step)

    def on_fit_end(self, trainer: Any, module: Any) -> None:
        if self._writer is not None:
            self._writer.flush()
            self._writer.close()
            self._writer = None

    def state_dict(self) -> Dict[str, Any]:
        # The log dir rides the callback sync so the DRIVER-side object
        # can point users at the files the worker wrote.
        return {"log_dir": self._log_dir}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self._log_dir = state.get("log_dir") or self._log_dir


class CSVLogger(Callback):
    """Append one metrics row per epoch to ``dirpath/metrics.csv``.

    Lightweight stand-in for the PTL loggers reference users attach to their
    Trainer; rank-0 only, header grows with newly-seen metric keys (rows are
    rewritten when the key set expands).
    """

    def __init__(self, dirpath: Optional[str] = None, name: str = "metrics.csv") -> None:
        self.dirpath = dirpath
        self.name = name
        self.rows: list[Dict[str, Any]] = []
        self._resolved_dir: Optional[str] = dirpath

    @property
    def log_path(self) -> str:
        """Path of the written CSV (resolved against the trainer's root dir
        once a fit has run)."""
        return os.path.join(self._resolved_dir or self.dirpath or ".", self.name)

    def on_train_epoch_end(self, trainer: Any, module: Any) -> None:
        if trainer.global_rank != 0:
            return
        row: Dict[str, Any] = {
            "epoch": trainer.current_epoch,
            "step": trainer.global_step,
        }
        for k, v in trainer.callback_metrics.items():
            try:
                row[k] = float(np.asarray(v))
            except (TypeError, ValueError):
                continue
        self.rows.append(row)
        self._write(trainer)

    def _write(self, trainer: Any = None) -> None:
        import csv

        dirpath = self.dirpath or (
            trainer.default_root_dir
            if trainer is not None
            else self._resolved_dir or "."
        )
        self._resolved_dir = dirpath
        os.makedirs(dirpath, exist_ok=True)
        path = os.path.join(dirpath, self.name)
        keys: list[str] = []
        for row in self.rows:
            for k in row:
                if k not in keys:
                    keys.append(k)
        with open(path, "w", newline="") as f:
            writer = csv.DictWriter(f, fieldnames=keys)
            writer.writeheader()
            writer.writerows(self.rows)

    def state_dict(self) -> Dict[str, Any]:
        # Rows ride the callback sync so the DRIVER-side logger instance can
        # rewrite the file locally after a distributed fit.
        return {"rows": self.rows, "dirpath": self._resolved_dir}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.rows = list(state.get("rows", []))
        self._resolved_dir = self.dirpath or state.get("dirpath")
        if self.rows:
            # Rewrite locally: in client mode the worker's file lives on the
            # remote head's filesystem; the driver needs its own copy.
            self._write()


class StochasticWeightAveraging(Callback):
    """Equal-weight average of params along the training trajectory
    (Izmailov et al. 2018), PTL's ``StochasticWeightAveraging`` analog.

    From ``swa_epoch_start`` on, the end-of-epoch params are folded into a
    host-side running average (``avg += (params - avg) / n``); at fit end
    the averaged weights replace the live ones (``swap_params=False`` keeps
    them aside as ``.swa_params`` instead). Three averaging flavors now
    exist, picked by cadence: in-step decayed EMA (``Trainer(ema_decay=)``),
    epoch-cadence equal SWA (this), and post-hoc checkpoint soups
    (``average_checkpoints``).

    TPU notes: the average lives on HOST memory (no HBM cost); collection
    runs at epoch cadence so the gather never blocks the step stream. Every
    rank computes the same average — ``gather_state`` is a collective under
    sharded strategies, mirroring ModelCheckpoint's every-rank discipline.
    """

    def __init__(
        self, swa_epoch_start: Any = 0.8, swap_params: bool = True
    ) -> None:
        if isinstance(swa_epoch_start, float) and not 0 <= swa_epoch_start <= 1:
            raise ValueError(
                f"float swa_epoch_start must be in [0, 1], got {swa_epoch_start}"
            )
        if isinstance(swa_epoch_start, int) and swa_epoch_start < 0:
            raise ValueError(
                f"int swa_epoch_start must be >= 0, got {swa_epoch_start}"
            )
        self.swa_epoch_start = swa_epoch_start
        self.swap_params = swap_params
        self.n_models = 0
        self.swa_params: Any = None

    def _start_epoch(self, trainer: Any) -> int:
        if isinstance(self.swa_epoch_start, float):
            max_epochs = getattr(
                getattr(trainer, "spec", trainer), "max_epochs", 1
            )
            return int(self.swa_epoch_start * max_epochs)
        return int(self.swa_epoch_start)

    def on_train_epoch_end(self, trainer: Any, module: Any) -> None:
        if trainer.current_epoch < self._start_epoch(trainer):
            return
        import jax

        params = trainer.strategy.gather_state(trainer.params)
        self.n_models += 1
        n = self.n_models
        if self.swa_params is None:
            self.swa_params = params
        else:
            self.swa_params = jax.tree_util.tree_map(
                lambda avg, p: avg + (np.asarray(p, avg.dtype) - avg) / n,
                self.swa_params,
                params,
            )

    def on_fit_end(self, trainer: Any, module: Any) -> None:
        if self.swa_params is None or not self.swap_params:
            return
        # The fit is over (no steps follow), so host arrays are fine here;
        # the rank-0 result collection device_gets them unchanged.
        trainer.params = self.swa_params
        module.params = self.swa_params

    def state_dict(self) -> Dict[str, Any]:
        # The running average rides checkpoints so fault-tolerant restarts
        # (Trainer(max_restarts=)) keep collecting instead of starting over.
        return {"n_models": self.n_models, "swa_params": self.swa_params}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.n_models = int(state.get("n_models", 0))
        self.swa_params = state.get("swa_params")


class PredictionWriter(Callback):
    """Per-rank streaming prediction writer (PTL's BasePredictionWriter).

    Large-scale inference on a pod can't funnel every prediction through
    the rank-0 result channel; each rank instead writes ITS shard of
    predictions (the loop hands callbacks disjoint per-process row sets
    that partition each batch exactly once) to ``output_dir`` as
    state-stream files readable with :meth:`read`. ``write_interval="batch"`` streams one file per batch —
    pair it with ``predict(return_predictions=False)`` and per-rank memory
    stays O(1 batch), with nothing shipped through the result channel;
    ``"epoch"`` writes a single file per rank at the end (this rank's
    accumulated shard — O(dataset/world) memory, independent of
    return_predictions).
    """

    def __init__(self, output_dir: str, write_interval: str = "batch") -> None:
        if write_interval not in ("batch", "epoch"):
            raise ValueError(
                f"write_interval must be 'batch' or 'epoch', got "
                f"{write_interval!r}"
            )
        self.output_dir = output_dir
        self.write_interval = write_interval
        self.written_paths: list = []

    def _write(self, tree: Any, path: str) -> None:
        from ray_lightning_tpu.utils.state_stream import (
            state_stream_to_file,
            to_state_stream,
        )

        os.makedirs(self.output_dir, exist_ok=True)
        state_stream_to_file(to_state_stream(tree), path)
        self.written_paths.append(path)

    def on_predict_batch_end(
        self, trainer: Any, module: Any, prediction: Any, batch_idx: int
    ) -> None:
        if self.write_interval != "batch":
            return
        self._write(
            prediction,
            os.path.join(
                self.output_dir,
                f"predictions_rank{trainer.global_rank}"
                f"_batch{batch_idx:05d}.npz",
            ),
        )

    def on_predict_end(self, trainer: Any, module: Any, predictions: Any) -> None:
        if self.write_interval != "epoch":
            return
        if predictions is None:
            return
        self._write(
            predictions,
            os.path.join(
                self.output_dir,
                f"predictions_rank{trainer.global_rank}.npz",
            ),
        )

    @staticmethod
    def read(path: str) -> Any:
        """Load one written prediction file back as its host pytree."""
        from ray_lightning_tpu.utils.state_stream import load_state_stream

        with open(path, "rb") as f:
            return load_state_stream(f.read())

    def state_dict(self) -> Dict[str, Any]:
        # Paths ride the callback sync so the driver can locate per-rank
        # shards after a distributed predict (shared-FS assumption, same
        # as best_model_path propagation).
        return {"written_paths": self.written_paths}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.written_paths = list(state.get("written_paths", []))
