from ray_lightning_tpu.trainer.callbacks import (
    Callback,
    EarlyStopping,
    ModelCheckpoint,
    JaxProfilerCallback,
    TPUStatsCallback,
)
from ray_lightning_tpu.trainer.data import (
    ArrayDataset,
    DataLoader,
    Dataset,
    DistributedSampler,
)
from ray_lightning_tpu.trainer.loop import TrainerSpec, TrainingLoop
from ray_lightning_tpu.trainer.module import DataModule, TPUModule
from ray_lightning_tpu.trainer.trainer import Trainer

__all__ = [
    "Trainer",
    "TPUModule",
    "DataModule",
    "TrainerSpec",
    "TrainingLoop",
    "Callback",
    "ModelCheckpoint",
    "EarlyStopping",
    "JaxProfilerCallback",
    "TPUStatsCallback",
    "DataLoader",
    "Dataset",
    "ArrayDataset",
    "DistributedSampler",
]
