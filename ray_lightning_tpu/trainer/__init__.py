from ray_lightning_tpu.trainer.callbacks import (
    Callback,
    CSVLogger,
    EarlyStopping,
    LearningRateMonitor,
    ModelCheckpoint,
    JaxProfilerCallback,
    PredictionWriter,
    StochasticWeightAveraging,
    TensorBoardLogger,
    TPUStatsCallback,
)
from ray_lightning_tpu.trainer.batch_finder import (
    ScaleBatchSizeResult,
    scale_batch_size,
)
from ray_lightning_tpu.trainer.ema import ema_params, params_ema
from ray_lightning_tpu.trainer.lr_finder import LRFindResult, lr_find
from ray_lightning_tpu.trainer.data import (
    ArrayDataset,
    DataLoader,
    Dataset,
    DistributedSampler,
    IterableDataset,
    TokenBinDataset,
    write_token_bin,
)
from ray_lightning_tpu.trainer.loop import (
    TrainerSpec,
    TrainingLoop,
    TrainingPreempted,
)
from ray_lightning_tpu.trainer.module import DataModule, TPUModule
from ray_lightning_tpu.trainer.trainer import Trainer

__all__ = [
    "Trainer",
    "TPUModule",
    "DataModule",
    "TrainerSpec",
    "TrainingLoop",
    "TrainingPreempted",
    "Callback",
    "ModelCheckpoint",
    "CSVLogger",
    "TensorBoardLogger",
    "LRFindResult",
    "lr_find",
    "ScaleBatchSizeResult",
    "scale_batch_size",
    "EarlyStopping",
    "PredictionWriter",
    "StochasticWeightAveraging",
    "LearningRateMonitor",
    "JaxProfilerCallback",
    "TPUStatsCallback",
    "params_ema",
    "ema_params",
    "DataLoader",
    "Dataset",
    "IterableDataset",
    "ArrayDataset",
    "DistributedSampler",
    "TokenBinDataset",
    "write_token_bin",
]
