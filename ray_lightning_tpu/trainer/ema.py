"""Exponential moving average of model weights, TPU-natively.

PTL users attach ``StochasticWeightAveraging``/EMA callbacks that touch
weights between steps on the host; under XLA that would sync the device
every step. Here EMA is an ``optax`` transform chained after the
optimizer: the averaged weights live INSIDE ``opt_state``, so the update
stays in the one compiled step function, shards under whatever layout the
strategy gives the optimizer state (ZeRO/GSPMD), and checkpoints/resumes
with no extra plumbing.

Enable with ``Trainer(ema_decay=0.999)``; after ``fit`` the averaged
weights are at ``trainer.ema_params`` (and ``module.ema_params``), and
``Trainer(eval_ema=True)`` runs val/test/predict with them.
"""
from __future__ import annotations

from typing import Any, NamedTuple, Optional


class EmaState(NamedTuple):
    """Carries the averaged params through ``opt_state``."""

    ema: Any
    count: Any  # int32 scalar: update count, for bias correction
    decay: Any  # float32 scalar: the decay the sum was accumulated with


def params_ema(decay: float, debias: bool = True) -> Any:
    """An optax transform tracking an EMA of the POST-update params.

    Chain it after the real optimizer: the incoming ``updates`` are final
    deltas, so ``params + updates`` is the new weight tensor the average
    should absorb. Updates pass through unchanged.

    ``debias=True`` stores the bias-corrected average (Adam-style
    ``ema / (1 - decay^t)``) lazily at read time via :func:`ema_params`;
    the raw running sum stays in state so the transform itself is a pure
    two-op map.
    """
    import jax
    import jax.numpy as jnp
    import optax

    d = float(decay)
    if not 0.0 < d < 1.0:
        raise ValueError(f"ema decay must be in (0, 1), got {decay}")

    def init_fn(params: Any) -> EmaState:
        # Start from zeros so debiasing is exact from step one (with
        # debias off, start from the initial params instead).
        zero = jax.tree_util.tree_map(
            jnp.zeros_like if debias else (lambda p: p), params
        )
        return EmaState(
            ema=zero,
            count=jnp.zeros((), jnp.int32),
            decay=jnp.asarray(d, jnp.float32),
        )

    def update_fn(updates: Any, state: EmaState, params: Any = None) -> Any:
        if params is None:
            raise ValueError("params_ema requires params in tx.update(...)")
        new_params = optax.apply_updates(params, updates)
        ema = jax.tree_util.tree_map(
            lambda e, p: d * e + (1.0 - d) * p, state.ema, new_params
        )
        return updates, EmaState(
            ema=ema, count=state.count + 1, decay=state.decay
        )

    return optax.GradientTransformation(init_fn, update_fn)


def find_ema_state(opt_state: Any) -> Optional[EmaState]:
    """Locate the :class:`EmaState` inside an arbitrary optimizer-state
    pytree (chain tuples, MultiSteps wrappers, ...)."""
    if isinstance(opt_state, EmaState):
        return opt_state
    if isinstance(opt_state, (tuple, list)):
        # NamedTuple wrappers (chain tuples, optax.MultiStepsState) are
        # tuples too, so this iteration reaches nested fields like
        # MultiSteps' inner_opt_state without special cases.
        for item in opt_state:
            found = find_ema_state(item)
            if found is not None:
                return found
    return None


def ema_params(
    opt_state: Any, decay: Optional[float] = None, debias: bool = True
) -> Optional[Any]:
    """Extract (and debias) the averaged params from ``opt_state``.

    ``decay=None`` uses the decay stored in the state (the one the sum was
    actually accumulated with). Returns None when no EMA transform is
    present or no update has been applied yet.
    """
    import jax
    import numpy as np

    state = find_ema_state(opt_state)
    if state is None:
        return None
    # .ravel()[0]: these may arrive as 0-d or replicated 1-d arrays; plain
    # int()/float() on an ndim>0 array is a NumPy deprecation.
    count = int(np.asarray(jax.device_get(state.count)).ravel()[0])
    if count == 0:
        return None
    if not debias:
        return state.ema
    if decay is None:
        decay = float(np.asarray(jax.device_get(state.decay)).ravel()[0])
    correction = 1.0 - float(decay) ** count
    return jax.tree_util.tree_map(lambda e: e / correction, state.ema)
