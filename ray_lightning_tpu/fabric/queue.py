"""Cross-process queue for worker -> driver messaging.

Fabric-native stand-in for ``ray.util.queue.Queue`` — the channel the
reference uses to ship Tune callback closures from worker rank 0 back to the
trial driver (ray_launcher.py:101-103, session.py:17-24, util.py:49-54).
Backed by a multiprocessing.Manager queue so the proxy is picklable and can be
handed to actors at spawn time.
"""
from __future__ import annotations

import queue as _queue
from typing import Any, Optional

from ray_lightning_tpu.fabric import core


class Queue:
    def __new__(cls, maxsize: int = 0):
        # Client mode: the queue must live on the head so workers there can
        # reach it; hand back the RPC-backed proxy instead.
        if cls is Queue and core._client_mode() is not None:
            from ray_lightning_tpu.fabric.client import ClientQueue

            return ClientQueue(maxsize)
        return super().__new__(cls)

    def __init__(self, maxsize: int = 0) -> None:
        sess = core._require_session()
        self._q = sess.manager.Queue(maxsize)
        self._closed = False

    def put(self, item: Any, block: bool = True, timeout: Optional[float] = None) -> None:
        # cloudpickle framing: Manager queues use plain pickle internally,
        # which rejects the closures/lambdas this channel exists to carry
        # (tune report closures, session.py contract).
        import cloudpickle

        self._q.put(cloudpickle.dumps(item), block, timeout)

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        import cloudpickle

        return cloudpickle.loads(self._q.get(block, timeout))

    def get_nowait(self) -> Any:
        import cloudpickle

        return cloudpickle.loads(self._q.get_nowait())

    def empty(self) -> bool:
        try:
            return self._q.empty()
        except (EOFError, BrokenPipeError, ConnectionError):
            return True

    def qsize(self) -> int:
        return self._q.qsize()

    def shutdown(self) -> None:
        # Manager-backed queues are reclaimed with the manager; just mark closed.
        self._closed = True

    def __getstate__(self) -> dict:
        return {"_q": self._q, "_closed": self._closed}

    def __setstate__(self, state: dict) -> None:
        self.__dict__.update(state)


Empty = _queue.Empty
Full = _queue.Full
