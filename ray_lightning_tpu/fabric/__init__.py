"""ray_lightning_tpu.fabric — a from-scratch actor/process launch fabric.

The reference delegates process launch, object transport, and driver<->worker
messaging to Ray core (C++ raylet + plasma object store; see SURVEY.md §2b and
/root/reference/ray_lightning/launchers/ray_launcher.py:105-114,235). This
module is the TPU build's native equivalent: a minimal actor system built on
OS processes, shared-memory object transport, and logical multi-node resource
scheduling. It deliberately exposes a Ray-like surface (``remote``, ``get``,
``put``, ``wait``, ``kill``) so the launcher layer reads like the reference
architecture while being a fully independent implementation.

Key properties:
- Actors are spawned processes; env vars (XLA flags, TPU topology) are applied
  in the child *before* any heavy import, so each actor can own its own XLA
  runtime configuration.
- ``put`` serializes through POSIX shared memory for zero-copy transport of
  model pytrees to workers on the same host (the C++ arena store in ``csrc/``
  accelerates large buffers; pure-Python fallback always available).
- Logical nodes with resource capacities ({"CPU": n, "TPU": n, custom}) enable
  fake multi-node clusters for tests, mirroring ``ray.cluster_utils.Cluster``
  usage in the reference test suite (test_ddp.py:54-61).
"""
from ray_lightning_tpu.fabric.core import (
    ActorDiedError,
    ActorHandle,
    FabricError,
    InsufficientResourcesError,
    ObjectRef,
    TaskRef,
    available_resources,
    cluster_resources,
    free,
    get,
    heartbeats,
    init,
    is_initialized,
    kill,
    nodes,
    placement_group,
    PlacementGroup,
    put,
    remote,
    remove_placement_group,
    shutdown,
    wait,
)
from ray_lightning_tpu.fabric.queue import Queue
from ray_lightning_tpu.fabric import cluster_utils

__all__ = [
    "init",
    "shutdown",
    "is_initialized",
    "remote",
    "get",
    "heartbeats",
    "put",
    "free",
    "wait",
    "kill",
    "nodes",
    "placement_group",
    "remove_placement_group",
    "PlacementGroup",
    "available_resources",
    "cluster_resources",
    "ObjectRef",
    "TaskRef",
    "ActorHandle",
    "Queue",
    "ActorDiedError",
    "FabricError",
    "InsufficientResourcesError",
    "cluster_utils",
]
