"""Fabric head server: serves a local fabric session to remote clients.

Parity target: the Ray Client server the reference leans on for its
"infinite laptop" workflow (`ray_start_client_server` in
/root/reference/ray_lightning/tests/test_client.py:9-14 and
``ray.init(address=...)`` at launchers/ray_launcher.py:41-42). A thin
request/response protocol over ``multiprocessing.connection`` (TCP +
authkey): the head owns the actors, shm object store, and queues; clients
drive them remotely through ``fabric.init(address="host:port")``.

Run standalone:  python -m ray_lightning_tpu.fabric.server --port 0 --num-cpus 4

Wire protocol (cloudpickle payloads; one request -> one response per client
thread, so a slow ``get`` never blocks other clients — each client opens its
own connection):
  ("spawn", blob, opts)        -> ("ok", actor_id)
  ("call", actor_id, blob)     -> ("ok", call_id)
  ("get", ref, timeout)        -> ("ok", value) | ("timeout",) | ("err", exc)
  ("wait", refs, n, timeout)   -> ("ok", (done_refs, pending_refs))
  ("put", payload_blob)        -> ("ok", ObjectRef)
  ("free", [refs])             -> ("ok", None)
  ("kill", actor_id)           -> ("ok", None)
  ("nodes" | "cluster_resources" | "available_resources") -> ("ok", value)
  ("queue_create", maxsize)    -> ("ok", (qid, proxy_blob))
  ("queue_op", qid, op, args)  -> ("ok", value) | ("err", exc)
  ("queue_delete", qid)        -> ("ok", None)
  ("actor_meta", actor_id)     -> ("ok", {node_id, node_ip, ...})
"""
from __future__ import annotations

import threading
import traceback
from typing import Any, Dict, Optional

import cloudpickle


def _env_authkey() -> Optional[bytes]:
    import os

    key = os.environ.get("RLT_FABRIC_AUTHKEY")
    return key.encode() if key else None


class FabricServer:
    """Owns a real local fabric session and serves it over a socket.

    Authentication: a shared secret over ``multiprocessing.connection``'s
    HMAC challenge. Resolution order: explicit ``authkey`` ctor arg, then
    ``RLT_FABRIC_AUTHKEY``, else a per-server random key is GENERATED
    (``secrets.token_hex``) and printed with the ready line — out of the
    box, a process that can merely reach the port no longer owns the
    fabric (Jupyter-token model).
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        authkey: Optional[bytes] = None,
    ) -> None:
        from multiprocessing.connection import Listener

        from ray_lightning_tpu.fabric import core

        # Only tear down the session at shutdown if this server created it;
        # when embedded next to an existing local session, stopping the
        # server must not kill the host process's actors/object store.
        self._owns_session = not core.is_initialized()
        if self._owns_session:
            core.init()
        key = authkey if authkey is not None else _env_authkey()
        if key is not None and not key:
            raise ValueError("authkey must be non-empty")
        self.authkey_generated = key is None
        if key is None:
            import secrets

            key = secrets.token_hex(16).encode()
        # Printable form for the ready line. Generated keys are always
        # hex; an operator-passed binary key stays usable (it is never
        # echoed) and only its display form is escaped.
        self.authkey = key.decode("utf-8", "backslashreplace")
        self._listener = Listener(
            address=(host, port), family="AF_INET", authkey=key
        )
        self.address = f"{self._listener.address[0]}:{self._listener.address[1]}"
        self._queues: Dict[str, Any] = {}
        self._actors: Dict[str, Any] = {}
        self._pgs: Dict[str, Any] = {}
        self._stop = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    def serve_forever(self) -> None:
        self.start()
        try:
            while not self._stop.is_set():
                self._stop.wait(0.5)
        finally:
            self.shutdown()

    def start(self) -> None:
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="fabric-server-accept", daemon=True
        )
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        from multiprocessing import AuthenticationError

        while not self._stop.is_set():
            try:
                conn = self._listener.accept()
            except (AuthenticationError, EOFError, ConnectionError):
                # Bad key, port scanner, or half-open handshake: the
                # misbehaving CLIENT must not kill the server — drop the
                # connection and keep listening.
                continue
            except OSError:
                if self._stop.is_set():
                    break  # listener closed by shutdown()
                # Transient socket error: back off briefly so a dead
                # listener cannot spin this loop hot.
                self._stop.wait(0.1)
                continue
            t = threading.Thread(
                target=self._client_loop, args=(conn,), daemon=True
            )
            t.start()

    def shutdown(self) -> None:
        from ray_lightning_tpu.fabric import core

        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        if self._owns_session:
            core.shutdown()

    # ------------------------------------------------------------------
    def _client_loop(self, conn: Any) -> None:
        while not self._stop.is_set():
            try:
                msg = cloudpickle.loads(conn.recv_bytes())
            except (EOFError, OSError):
                break
            try:
                resp = self._handle(msg)
            except BaseException as exc:  # noqa: BLE001 - ship to client
                resp = ("err", _exc_for_wire(exc))
            try:
                conn.send_bytes(cloudpickle.dumps(resp, protocol=5))
            except (OSError, BrokenPipeError):
                break
        try:
            conn.close()
        except OSError:
            pass

    # ------------------------------------------------------------------
    def _handle(self, msg: Any) -> Any:
        from ray_lightning_tpu.fabric import core

        kind = msg[0]
        if kind == "spawn":
            _, blob, opts = msg
            cls, args, kwargs = cloudpickle.loads(blob)
            opts = dict(opts)
            # Clients reference placement groups by id (the server-side
            # PlacementGroup holds live Node objects and never crosses the
            # wire); resolve to the registered object before scheduling.
            pg_id = opts.pop("__pg_id__", None)
            if pg_id is not None:
                pg = self._pgs.get(pg_id)
                if pg is None:
                    raise core.FabricError(f"unknown placement group {pg_id}")
                opts["placement_group"] = pg
            handle = core.remote(cls).options(**opts).remote(*args, **kwargs)
            self._actors[handle.actor_id] = handle
            return ("ok", handle.actor_id)
        if kind == "call":
            _, actor_id, blob = msg
            handle = self._actors.get(actor_id)
            if handle is None:
                raise core.ActorDiedError(f"unknown actor {actor_id}")
            name, args, kwargs = cloudpickle.loads(blob)
            ref = getattr(handle, name).remote(*args, **kwargs)
            return ("ok", ref.call_id)
        if kind == "get":
            _, ref, timeout = msg
            try:
                return ("ok", core.get(ref, timeout=timeout))
            except TimeoutError:
                return ("timeout",)
        if kind == "wait":
            _, refs, num_returns, timeout = msg
            done, pending = core.wait(
                refs, num_returns=num_returns, timeout=timeout
            )
            return ("ok", (done, pending))
        if kind == "put":
            _, blob = msg
            return ("ok", core.put(cloudpickle.loads(blob)))
        if kind == "free":
            _, refs = msg
            core.free(refs)
            return ("ok", None)
        if kind == "kill":
            _, actor_id = msg
            handle = self._actors.pop(actor_id, None)
            if handle is not None:
                core.kill(handle)
            return ("ok", None)
        if kind == "pg_create":
            _, bundles, strategy = msg
            pg = core.placement_group(bundles, strategy=strategy)
            self._pgs[pg.id] = pg
            return ("ok", (pg.id, pg.bundle_node_ids))
        if kind == "pg_remove":
            _, pg_id = msg
            pg = self._pgs.pop(pg_id, None)
            if pg is not None:
                core.remove_placement_group(pg)
            return ("ok", None)
        if kind == "nodes":
            return ("ok", core.nodes())
        if kind == "cluster_resources":
            return ("ok", core.cluster_resources())
        if kind == "available_resources":
            return ("ok", core.available_resources())
        if kind == "actor_meta":
            _, actor_id = msg
            handle = self._actors.get(actor_id)
            if handle is None:
                raise core.ActorDiedError(f"unknown actor {actor_id}")
            return (
                "ok",
                {
                    "node_id": handle.node_id,
                    "node_ip": handle.node_ip,
                    "allocated_resources": handle.allocated_resources,
                    "actor_options": handle.actor_options,
                    "is_alive": handle.is_alive(),
                },
            )
        if kind == "queue_create":
            import uuid

            from ray_lightning_tpu.fabric.queue import Queue

            qid = uuid.uuid4().hex[:12]
            q = Queue(msg[1] if len(msg) > 1 else 0)
            self._queues[qid] = q
            # Ship the manager-proxy state so server-spawned workers (which
            # carry the server's mp authkey) can use the queue directly.
            proxy_blob = cloudpickle.dumps(q, protocol=5)
            return ("ok", (qid, proxy_blob))
        if kind == "queue_op":
            _, qid, op, args = msg
            q = self._queues[qid]
            return ("ok", getattr(q, op)(*args))
        if kind == "queue_delete":
            _, qid = msg
            q = self._queues.pop(qid, None)
            if q is not None:
                q.shutdown()
            return ("ok", None)
        if kind == "ping":
            return ("ok", "pong")
        raise ValueError(f"unknown request {kind!r}")


def _exc_for_wire(exc: BaseException) -> BaseException:
    try:
        cloudpickle.dumps(exc)
        return exc
    except Exception:  # noqa: BLE001
        return RuntimeError(
            f"{type(exc).__name__}: {exc}\n{traceback.format_exc()}"
        )


def main(argv: Any = None) -> None:
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--num-cpus", type=float, default=None)
    parser.add_argument("--num-tpus", type=float, default=None)
    args = parser.parse_args(argv)

    from ray_lightning_tpu.fabric import core

    core.init(num_cpus=args.num_cpus, num_tpus=args.num_tpus)
    server = FabricServer(host=args.host, port=args.port)
    # Parseable ready line for launch scripts/tests. A GENERATED key is
    # printed so the operator can hand it to clients (Jupyter-token
    # model); an operator-provided key (env/ctor) is never echoed.
    if server.authkey_generated:
        print(
            f"FABRIC_SERVER_READY {server.address} key={server.authkey}",
            flush=True,
        )
    else:
        print(f"FABRIC_SERVER_READY {server.address}", flush=True)
    server.serve_forever()


if __name__ == "__main__":
    main()
