"""Fake multi-node clusters for tests.

Equivalent of ``ray.cluster_utils.Cluster`` as used by the reference's test
suite to simulate two nodes in one process (test_ddp.py:54-61). Nodes are
logical: every actor still runs on this machine, but scheduling, node IPs, and
rank math behave as if the cluster had multiple hosts — which is exactly what
the launcher's global->(local, node) rank mapping needs for coverage.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from ray_lightning_tpu.fabric import core


class Cluster:
    def __init__(
        self,
        initialize_head: bool = True,
        head_node_args: Optional[Dict[str, Any]] = None,
    ) -> None:
        self._nodes: List[core.Node] = []
        if initialize_head:
            args = dict(head_node_args or {})
            if core.is_initialized() and args:
                raise core.FabricError(
                    "fabric is already initialized; head_node_args would be "
                    "ignored — call fabric.shutdown() first"
                )
            core.init(
                num_cpus=args.get("num_cpus"),
                num_tpus=args.get("num_tpus"),
                resources=args.get("resources"),
            )
            sess = core._require_session()
            self._nodes.append(sess.nodes[0])

    def add_node(
        self,
        num_cpus: float = 1,
        num_tpus: float = 0,
        resources: Optional[Dict[str, float]] = None,
        node_ip: Optional[str] = None,
    ) -> core.Node:
        capacity: Dict[str, float] = {"CPU": float(num_cpus)}
        if num_tpus:
            capacity["TPU"] = float(num_tpus)
        for k, v in (resources or {}).items():
            capacity[k] = float(v)
        node = core._add_node(capacity, node_ip=node_ip)
        self._nodes.append(node)
        return node

    def shutdown(self) -> None:
        core.shutdown()
