"""Fabric core: session, logical nodes, object store, actors, futures.

Native replacement for the Ray-core features the reference consumes
(SURVEY.md §2b): actor creation with per-worker resources
(ray_launcher.py:105-114), ``ray.put`` model shipping (:235), ``ray.get`` /
``ray.wait`` driver loops (util.py:57-70), and ``ray.kill(no_restart=True)``
teardown (:125-127). Implementation is process-based and from scratch.
"""
from __future__ import annotations

import atexit
import itertools
import multiprocessing as mp
import os
import threading
import time
import uuid
from dataclasses import dataclass, field
from multiprocessing import shared_memory
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import cloudpickle

from ray_lightning_tpu.utils.ports import get_node_ip


def _record_event(name: str, level: str = "info", **kv: Any) -> None:
    """Driver-side actor lifecycle into the process event log
    (obs.events) — best-effort: the reader threads also reach here
    during interpreter teardown, where imports can fail."""
    try:
        from ray_lightning_tpu.obs.events import get_event_log

        get_event_log().record("fabric", name, level=level, **kv)
    except Exception:  # noqa: BLE001 - forensics must never break fabric
        pass


class FabricError(RuntimeError):
    pass


class InsufficientResourcesError(FabricError):
    pass


class ActorDiedError(FabricError):
    pass


# --------------------------------------------------------------------------
# Logical nodes & resources
# --------------------------------------------------------------------------
@dataclass
class Node:
    node_id: str
    node_ip: str
    capacity: Dict[str, float]
    used: Dict[str, float] = field(default_factory=dict)

    def available(self) -> Dict[str, float]:
        return {
            k: self.capacity.get(k, 0.0) - self.used.get(k, 0.0)
            for k in self.capacity
        }

    def fits(self, req: Dict[str, float]) -> bool:
        avail = self.available()
        return all(avail.get(k, 0.0) >= v - 1e-9 for k, v in req.items() if v)

    def acquire(self, req: Dict[str, float]) -> None:
        for k, v in req.items():
            if v:
                self.used[k] = self.used.get(k, 0.0) + v

    def release(self, req: Dict[str, float]) -> None:
        for k, v in req.items():
            if v:
                self.used[k] = max(0.0, self.used.get(k, 0.0) - v)


def _detect_local_capacity() -> Dict[str, float]:
    cap = _detect_local_capacity_inner()
    if not cap.get("TPU") and os.environ.get("RLT_REQUIRE_TPU") == "1":
        # Benchmarks set this so a failed probe is a hard error, never a
        # silent fall-back onto CPU that records a bogus number.
        raise FabricError(
            "RLT_REQUIRE_TPU=1 but no TPU chips detected (probe failed or "
            "none visible); set RLT_NUM_TPU_CHIPS to override"
        )
    return cap


def _detect_local_capacity_inner() -> Dict[str, float]:
    cap: Dict[str, float] = {"CPU": float(os.cpu_count() or 1)}
    # TPU chips: respect an explicit override (set by tests / TPU VM metadata);
    # otherwise probe lazily via jax only if it is already imported, to keep
    # fabric.init() cheap on the driver (which may have no accelerator).
    env_chips = os.environ.get("RLT_NUM_TPU_CHIPS")
    if env_chips is not None:
        cap["TPU"] = float(env_chips)
        return cap
    # Fast path: an explicit JAX_PLATFORMS that excludes TPU backends means
    # no chips without any probe (the common test configuration).
    platforms = os.environ.get("JAX_PLATFORMS", "")
    if platforms and not any(
        p.strip() in ("tpu", "axon") for p in platforms.split(",")
    ):
        return cap
    # Otherwise count chips in a short-lived subprocess: initializing the
    # TPU runtime in the *driver* would hold the host's chips for the whole
    # process lifetime (libtpu is exclusive), starving the worker actors —
    # and can hang outright if the device service is wedged, hence the
    # timeout. Set RLT_NUM_TPU_CHIPS=0 to skip the probe entirely.
    try:
        import subprocess
        import sys as _sys

        out = subprocess.run(
            [
                _sys.executable,
                "-c",
                "import jax; print(len([d for d in jax.devices() if d.platform=='tpu']))",
            ],
            capture_output=True,
            timeout=90,
            text=True,
        )
        chips = int(out.stdout.strip().splitlines()[-1]) if out.returncode == 0 else 0
        if chips:
            cap["TPU"] = float(chips)
    except Exception:  # noqa: BLE001 - probe failure means no TPUs visible
        import warnings

        warnings.warn(
            "TPU probe subprocess failed or timed out; assuming no TPU chips. "
            "Set RLT_NUM_TPU_CHIPS to override.",
            stacklevel=2,
        )
    return cap


# --------------------------------------------------------------------------
# Session
# --------------------------------------------------------------------------
class _Session:
    # Retained finished-call results per session: enough for any realistic
    # set of simultaneously-live futures, bounded so a long Tuner run's
    # completed calls don't accumulate forever.
    RESULTS_CAP = int(os.environ.get("RLT_FABRIC_RESULTS_CAP", "4096"))

    def __init__(self) -> None:
        from collections import OrderedDict

        self.nodes: List[Node] = []
        self.actors: Dict[str, "ActorHandle"] = {}
        self.store: Dict[str, Tuple[shared_memory.SharedMemory, int]] = {}
        self.lock = threading.RLock()
        self.cv = threading.Condition(self.lock)
        self.results: "OrderedDict[Tuple[str, int], Tuple[bool, Any]]" = (
            OrderedDict()
        )
        # Keys evicted from `results` (bounded ring): lets get()/wait() on a
        # stale ref fail loudly instead of blocking forever.
        self.evicted: "OrderedDict[Tuple[str, int], None]" = OrderedDict()
        self.dead_actors: Dict[str, str] = {}  # actor_id -> reason
        self.mp_ctx = mp.get_context("spawn")
        self._manager: Optional[Any] = None
        self._counter = itertools.count()

    def add_result(self, key: Tuple[str, int], value: Tuple[bool, Any]) -> None:
        """Record a call result, evicting the oldest beyond RESULTS_CAP.

        Results stay cached so repeated get()/wait() on the same ref keep
        working (Ray-like contract; the driver poll loop re-waits refs);
        the cap bounds growth — refs are consumed promptly in practice, so
        evicting ancient entries is safe."""
        self.results[key] = value
        while len(self.results) > self.RESULTS_CAP:
            old_key, _ = self.results.popitem(last=False)
            self.evicted[old_key] = None
            while len(self.evicted) > 4 * self.RESULTS_CAP:
                self.evicted.popitem(last=False)

    @property
    def manager(self):
        if self._manager is None:
            self._manager = self.mp_ctx.Manager()
        return self._manager

    def next_id(self) -> int:
        return next(self._counter)


_session: Optional[_Session] = None


def _client_mode():
    """The connected FabricClient module, or None (local mode)."""
    from ray_lightning_tpu.fabric import client

    return client if client.is_connected() else None


def is_initialized() -> bool:
    return _session is not None or _client_mode() is not None


def init(
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
    ignore_reinit_error: bool = True,
    address: Optional[str] = None,
    authkey: Optional[str] = None,
) -> None:
    """Start the fabric session with a single local head node.

    ``resources`` adds custom logical resources (the reference tests this
    passthrough with ``ray.init(resources={"extra": 4})``, test_ddp.py:34-39).
    ``address="host:port"`` enters client mode: connect to a remote
    :class:`~ray_lightning_tpu.fabric.server.FabricServer` head and proxy
    every fabric call there (the Ray Client "infinite laptop" analog,
    reference test_client.py:17-30). ``authkey`` is the server's shared
    secret (from its ready line or its ``RLT_FABRIC_AUTHKEY``); defaults
    to this process's ``RLT_FABRIC_AUTHKEY``.
    """
    global _session
    if address is not None:
        from ray_lightning_tpu.fabric import client

        client.connect(address, authkey=authkey)
        return
    if _client_mode() is not None:
        return  # already connected to a head; local init is a no-op
    if _session is not None:
        if ignore_reinit_error:
            return
        raise FabricError("fabric already initialized")
    # Detect BEFORE publishing the session: if detection raises (e.g.
    # RLT_REQUIRE_TPU with a wedged probe), no half-built session must
    # linger — a retrying caller would otherwise hit the reinit fast-path
    # and silently run with zero resources.
    cap = _detect_local_capacity()
    if num_cpus is not None:
        cap["CPU"] = float(num_cpus)
    if num_tpus is not None:
        cap["TPU"] = float(num_tpus)
    if resources:
        cap.update({k: float(v) for k, v in resources.items()})
    session = _Session()
    session.nodes.append(Node("node-0", get_node_ip(), cap))
    _session = session


def _require_session() -> _Session:
    if _session is None:
        init()
    assert _session is not None
    return _session


def shutdown() -> None:
    _c = _client_mode()
    if _c is not None:
        from ray_lightning_tpu.fabric import client

        client.disconnect()
        return
    global _session
    if _session is None:
        return
    sess = _session
    with sess.lock:
        handles = list(sess.actors.values())
    for handle in handles:
        try:
            kill(handle)
        except Exception:  # noqa: BLE001
            pass
    for shm, _ in sess.store.values():
        try:
            shm.close()
            shm.unlink()
        except Exception:  # noqa: BLE001
            pass
    sess.store.clear()
    if sess._manager is not None:
        try:
            sess._manager.shutdown()
        except Exception:  # noqa: BLE001
            pass
    _session = None


atexit.register(shutdown)


def _add_node(capacity: Dict[str, float], node_ip: Optional[str] = None) -> Node:
    """Register an extra logical node (used by cluster_utils for fake clusters)."""
    sess = _require_session()
    with sess.lock:
        node_id = f"node-{len(sess.nodes)}"
        ip = node_ip or f"10.77.{len(sess.nodes)}.1"
        node = Node(node_id, ip, dict(capacity))
        sess.nodes.append(node)
        return node


def nodes() -> List[Dict[str, Any]]:
    _c = _client_mode()
    if _c is not None:
        return _c.nodes()
    sess = _require_session()
    with sess.lock:
        return [
            {
                "NodeID": n.node_id,
                "NodeManagerAddress": n.node_ip,
                "Resources": dict(n.capacity),
                "Available": n.available(),
                "alive": True,
            }
            for n in sess.nodes
        ]


def cluster_resources() -> Dict[str, float]:
    _c = _client_mode()
    if _c is not None:
        return _c.cluster_resources()
    sess = _require_session()
    with sess.lock:
        total: Dict[str, float] = {}
        for n in sess.nodes:
            for k, v in n.capacity.items():
                total[k] = total.get(k, 0.0) + v
        return total


def available_resources() -> Dict[str, float]:
    _c = _client_mode()
    if _c is not None:
        return _c.available_resources()
    sess = _require_session()
    with sess.lock:
        total: Dict[str, float] = {}
        for n in sess.nodes:
            for k, v in n.available().items():
                total[k] = total.get(k, 0.0) + v
        return total


def heartbeats() -> Dict[str, Dict[str, Any]]:
    """Latest heartbeat per live actor: worker-pushed process stats
    (rss_bytes, cpu_s, uptime_s, calls_handled, calls_in_flight,
    last_call_age_s) plus the driver-side ``age_s`` of the push. Workers
    push every ``RLT_HEARTBEAT_S`` seconds (default 10; <= 0 disables),
    so an empty dict just means no interval has elapsed yet.
    ``obs.heartbeats_to_registry`` folds this into Prometheus gauges."""
    if _session is None:
        return {}
    with _session.cv:
        handles = list(_session.actors.values())
    now = time.monotonic()
    out: Dict[str, Dict[str, Any]] = {}
    for h in handles:
        hb = h._last_heartbeat
        if hb is None:
            continue
        t_recv, stats = hb
        entry = dict(stats)
        entry["age_s"] = round(now - t_recv, 3)
        out[h.actor_id] = entry
    return out


# --------------------------------------------------------------------------
# Placement groups (gang scheduling)
# --------------------------------------------------------------------------
@dataclass
class _Bundle:
    """One reserved resource bundle of a placement group."""

    index: int
    request: Dict[str, float]
    node: Node
    remaining: Dict[str, float]


class PlacementGroup:
    """A gang reservation: N resource bundles acquired atomically.

    The fabric analog of ``ray.util.placement_group`` as the reference's
    Tune integration consumes it (tune.py:50-55: ``PlacementGroupFactory(
    [head] + N*[worker], strategy="PACK")``): the bundles are RESERVED on
    logical nodes at creation; actors then schedule INTO a bundle via
    ``options(placement_group=pg, placement_group_bundle_index=i)``,
    drawing from the reservation instead of free node capacity.

    Strategies (Ray semantics):
      - ``"PACK"``: all bundles on one node when possible, else spill to
        as few nodes as needed (best effort).
      - ``"STRICT_PACK"``: all bundles on one node, or placement fails.
      - ``"SPREAD"``: bundles across distinct nodes where possible.
    """

    def __init__(self, pg_id: str, bundles: List[_Bundle], strategy: str):
        self.id = pg_id
        self.bundles = bundles
        self.strategy = strategy
        self.removed = False

    @property
    def bundle_node_ids(self) -> List[str]:
        return [b.node.node_id for b in self.bundles]


def placement_group(
    bundles: List[Dict[str, float]], strategy: str = "PACK"
) -> Any:
    """Atomically reserve ``bundles`` on the cluster's logical nodes.

    Raises :class:`InsufficientResourcesError` when the bundles cannot be
    placed under ``strategy`` with current availability (nothing is leaked:
    partial acquisitions roll back). In client mode the reservation lives
    on the fabric head and a lightweight proxy is returned."""
    _c = _client_mode()
    if _c is not None:
        return _c.placement_group(bundles, strategy=strategy)
    if strategy not in ("PACK", "STRICT_PACK", "SPREAD"):
        raise ValueError(f"unknown placement strategy {strategy!r}")
    reqs = [
        {k: float(v) for k, v in b.items() if float(v)} for b in bundles
    ]
    if not reqs:
        raise ValueError("placement group needs at least one bundle")
    sess = _require_session()
    with sess.lock:
        total: Dict[str, float] = {}
        for r in reqs:
            for k, v in r.items():
                total[k] = total.get(k, 0.0) + v
        assigned: List[Node] = []
        one_node = (
            next((n for n in sess.nodes if n.fits(total)), None)
            if strategy in ("PACK", "STRICT_PACK")
            else None
        )
        if one_node is not None:
            assigned = [one_node] * len(reqs)
        elif strategy == "STRICT_PACK":
            raise InsufficientResourcesError(
                f"STRICT_PACK placement of {reqs} (total {total}) fits no "
                f"single node; available per node: "
                f"{[n.available() for n in sess.nodes]}"
            )
        else:
            # Greedy spill (PACK) / distribution (SPREAD). Acquire as we
            # assign so same-node bundles see each other's reservations;
            # roll back on failure.
            placed_count: Dict[str, int] = {}
            acquired: List[Tuple[Node, Dict[str, float]]] = []
            try:
                for r in reqs:
                    fitting = [n for n in sess.nodes if n.fits(r)]
                    if not fitting:
                        raise InsufficientResourcesError(
                            f"cannot place bundle {r}; available per node: "
                            f"{[n.available() for n in sess.nodes]}"
                        )
                    key = (
                        min
                        if strategy == "SPREAD"
                        else max
                    )
                    node = key(
                        fitting,
                        key=lambda n: (
                            placed_count.get(n.node_id, 0),
                            # tie-break: keep node order deterministic
                            -sess.nodes.index(n),
                        ),
                    )
                    node.acquire(r)
                    acquired.append((node, r))
                    assigned.append(node)
                    placed_count[node.node_id] = (
                        placed_count.get(node.node_id, 0) + 1
                    )
            except InsufficientResourcesError:
                for node, r in acquired:
                    node.release(r)
                raise
        if one_node is not None:
            for r in reqs:
                one_node.acquire(r)
        pg = PlacementGroup(
            f"pg-{uuid.uuid4().hex[:8]}",
            [
                _Bundle(i, dict(r), node, dict(r))
                for i, (r, node) in enumerate(zip(reqs, assigned))
            ],
            strategy,
        )
        return pg


def remove_placement_group(pg: Any) -> None:
    """Release a placement group's reservations, killing any actors still
    scheduled into its bundles first (Ray semantics: removing a group
    terminates its occupants).

    Ordering matters: releasing node capacity while occupants still hold
    bundle reservations would let a new actor double-book the node — the
    freed CPUs/chips would be promised twice until the occupant died. So
    the group is tombstoned first (new spawns into it fail fast), the
    occupants are killed (their resources return to the bundle, not the
    node), and only then do the bundle reservations go back to the nodes.
    """
    _c = _client_mode()
    if _c is not None:
        _c.remove_placement_group(pg)
        return
    sess = _require_session()
    with sess.lock:
        # Check-and-set under the lock: concurrent removals (user cleanup
        # racing Tuner teardown) must not double-release node capacity.
        if pg.removed:
            return
        pg.removed = True
        bundle_ids = {id(b) for b in pg.bundles}
        occupants = [
            h
            for h in sess.actors.values()
            if h._pg_bundle is not None and id(h._pg_bundle) in bundle_ids
        ]
    for handle in occupants:
        try:
            kill(handle)
        except Exception:  # noqa: BLE001 - the actor may already be dead
            pass
    with sess.lock:
        for b in pg.bundles:
            b.node.release(b.request)
    with sess.cv:
        sess.cv.notify_all()


def _release_actor_resources(handle: "ActorHandle") -> None:
    """Return an actor's resources to its placement-group bundle (if it was
    gang-scheduled) or to its node's free pool. Caller holds sess.lock."""
    bundle = handle._pg_bundle
    if bundle is not None:
        for k, v in handle._request.items():
            if v:
                bundle.remaining[k] = bundle.remaining.get(k, 0.0) + v
    else:
        handle._node.release(handle._request)


# --------------------------------------------------------------------------
# Object store (shared memory)
# --------------------------------------------------------------------------
@dataclass(frozen=True)
class ObjectRef:
    """Reference to an object in the driver's shared-memory store.

    Picklable: workers receiving a ref attach to the shm segment by name and
    deserialize in place — the fabric equivalent of plasma-store transport
    behind ``ray.put`` (ray_launcher.py:235).
    """

    id: str
    shm_name: str
    size: int

    def __reduce__(self):
        return (_objectref_from_wire, (self.id, self.shm_name, self.size))


def _objectref_from_wire(id: str, shm_name: str, size: int) -> "ObjectRef":
    return ObjectRef(id=id, shm_name=shm_name, size=size)


def put(obj: Any) -> ObjectRef:
    _c = _client_mode()
    if _c is not None:
        return _c.put(obj)
    sess = _require_session()
    payload = cloudpickle.dumps(obj, protocol=5)
    ref_id = uuid.uuid4().hex[:16]
    shm = shared_memory.SharedMemory(create=True, size=max(1, len(payload)))
    shm.buf[: len(payload)] = payload
    with sess.lock:
        sess.store[ref_id] = (shm, len(payload))
    return ObjectRef(id=ref_id, shm_name=shm.name, size=len(payload))


def _get_object(ref: ObjectRef) -> Any:
    sess = _session
    if sess is not None:
        with sess.lock:
            entry = sess.store.get(ref.id)
        if entry is not None:
            shm, size = entry
            return cloudpickle.loads(bytes(shm.buf[:size]))
    # Not the owner (we are inside a worker): attach read-only by name.
    shm = shared_memory.SharedMemory(name=ref.shm_name)
    try:
        # Python <=3.12 registers ATTACHED segments with this process's
        # resource_tracker as if it owned them; at worker exit the tracker
        # would then unlink driver-owned segments and print "leaked
        # shared_memory objects" warnings. Deregister — the creating session
        # owns cleanup (free()/shutdown()).
        try:
            from multiprocessing import resource_tracker

            resource_tracker.unregister("/" + ref.shm_name.lstrip("/"), "shared_memory")
        except Exception:  # noqa: BLE001 - tracker API/registration varies
            pass
        return cloudpickle.loads(bytes(shm.buf[: ref.size]))
    finally:
        shm.close()


def free(refs: Sequence[ObjectRef]) -> None:
    _c = _client_mode()
    if _c is not None:
        _c.free(refs)
        return
    sess = _require_session()
    with sess.lock:
        for ref in refs:
            entry = sess.store.pop(ref.id, None)
            if entry is not None:
                shm, _ = entry
                try:
                    shm.close()
                    shm.unlink()
                except Exception:  # noqa: BLE001
                    pass


# --------------------------------------------------------------------------
# Futures
# --------------------------------------------------------------------------
@dataclass(frozen=True)
class TaskRef:
    """Future for an in-flight actor method call."""

    actor_id: str
    call_id: int


def _task_done(sess: _Session, ref: TaskRef) -> bool:
    key = (ref.actor_id, ref.call_id)
    return (
        key in sess.results
        or key in sess.evicted
        or ref.actor_id in sess.dead_actors
    )


def get(refs: Any, timeout: Optional[float] = None) -> Any:
    """Resolve ObjectRef/TaskRef (or a list of them) to values."""
    _c = _client_mode()
    if _c is not None:
        return _c.get(refs, timeout=timeout)
    if isinstance(refs, (list, tuple)):
        return type(refs)(get(r, timeout=timeout) for r in refs)
    if isinstance(refs, ObjectRef):
        return _get_object(refs)
    if not isinstance(refs, TaskRef):
        return refs  # plain value passthrough
    sess = _require_session()
    deadline = None if timeout is None else time.monotonic() + timeout
    with sess.cv:
        while not _task_done(sess, refs):
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                raise TimeoutError("fabric.get timed out")
            sess.cv.wait(timeout=remaining if remaining is not None else 1.0)
        key = (refs.actor_id, refs.call_id)
        if key not in sess.results:
            if key in sess.evicted:
                raise FabricError(
                    f"result for {refs} was evicted from the bounded results "
                    f"cache (RLT_FABRIC_RESULTS_CAP={sess.RESULTS_CAP}) before "
                    "it was consumed; fetch results promptly or raise the cap"
                )
            raise ActorDiedError(
                f"actor {refs.actor_id} died: {sess.dead_actors.get(refs.actor_id)}"
            )
        # Cached (bounded — see _Session.add_result) so repeated get()/wait()
        # on the same ref keep working.
        ok, value = sess.results[key]
    if ok:
        return value
    exc, tb = value
    if hasattr(exc, "add_note"):
        exc.add_note(f"[worker traceback]\n{tb}")
    raise exc


def wait(
    refs: Sequence[TaskRef],
    num_returns: int = 1,
    timeout: Optional[float] = None,
) -> Tuple[List[TaskRef], List[TaskRef]]:
    """Split ``refs`` into (done, pending); blocks until ``num_returns`` done
    or ``timeout`` elapses. ``timeout=0`` polls — the driver's result loop uses
    this exactly like the reference's ``ray.wait(timeout=0)`` poll
    (util.py:57-70)."""
    _c = _client_mode()
    if _c is not None:
        return _c.wait(refs, num_returns=num_returns, timeout=timeout)
    sess = _require_session()
    deadline = None if timeout is None else time.monotonic() + timeout
    with sess.cv:
        while True:
            done = [r for r in refs if _task_done(sess, r)]
            if len(done) >= min(num_returns, len(refs)):
                break
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                break
            sess.cv.wait(timeout=min(0.25, remaining) if remaining is not None else 0.25)
        done_set = {(r.actor_id, r.call_id) for r in done}
        pending = [r for r in refs if (r.actor_id, r.call_id) not in done_set]
    return done, pending


# --------------------------------------------------------------------------
# Actors
# --------------------------------------------------------------------------
class _RemoteMethod:
    def __init__(self, handle: "ActorHandle", name: str) -> None:
        self._handle = handle
        self._name = name

    def remote(self, *args: Any, **kwargs: Any) -> TaskRef:
        return self._handle._call(self._name, args, kwargs)

    def __repr__(self) -> str:
        return f"<RemoteMethod {self._handle.actor_id}.{self._name}>"


class ActorHandle:
    """Driver-side handle to a spawned actor process."""

    def __init__(
        self,
        actor_id: str,
        process: Any,
        conn: Any,
        node: Node,
        request: Dict[str, float],
        options: Dict[str, Any],
        pg_bundle: Optional[_Bundle] = None,
    ) -> None:
        self.actor_id = actor_id
        self._process = process
        self._conn = conn
        self._node = node
        self._request = request
        self._options = options
        self._pg_bundle = pg_bundle
        self._send_lock = threading.Lock()
        self._alive = True
        #: (monotonic receive time, stats dict) of the worker's newest
        #: heartbeat push (fabric/worker.py's heartbeat thread); None
        #: until the first one lands. Read via :func:`heartbeats`.
        self._last_heartbeat: Optional[Tuple[float, Dict[str, Any]]] = None
        self._reader = threading.Thread(
            target=self._reader_loop, name=f"fabric-reader-{actor_id}", daemon=True
        )
        self._reader.start()

    # -- introspection used by tests / launcher ---------------------------
    @property
    def node_id(self) -> str:
        return self._node.node_id

    @property
    def node_ip(self) -> str:
        return self._node.node_ip

    @property
    def allocated_resources(self) -> Dict[str, float]:
        return dict(self._request)

    @property
    def actor_options(self) -> Dict[str, Any]:
        return dict(self._options)

    def is_alive(self) -> bool:
        return self._alive and self._process.is_alive()

    # -- plumbing ---------------------------------------------------------
    def _reader_loop(self) -> None:
        sess = _session
        while True:
            try:
                msg = cloudpickle.loads(self._conn.recv_bytes())
            except (EOFError, OSError):
                break
            except Exception:  # noqa: BLE001 - deserialization failure
                break
            if msg[0] == "result":
                _, call_id, ok, value = msg
                if sess is not None:
                    with sess.cv:
                        sess.add_result((self.actor_id, call_id), (ok, value))
                        sess.cv.notify_all()
            elif msg[0] in ("ready", "ready_error"):
                if sess is not None:
                    with sess.cv:
                        sess.add_result(
                            (self.actor_id, -1), (msg[0] == "ready", msg[1])
                        )
                        sess.cv.notify_all()
            elif msg[0] == "heartbeat":
                # Worker-initiated health push (rss, cpu, call counters):
                # stored on the handle, surfaced via heartbeats() and the
                # obs registry — no call_id, nothing blocks on it.
                self._last_heartbeat = (time.monotonic(), msg[1])
                if msg[1].get("terminating"):
                    # The worker's SIGTERM handler ran: a CLEAN
                    # terminate, distinguishable from a heartbeat
                    # flatline (crash/SIGKILL) in the event log.
                    _record_event(
                        "worker_terminating",
                        actor=self.actor_id,
                        reason=str(msg[1].get("reason", "")),
                    )
        # Pipe closed: mark actor dead so blocked getters wake up, and release
        # its node resources so a relaunch after a crash can be placed.
        self._alive = False
        if sess is not None:
            with sess.cv:
                exitcode = self._process.exitcode
                # Only the FIRST death record is news: kill() already
                # logged an intentional termination.
                fresh = self.actor_id not in sess.dead_actors
                sess.dead_actors.setdefault(
                    self.actor_id, f"process exited (exitcode={exitcode})"
                )
                if sess.actors.pop(self.actor_id, None) is not None:
                    _release_actor_resources(self)
                sess.cv.notify_all()
            if fresh:
                _record_event(
                    "actor_death", level="warn",
                    actor=self.actor_id, exitcode=exitcode,
                )

    def _send(self, msg: Any) -> None:
        if not self._alive:
            raise ActorDiedError(f"actor {self.actor_id} is dead")
        payload = cloudpickle.dumps(msg, protocol=5)
        with self._send_lock:
            self._conn.send_bytes(payload)

    def _call(self, name: str, args: Tuple, kwargs: Dict) -> TaskRef:
        sess = _require_session()
        call_id = sess.next_id()
        blob = cloudpickle.dumps((name, args, kwargs), protocol=5)
        self._send(("call", call_id, blob))
        return TaskRef(actor_id=self.actor_id, call_id=call_id)

    def __getattr__(self, name: str) -> _RemoteMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return _RemoteMethod(self, name)

    def _shutdown(self, force: bool = False) -> None:
        if self._alive:
            try:
                self._send(("shutdown",))
            except Exception:  # noqa: BLE001
                pass
        self._process.join(timeout=0.1 if force else 5.0)
        if self._process.is_alive():
            self._process.terminate()
            # Generous grace: SIGTERM triggers the worker's atexit teardown,
            # which may itself be shutting down nested actors; SIGKILL too
            # early would orphan them.
            self._process.join(timeout=15.0)
            if self._process.is_alive():
                self._process.kill()
                self._process.join(timeout=2.0)
        self._alive = False


class ActorClass:
    """Result of ``fabric.remote(cls)``; spawn with ``.options(...).remote()``."""

    def __init__(self, cls: type, default_options: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._default_options = default_options or {}

    def options(self, **opts: Any) -> "ActorClass":
        merged = dict(self._default_options)
        merged.update(opts)
        return ActorClass(self._cls, merged)

    def remote(self, *args: Any, **kwargs: Any) -> ActorHandle:
        return _spawn_actor(self._cls, args, kwargs, self._default_options)


def remote(cls: type) -> "ActorClass":
    """Decorator/wrapper turning a class into a spawnable actor class."""
    _c = _client_mode()
    if _c is not None:
        return _c.remote(cls)
    return ActorClass(cls)


def _spawn_actor(
    cls: type,
    args: Tuple,
    kwargs: Dict,
    opts: Dict[str, Any],
) -> ActorHandle:
    sess = _require_session()
    request: Dict[str, float] = {}
    request["CPU"] = float(opts.get("num_cpus", 1) or 0)
    if opts.get("num_tpus"):
        request["TPU"] = float(opts["num_tpus"])
    for k, v in (opts.get("resources") or {}).items():
        request[k] = float(v)

    pg: Optional[PlacementGroup] = opts.get("placement_group")
    pg_bundle: Optional[_Bundle] = None
    with sess.lock:
        if pg is not None:
            # Gang-scheduled: draw from the bundle's reservation, land on
            # the bundle's node (Ray's placement_group/bundle_index opts).
            idx = int(opts.get("placement_group_bundle_index", 0))
            if pg.removed:
                raise FabricError(f"placement group {pg.id} was removed")
            if not 0 <= idx < len(pg.bundles):
                raise ValueError(
                    f"bundle index {idx} out of range for {len(pg.bundles)}"
                    " bundles"
                )
            pg_bundle = pg.bundles[idx]
            short = {
                k: v
                for k, v in request.items()
                if v and pg_bundle.remaining.get(k, 0.0) < v - 1e-9
            }
            if short:
                raise InsufficientResourcesError(
                    f"actor requiring {request} does not fit bundle {idx} "
                    f"of {pg.id} (remaining {pg_bundle.remaining})"
                )
            for k, v in request.items():
                if v:
                    pg_bundle.remaining[k] -= v
            node = pg_bundle.node
        else:
            node = None
            for cand in sess.nodes:
                if cand.fits(request):
                    node = cand
                    break
            if node is None:
                raise InsufficientResourcesError(
                    f"cannot place actor requiring {request}; "
                    f"available per node: {[n.available() for n in sess.nodes]}"
                )
            node.acquire(request)

    env = dict(opts.get("env") or {})
    actor_id = f"actor-{uuid.uuid4().hex[:8]}"
    try:
        proc, parent_conn = _boot_worker_process(actor_id, env, node)
    except BaseException:
        # Boot never produced a handle; hand the reservation back directly.
        with sess.lock:
            if pg_bundle is not None:
                for k, v in request.items():
                    if v:
                        pg_bundle.remaining[k] = (
                            pg_bundle.remaining.get(k, 0.0) + v
                        )
            else:
                node.release(request)
        raise
    handle = ActorHandle(
        actor_id, proc, parent_conn, node, request, opts, pg_bundle=pg_bundle
    )
    with sess.lock:
        sess.actors[actor_id] = handle

    # Ship the class + ctor args (after env application in the child).
    blob = cloudpickle.dumps((cls, args, kwargs), protocol=5)
    handle._send(("init", blob))
    if opts.get("lazy_init"):
        # Deferred construction: return the handle NOW and let the
        # caller barrier on readiness itself (a ping). Required for
        # gang spawns whose __init__s rendezvous with EACH OTHER
        # (jax.distributed.initialize blocks until every member
        # registers) — waiting for member 1's ctor before spawning
        # member 2 deadlocks by construction. A failed ctor still
        # surfaces: the worker answers every later call with
        # "actor not initialized", so the readiness ping raises.
        _record_event(
            "actor_start", actor=actor_id, node=node.node_id,
            cls=cls.__name__, lazy=True,
        )
        return handle
    # Wait for construction so init errors surface eagerly on the driver.
    try:
        get(TaskRef(actor_id=actor_id, call_id=-1), timeout=opts.get("init_timeout", 300.0))
    except BaseException:
        kill(handle)
        raise
    _record_event(
        "actor_start", actor=actor_id, node=node.node_id,
        cls=cls.__name__,
    )
    return handle


class _ProcHandle:
    """subprocess.Popen wrapped in the multiprocessing.Process API surface
    ActorHandle expects (is_alive/exitcode/join/terminate/kill)."""

    def __init__(self, popen: Any) -> None:
        self._p = popen

    def is_alive(self) -> bool:
        return self._p.poll() is None

    @property
    def exitcode(self) -> Optional[int]:
        return self._p.poll()

    def join(self, timeout: Optional[float] = None) -> None:
        import subprocess

        try:
            self._p.wait(timeout)
        except subprocess.TimeoutExpired:
            pass

    def terminate(self) -> None:
        self._p.terminate()

    def kill(self) -> None:
        self._p.kill()


def _boot_worker_process(actor_id: str, env: Dict[str, Any], node: Node):
    """Exec a fresh worker interpreter and hand back (process, connection).

    Uses ``python -m ray_lightning_tpu.fabric.worker`` + an AF_UNIX
    Listener — NOT multiprocessing.Process — so the child never replays the
    driver's ``__main__`` module (mp spawn would, re-running unguarded user
    scripts recursively). Env overrides are applied to the exec environment,
    i.e. strictly before the child interpreter (and thus jax) starts.
    """
    import secrets
    import subprocess
    import sys
    from multiprocessing.connection import Listener

    child_env = dict(os.environ)
    # Propagate the driver's import roots (mp spawn used to ship sys.path in
    # its preparation data; exec'd workers need it via PYTHONPATH so classes
    # cloudpickled *by reference* — e.g. from a test module or a script's
    # package — resolve in the child).
    driver_paths = [p for p in sys.path if p]
    inherited = child_env.get("PYTHONPATH", "")
    child_env["PYTHONPATH"] = os.pathsep.join(
        driver_paths + ([inherited] if inherited else [])
    )
    for key, value in env.items():
        if value is None:
            child_env.pop(key, None)
        elif key == "PYTHONPATH":
            # Merge rather than clobber: the driver sys.path entries above
            # are what let by-reference cloudpickles resolve in the child.
            child_env[key] = os.pathsep.join(
                [str(value), child_env.get("PYTHONPATH", "")]
            ).rstrip(os.pathsep)
        else:
            child_env[key] = str(value)
    # Logical node identity for actor code (rank math, IPs).
    child_env["RLT_NODE_ID"] = str(node.node_id)
    child_env["RLT_NODE_IP"] = str(node.node_ip)

    authkey = secrets.token_bytes(32)
    listener = Listener(family="AF_UNIX", authkey=authkey)
    try:
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_lightning_tpu.fabric.worker",
             str(listener.address)],
            env=child_env,
            stdin=subprocess.PIPE,
        )
        proc.stdin.write(authkey.hex().encode() + b"\n")
        # Second line: the driver's multiprocessing authkey. Manager/Queue
        # proxies authenticate with current_process().authkey, which
        # mp.Process children inherit automatically but exec'd workers do
        # not; the worker restores it so driver-owned proxies (tune queues)
        # keep working across any nesting depth.
        proc.stdin.write(mp.current_process().authkey.hex().encode() + b"\n")
        proc.stdin.flush()
        proc.stdin.close()
        # accept() has no timeout; run it in a thread and watch for the
        # child dying pre-connect so a boot crash can't hang the driver.
        box: Dict[str, Any] = {}

        def _accept() -> None:
            try:
                box["conn"] = listener.accept()
            except BaseException as exc:  # noqa: BLE001
                box["err"] = exc

        t = threading.Thread(target=_accept, daemon=True)
        t.start()
        deadline = time.monotonic() + 120.0
        while "conn" not in box and "err" not in box:
            if proc.poll() is not None:
                raise ActorDiedError(
                    f"actor {actor_id} worker process exited during boot "
                    f"(exitcode={proc.returncode})"
                )
            if time.monotonic() > deadline:
                proc.kill()
                raise ActorDiedError(f"actor {actor_id} boot timed out")
            t.join(timeout=0.05)
        if "err" in box:
            proc.kill()
            raise box["err"]
        return _ProcHandle(proc), box["conn"]
    finally:
        listener.close()


def kill(handle: ActorHandle, no_restart: bool = True) -> None:
    """Terminate an actor and release its resources (no restart semantics,
    matching ``ray.kill(no_restart=True)`` in ray_launcher.py:126).

    ``no_restart=False`` is REJECTED loudly: fabric actors have no
    restart machinery (no retained spawn spec, no supervision), so
    silently accepting the flag would promise a restart that never
    comes. Restartable serving replicas are the serve layer's job —
    ``serve.supervisor.FleetSupervisor`` re-runs a dead replica's
    original spawn via ``ServeClient.respawn_replica``.
    """
    if not no_restart:
        raise ValueError(
            "fabric.kill(no_restart=False) is unsupported: fabric actors "
            "are never restarted in place. For restartable serving "
            "replicas use serve.supervisor.FleetSupervisor (which "
            "re-runs the original spawn), then kill with the default "
            "no_restart=True."
        )
    _c = _client_mode()
    if _c is not None:
        _c.kill(handle)
        return
    sess = _require_session()
    # Record the intent BEFORE the process dies, so the reader thread's
    # subsequent death record is recognizably a consequence of this kill.
    if handle._alive:
        _record_event("actor_kill", actor=handle.actor_id)
    handle._shutdown(force=True)
    with sess.lock:
        if handle.actor_id in sess.actors:
            _release_actor_resources(handle)
            del sess.actors[handle.actor_id]
        sess.dead_actors.setdefault(handle.actor_id, "killed")
    with sess.cv:
        sess.cv.notify_all()
