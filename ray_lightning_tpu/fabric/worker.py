"""Child-process entrypoint for fabric actors.

Spawned as ``python -m ray_lightning_tpu.fabric.worker <socket-address>`` by
the driver (NOT via multiprocessing.Process): a fresh interpreter that never
re-imports the user's ``__main__`` module, so unguarded user scripts cannot
recursively re-launch training the way multiprocessing's spawn
``_fixup_main_from_path`` would. This mirrors Ray's worker-process model
(the reference's actors are plain Ray workers, launchers/utils.py:27-52).

Environment overrides (XLA_FLAGS, JAX_PLATFORMS, TPU topology vars) arrive
via the process environment — set by the driver *before* exec, hence before
anything can import jax. The actor class arrives as a cloudpickle blob over
the connection.

Wire protocol (length-prefixed cloudpickle over a Connection):
  driver -> worker: ("init", blob)            instantiate actor class
                    ("call", call_id, blob)   run method, blob=(name, args, kw)
                    ("shutdown",)
  worker -> driver: ("ready", actor_repr)
                    ("result", call_id, ok, blob)  blob=value or (exc, tb_str)
"""
import os
import sys
import traceback


#: Flipped once shutdown begins (normal loop exit or a first SIGTERM).
#: ``kill()`` SIGTERMs shortly after sending the "shutdown" message, so the
#: signal routinely lands while atexit is already running multiprocessing
#: manager finalizers — raising SystemExit there prints a traceback into
#: whatever captures stderr (it half-filled BENCH_r04.json). Once exiting,
#: further SIGTERMs are no-ops.
_EXITING = False

#: Set by _worker_main: pushes one final ("heartbeat", {...,
#: "terminating": True}) frame so the driver can tell a CLEAN terminate
#: (this handler ran) from a heartbeat flatline (the process just
#: vanished). Best-effort: bounded lock wait, every failure swallowed —
#: a wedged connection must not stall the exit the signal asked for.
_TERM_NOTIFY = None


def _on_sigterm(*_):
    global _EXITING
    if _EXITING or sys.is_finalizing():
        return
    _EXITING = True
    if _TERM_NOTIFY is not None:
        try:
            _TERM_NOTIFY()
        except Exception:  # noqa: BLE001 - exit anyway
            pass
    sys.exit(0)


def _install_unraisable_filter():
    """Silence the one benign unraisable: our SIGTERM SystemExit landing
    inside a finalizer/__del__ (e.g. a manager proxy's Finalize _decref
    mid-connection), where Python can only report-and-swallow it. The
    process still exits promptly — kill() sends the "shutdown" message
    before SIGTERM, so the actor loop breaks on its next recv (with
    SIGKILL escalation as the backstop). Everything else chains to the
    default hook."""
    default = sys.unraisablehook

    def hook(args):
        if args.exc_type is SystemExit and _EXITING:
            return
        default(args)

    sys.unraisablehook = hook


def _proc_stats():
    """Process-level stats for one heartbeat: rss, cpu time, uptime."""
    rss = 0
    try:
        with open("/proc/self/statm") as f:
            rss = int(f.read().split()[1]) * (os.sysconf("SC_PAGE_SIZE"))
    except (OSError, ValueError, IndexError):
        try:
            import resource

            # ru_maxrss is KiB on Linux — peak, not current; better than
            # nothing on non-procfs platforms.
            rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        except Exception:  # noqa: BLE001
            pass
    times = os.times()
    return rss, times.user + times.system


def _heartbeat_loop(send, state, interval_s):
    """Daemon thread: push ("heartbeat", stats) to the driver every
    ``interval_s`` until the connection dies. The stats let the driver
    aggregate worker health (rss, cpu, last-call age) into its metrics
    registry without an RPC round trip — and without competing with a
    busy actor loop, which handles calls serially."""
    import time

    import cloudpickle

    while not _EXITING:
        time.sleep(interval_s)
        if _EXITING:
            return
        rss, cpu_s = _proc_stats()
        now = time.monotonic()
        stats = {
            "pid": os.getpid(),
            "rss_bytes": rss,
            "cpu_s": round(cpu_s, 3),
            "uptime_s": round(now - state["t0"], 3),
            "calls_handled": state["calls"],
            "calls_in_flight": state["busy"],
            "last_call_age_s": (
                None
                if state["last_end"] is None
                else round(now - state["last_end"], 3)
            ),
        }
        # Preemption notice piggybacks on the heartbeat: processes with
        # no RPC surface (gang followers) still reach the supervisor.
        # peek_state never CREATES a monitor — an unarmed process pays
        # one None check.
        try:
            from ray_lightning_tpu.serve.preempt import peek_state

            p = peek_state()
            if p and p.get("pending"):
                stats["preempt"] = p
        except Exception:  # noqa: BLE001 - heartbeats must keep flowing
            pass
        try:
            send(cloudpickle.dumps(("heartbeat", stats)))
        except (OSError, ValueError):
            return  # driver gone; the main loop is exiting too


def _worker_main(conn):
    """Run the actor loop. ``conn`` is an authenticated duplex Connection."""
    import signal
    import threading
    import time

    # SIGTERM (e.g. a tuner killing a trial actor) must run atexit so this
    # process's own fabric session shuts down any nested actors it spawned
    # (a trial's training workers) instead of orphaning them.
    signal.signal(signal.SIGTERM, _on_sigterm)
    _install_unraisable_filter()

    # Honor an explicit JAX platform choice even when a PJRT plugin loaded
    # at interpreter boot (sitecustomize) already forced its own config.
    from ray_lightning_tpu.utils.platform import apply_jax_platform_env

    apply_jax_platform_env()

    import cloudpickle  # after env setup; cheap, no jax dependency

    # Heartbeats share the connection with call results; serialize the
    # byte stream (interleaved send_bytes from two threads would corrupt
    # framing). RLT_HEARTBEAT_S <= 0 disables.
    send_lock = threading.Lock()

    def send(payload):
        with send_lock:
            conn.send_bytes(payload)

    hb_state = {"calls": 0, "busy": 0, "last_end": None, "t0": time.monotonic()}

    def _term_notify():
        """The final heartbeat a SIGTERM'd worker pushes before exiting:
        the driver reads ``terminating`` and classifies this death as a
        clean terminate, not a flatline. Lock wait is bounded — the
        heartbeat thread may be mid-send."""
        rss, cpu_s = _proc_stats()
        payload = cloudpickle.dumps((
            "heartbeat",
            {
                "pid": os.getpid(),
                "rss_bytes": rss,
                "cpu_s": round(cpu_s, 3),
                "uptime_s": round(time.monotonic() - hb_state["t0"], 3),
                "calls_handled": hb_state["calls"],
                "calls_in_flight": hb_state["busy"],
                "last_call_age_s": None,
                "terminating": True,
                "reason": "sigterm",
            },
        ))
        if send_lock.acquire(timeout=0.5):
            try:
                conn.send_bytes(payload)
            finally:
                send_lock.release()

    global _TERM_NOTIFY
    _TERM_NOTIFY = _term_notify
    try:
        hb_interval = float(os.environ.get("RLT_HEARTBEAT_S", "10"))
    except ValueError:
        hb_interval = 10.0
    if hb_interval > 0:
        threading.Thread(
            target=_heartbeat_loop,
            args=(send, hb_state, hb_interval),
            name="fabric-heartbeat",
            daemon=True,
        ).start()

    actor = None
    try:
        while True:
            try:
                msg = cloudpickle.loads(conn.recv_bytes())
            except (EOFError, OSError):
                break
            kind = msg[0]
            if kind == "shutdown":
                break
            if kind == "init":
                try:
                    cls, args, kwargs = cloudpickle.loads(msg[1])
                    actor = cls(*args, **kwargs)
                    send(cloudpickle.dumps(("ready", repr(type(actor)))))
                except BaseException as exc:  # noqa: BLE001 - report to driver
                    send(
                        cloudpickle.dumps(
                            ("ready_error", _exc_payload(exc))
                        )
                    )
                continue
            if kind == "call":
                call_id, blob = msg[1], msg[2]
                hb_state["busy"] = 1
                try:
                    name, args, kwargs = cloudpickle.loads(blob)
                    if actor is None:
                        raise RuntimeError("actor not initialized")
                    result = getattr(actor, name)(*args, **kwargs)
                    payload = cloudpickle.dumps(("result", call_id, True, result))
                except (SystemExit, KeyboardInterrupt):
                    # SIGTERM's sys.exit must propagate so the process exits
                    # promptly (running atexit -> nested-actor cleanup)
                    # instead of being reported as a call failure.
                    raise
                except BaseException as exc:  # noqa: BLE001 - ship to driver
                    payload = cloudpickle.dumps(
                        ("result", call_id, False, _exc_payload(exc))
                    )
                finally:
                    hb_state["busy"] = 0
                    hb_state["calls"] += 1
                    hb_state["last_end"] = time.monotonic()
                send(payload)
                continue
    finally:
        global _EXITING
        _EXITING = True  # late SIGTERMs (kill()'s follow-up) are no-ops now
        try:
            conn.close()
        except OSError:
            pass
        # Normal interpreter shutdown (atexit handlers run, letting runtimes
        # like PJRT release device locks cleanly).
        sys.stdout.flush()
        sys.stderr.flush()


def _exc_payload(exc):
    tb = traceback.format_exc()
    try:
        import cloudpickle

        cloudpickle.dumps(exc)  # probe picklability
        return (exc, tb)
    except Exception:  # noqa: BLE001
        return (RuntimeError(f"{type(exc).__name__}: {exc}"), tb)


def main(argv) -> None:
    """``python -m ray_lightning_tpu.fabric.worker <address>`` entrypoint.

    The connection authkey arrives on stdin (hex line) so it never shows in
    ``/proc/*/cmdline`` or the environment.
    """
    import multiprocessing as mp
    from multiprocessing.connection import Client

    address = argv[1]
    authkey = bytes.fromhex(sys.stdin.readline().strip())
    mp_authkey = bytes.fromhex(sys.stdin.readline().strip())
    # Restore the driver's multiprocessing authkey (normally inherited by
    # mp children) so Manager/Queue proxies shipped from the driver
    # authenticate in this process and in any actors it nests.
    mp.current_process().authkey = mp_authkey
    conn = Client(address, authkey=authkey)
    _worker_main(conn)


if __name__ == "__main__":
    main(sys.argv)
