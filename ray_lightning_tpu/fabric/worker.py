"""Child-process entrypoint for fabric actors.

Kept intentionally light: only stdlib imports at module scope, so the spawned
process can apply environment overrides (XLA_FLAGS, JAX_PLATFORMS, TPU
topology vars) *before* anything imports jax. The actor class itself arrives
as a cloudpickle blob after env setup.

Wire protocol (length-prefixed cloudpickle over a duplex Pipe):
  driver -> worker: ("init", blob)            instantiate actor class
                    ("call", call_id, blob)   run method, blob=(name, args, kw)
                    ("shutdown",)
  worker -> driver: ("ready", actor_repr)
                    ("result", call_id, ok, blob)  blob=value or (exc, tb_str)
"""
import os
import sys
import traceback


def _worker_main(conn, env_overrides, node_info):
    """Run the actor loop. ``conn`` is the child end of a duplex Pipe."""
    import signal

    # SIGTERM (e.g. a tuner killing a trial actor) must run atexit so this
    # process's own fabric session shuts down any nested actors it spawned
    # (a trial's training workers) instead of orphaning them.
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))

    for key, value in env_overrides.items():
        if value is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = str(value)

    # Make the logical node identity visible to actor code (rank math, IPs).
    os.environ["RLT_NODE_ID"] = str(node_info.get("node_id", "node-0"))
    os.environ["RLT_NODE_IP"] = str(node_info.get("node_ip", "127.0.0.1"))

    # Honor an explicit JAX platform choice even when a PJRT plugin loaded at
    # interpreter boot (via sitecustomize) has already forced its own
    # ``jax_platforms`` config, which silently overrides the env var.
    if "JAX_PLATFORMS" in env_overrides and env_overrides["JAX_PLATFORMS"]:
        try:
            import jax

            jax.config.update("jax_platforms", str(env_overrides["JAX_PLATFORMS"]))
        except Exception:  # noqa: BLE001 - jax may be absent in pure actors
            pass

    import cloudpickle  # after env setup; cheap, no jax dependency

    actor = None
    try:
        while True:
            try:
                msg = cloudpickle.loads(conn.recv_bytes())
            except (EOFError, OSError):
                break
            kind = msg[0]
            if kind == "shutdown":
                break
            if kind == "init":
                try:
                    cls, args, kwargs = cloudpickle.loads(msg[1])
                    actor = cls(*args, **kwargs)
                    conn.send_bytes(cloudpickle.dumps(("ready", repr(type(actor)))))
                except BaseException as exc:  # noqa: BLE001 - report to driver
                    conn.send_bytes(
                        cloudpickle.dumps(
                            ("ready_error", _exc_payload(exc))
                        )
                    )
                continue
            if kind == "call":
                call_id, blob = msg[1], msg[2]
                try:
                    name, args, kwargs = cloudpickle.loads(blob)
                    if actor is None:
                        raise RuntimeError("actor not initialized")
                    result = getattr(actor, name)(*args, **kwargs)
                    payload = cloudpickle.dumps(("result", call_id, True, result))
                except (SystemExit, KeyboardInterrupt):
                    # SIGTERM's sys.exit must propagate so the process exits
                    # promptly (running atexit -> nested-actor cleanup)
                    # instead of being reported as a call failure.
                    raise
                except BaseException as exc:  # noqa: BLE001 - ship to driver
                    payload = cloudpickle.dumps(
                        ("result", call_id, False, _exc_payload(exc))
                    )
                conn.send_bytes(payload)
                continue
    finally:
        try:
            conn.close()
        except OSError:
            pass
        # Normal interpreter shutdown (atexit handlers run, letting runtimes
        # like PJRT release device locks cleanly).
        sys.stdout.flush()
        sys.stderr.flush()


def _exc_payload(exc):
    tb = traceback.format_exc()
    try:
        import cloudpickle

        cloudpickle.dumps(exc)  # probe picklability
        return (exc, tb)
    except Exception:  # noqa: BLE001
        return (RuntimeError(f"{type(exc).__name__}: {exc}"), tb)
