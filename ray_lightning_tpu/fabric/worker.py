"""Child-process entrypoint for fabric actors.

Spawned as ``python -m ray_lightning_tpu.fabric.worker <socket-address>`` by
the driver (NOT via multiprocessing.Process): a fresh interpreter that never
re-imports the user's ``__main__`` module, so unguarded user scripts cannot
recursively re-launch training the way multiprocessing's spawn
``_fixup_main_from_path`` would. This mirrors Ray's worker-process model
(the reference's actors are plain Ray workers, launchers/utils.py:27-52).

Environment overrides (XLA_FLAGS, JAX_PLATFORMS, TPU topology vars) arrive
via the process environment — set by the driver *before* exec, hence before
anything can import jax. The actor class arrives as a cloudpickle blob over
the connection.

Wire protocol (length-prefixed cloudpickle over a Connection):
  driver -> worker: ("init", blob)            instantiate actor class
                    ("call", call_id, blob)   run method, blob=(name, args, kw)
                    ("shutdown",)
  worker -> driver: ("ready", actor_repr)
                    ("result", call_id, ok, blob)  blob=value or (exc, tb_str)
"""
import os
import sys
import traceback


#: Flipped once shutdown begins (normal loop exit or a first SIGTERM).
#: ``kill()`` SIGTERMs shortly after sending the "shutdown" message, so the
#: signal routinely lands while atexit is already running multiprocessing
#: manager finalizers — raising SystemExit there prints a traceback into
#: whatever captures stderr (it half-filled BENCH_r04.json). Once exiting,
#: further SIGTERMs are no-ops.
_EXITING = False


def _on_sigterm(*_):
    global _EXITING
    if _EXITING or sys.is_finalizing():
        return
    _EXITING = True
    sys.exit(0)


def _install_unraisable_filter():
    """Silence the one benign unraisable: our SIGTERM SystemExit landing
    inside a finalizer/__del__ (e.g. a manager proxy's Finalize _decref
    mid-connection), where Python can only report-and-swallow it. The
    process still exits promptly — kill() sends the "shutdown" message
    before SIGTERM, so the actor loop breaks on its next recv (with
    SIGKILL escalation as the backstop). Everything else chains to the
    default hook."""
    default = sys.unraisablehook

    def hook(args):
        if args.exc_type is SystemExit and _EXITING:
            return
        default(args)

    sys.unraisablehook = hook


def _worker_main(conn):
    """Run the actor loop. ``conn`` is an authenticated duplex Connection."""
    import signal

    # SIGTERM (e.g. a tuner killing a trial actor) must run atexit so this
    # process's own fabric session shuts down any nested actors it spawned
    # (a trial's training workers) instead of orphaning them.
    signal.signal(signal.SIGTERM, _on_sigterm)
    _install_unraisable_filter()

    # Honor an explicit JAX platform choice even when a PJRT plugin loaded
    # at interpreter boot (sitecustomize) already forced its own config.
    from ray_lightning_tpu.utils.platform import apply_jax_platform_env

    apply_jax_platform_env()

    import cloudpickle  # after env setup; cheap, no jax dependency

    actor = None
    try:
        while True:
            try:
                msg = cloudpickle.loads(conn.recv_bytes())
            except (EOFError, OSError):
                break
            kind = msg[0]
            if kind == "shutdown":
                break
            if kind == "init":
                try:
                    cls, args, kwargs = cloudpickle.loads(msg[1])
                    actor = cls(*args, **kwargs)
                    conn.send_bytes(cloudpickle.dumps(("ready", repr(type(actor)))))
                except BaseException as exc:  # noqa: BLE001 - report to driver
                    conn.send_bytes(
                        cloudpickle.dumps(
                            ("ready_error", _exc_payload(exc))
                        )
                    )
                continue
            if kind == "call":
                call_id, blob = msg[1], msg[2]
                try:
                    name, args, kwargs = cloudpickle.loads(blob)
                    if actor is None:
                        raise RuntimeError("actor not initialized")
                    result = getattr(actor, name)(*args, **kwargs)
                    payload = cloudpickle.dumps(("result", call_id, True, result))
                except (SystemExit, KeyboardInterrupt):
                    # SIGTERM's sys.exit must propagate so the process exits
                    # promptly (running atexit -> nested-actor cleanup)
                    # instead of being reported as a call failure.
                    raise
                except BaseException as exc:  # noqa: BLE001 - ship to driver
                    payload = cloudpickle.dumps(
                        ("result", call_id, False, _exc_payload(exc))
                    )
                conn.send_bytes(payload)
                continue
    finally:
        global _EXITING
        _EXITING = True  # late SIGTERMs (kill()'s follow-up) are no-ops now
        try:
            conn.close()
        except OSError:
            pass
        # Normal interpreter shutdown (atexit handlers run, letting runtimes
        # like PJRT release device locks cleanly).
        sys.stdout.flush()
        sys.stderr.flush()


def _exc_payload(exc):
    tb = traceback.format_exc()
    try:
        import cloudpickle

        cloudpickle.dumps(exc)  # probe picklability
        return (exc, tb)
    except Exception:  # noqa: BLE001
        return (RuntimeError(f"{type(exc).__name__}: {exc}"), tb)


def main(argv) -> None:
    """``python -m ray_lightning_tpu.fabric.worker <address>`` entrypoint.

    The connection authkey arrives on stdin (hex line) so it never shows in
    ``/proc/*/cmdline`` or the environment.
    """
    import multiprocessing as mp
    from multiprocessing.connection import Client

    address = argv[1]
    authkey = bytes.fromhex(sys.stdin.readline().strip())
    mp_authkey = bytes.fromhex(sys.stdin.readline().strip())
    # Restore the driver's multiprocessing authkey (normally inherited by
    # mp children) so Manager/Queue proxies shipped from the driver
    # authenticate in this process and in any actors it nests.
    mp.current_process().authkey = mp_authkey
    conn = Client(address, authkey=authkey)
    _worker_main(conn)


if __name__ == "__main__":
    main(sys.argv)
