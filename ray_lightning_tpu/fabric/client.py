"""Fabric client mode: drive a remote fabric head from a lightweight process.

Parity target: Ray Client ("infinite laptop") usage in the reference —
``ray_start_client_server`` fixtures and ``ray.init("ray://...")`` examples
(/root/reference/ray_lightning/tests/test_client.py:17-30). A driver with no
accelerator connects to a head that owns the resources; all actor
creation/object transport proxies over a socket.
"""
from __future__ import annotations


def connect(address: str) -> None:
    raise NotImplementedError(
        "fabric client mode is not wired up yet; run the driver on the head "
        "node (fabric.init() with no address)"
    )
