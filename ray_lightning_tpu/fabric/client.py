"""Fabric client mode: drive a remote fabric head from a lightweight process.

Parity target: Ray Client ("infinite laptop") usage in the reference —
``ray_start_client_server`` fixtures and ``ray.init("ray://...")`` examples
(/root/reference/ray_lightning/tests/test_client.py:17-30; the strategy
docstrings advertise exactly this workflow at ray_ddp.py:46-56). The driver
process owns no resources; ``fabric.init(address="host:port")`` connects to a
:class:`~ray_lightning_tpu.fabric.server.FabricServer` and every fabric call
(actor spawn, method call, put/get/wait/kill, queues) proxies over the
socket. Actors run on the head; the client stays a thin controller, so a
laptop can drive a TPU-host fabric.

Concurrency: one TCP connection per client *thread* (the protocol is
request/response), created lazily and cached thread-locally — the launcher's
poll loop and a blocking ``get`` from another thread never interleave frames.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import cloudpickle


class FabricClient:
    def __init__(self, address: str, authkey: Optional[str] = None) -> None:
        host, _, port = address.rpartition(":")
        self._addr = (host or "127.0.0.1", int(port))
        self._authkey = self._resolve_authkey(authkey)
        self._local = threading.local()
        self._conns: List[Any] = []
        self._lock = threading.Lock()
        # Validate eagerly so a bad address fails at init, not first use.
        self.request(("ping",))

    @staticmethod
    def _resolve_authkey(explicit: Optional[str]) -> bytes:
        """Explicit arg > RLT_FABRIC_AUTHKEY. There is no static default:
        servers generate a per-instance key (printed in their ready line)
        precisely so reaching the port is not enough to own the fabric."""
        import os

        key = explicit or os.environ.get("RLT_FABRIC_AUTHKEY")
        if not key:
            raise RuntimeError(
                "fabric client mode needs the server's authkey: pass "
                "fabric.init(address=..., authkey=...) or set "
                "RLT_FABRIC_AUTHKEY. The server prints a generated key in "
                "its 'FABRIC_SERVER_READY <addr> key=<key>' line (an "
                "operator-set RLT_FABRIC_AUTHKEY on the server side must "
                "be used instead when present)."
            )
        return key.encode()

    # -- transport ------------------------------------------------------
    def _conn(self) -> Any:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            from multiprocessing import AuthenticationError
            from multiprocessing.connection import Client as MPClient

            try:
                conn = MPClient(
                    self._addr, family="AF_INET", authkey=self._authkey
                )
            except AuthenticationError as exc:
                raise RuntimeError(
                    f"fabric head at {self._addr[0]}:{self._addr[1]} "
                    "rejected the authkey; use the key from the server's "
                    "ready line (or its RLT_FABRIC_AUTHKEY)"
                ) from exc
            self._local.conn = conn
            with self._lock:
                self._conns.append(conn)
        return conn

    def request(self, msg: Any) -> Any:
        conn = self._conn()
        conn.send_bytes(cloudpickle.dumps(msg, protocol=5))
        status, *rest = cloudpickle.loads(conn.recv_bytes())
        if status == "ok":
            return rest[0]
        if status == "timeout":
            raise TimeoutError("fabric.get timed out (remote)")
        raise rest[0]

    def close(self) -> None:
        with self._lock:
            conns, self._conns = self._conns, []
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass


_client: Optional[FabricClient] = None


def connect(address: str, authkey: Optional[str] = None) -> FabricClient:
    """Connect this process to a remote fabric head (client mode)."""
    global _client
    if _client is not None:
        host, _, port = address.rpartition(":")
        if (host or "127.0.0.1", int(port)) != _client._addr:
            raise RuntimeError(
                f"already connected to fabric head at "
                f"{_client._addr[0]}:{_client._addr[1]}; call "
                f"fabric.shutdown() before connecting to {address}"
            )
        return _client
    _client = FabricClient(address, authkey=authkey)
    return _client


def get_client() -> Optional[FabricClient]:
    return _client


def is_connected() -> bool:
    return _client is not None


def disconnect() -> None:
    global _client
    if _client is not None:
        _client.close()
        _client = None


# ---------------------------------------------------------------------------
# Client-side handle types mirroring core's surface
# ---------------------------------------------------------------------------
class _ClientRemoteMethod:
    def __init__(self, handle: "ClientActorHandle", name: str) -> None:
        self._handle = handle
        self._name = name

    def remote(self, *args: Any, **kwargs: Any):
        from ray_lightning_tpu.fabric.core import TaskRef

        blob = cloudpickle.dumps((self._name, args, kwargs), protocol=5)
        call_id = _client.request(("call", self._handle.actor_id, blob))
        return TaskRef(actor_id=self._handle.actor_id, call_id=call_id)


class ClientActorHandle:
    """Client-side proxy to an actor living on the fabric head."""

    def __init__(self, actor_id: str) -> None:
        self.actor_id = actor_id

    def _meta(self) -> Dict[str, Any]:
        return _client.request(("actor_meta", self.actor_id))

    @property
    def node_id(self) -> str:
        return self._meta()["node_id"]

    @property
    def node_ip(self) -> str:
        return self._meta()["node_ip"]

    @property
    def allocated_resources(self) -> Dict[str, float]:
        return self._meta()["allocated_resources"]

    @property
    def actor_options(self) -> Dict[str, Any]:
        return self._meta()["actor_options"]

    def is_alive(self) -> bool:
        return self._meta()["is_alive"]

    def __getattr__(self, name: str) -> _ClientRemoteMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return _ClientRemoteMethod(self, name)


class ClientActorClass:
    def __init__(self, cls: type, options: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._options = options or {}

    def options(self, **opts: Any) -> "ClientActorClass":
        merged = dict(self._options)
        merged.update(opts)
        return ClientActorClass(self._cls, merged)

    def remote(self, *args: Any, **kwargs: Any) -> ClientActorHandle:
        blob = cloudpickle.dumps((self._cls, args, kwargs), protocol=5)
        opts = dict(self._options)
        pg = opts.pop("placement_group", None)
        if pg is not None:
            # Ship the id; the server resolves it to its live PlacementGroup
            # (which holds Node objects and cannot cross the wire).
            opts["__pg_id__"] = pg.id
        actor_id = _client.request(("spawn", blob, opts))
        return ClientActorHandle(actor_id)


# ---------------------------------------------------------------------------
# API surface used by core's routing
# ---------------------------------------------------------------------------
def remote(cls: type) -> ClientActorClass:
    return ClientActorClass(cls)


def get(refs: Any, timeout: Optional[float] = None) -> Any:
    from ray_lightning_tpu.fabric.core import ObjectRef, TaskRef

    if isinstance(refs, (list, tuple)):
        return type(refs)(get(r, timeout=timeout) for r in refs)
    if isinstance(refs, (ObjectRef, TaskRef)):
        return _client.request(("get", refs, timeout))
    return refs


def put(obj: Any) -> Any:
    return _client.request(("put", cloudpickle.dumps(obj, protocol=5)))


def free(refs: Sequence[Any]) -> None:
    _client.request(("free", list(refs)))


def wait(
    refs: Sequence[Any], num_returns: int = 1, timeout: Optional[float] = None
) -> Tuple[List[Any], List[Any]]:
    return _client.request(("wait", list(refs), num_returns, timeout))


def kill(handle: Any, no_restart: bool = True) -> None:
    # Same contract as core.kill: the fabric never restarts actors in
    # place, so no_restart=False must fail loudly instead of silently
    # doing the no_restart=True thing (see serve.supervisor for the
    # restart path).
    if not no_restart:
        raise ValueError(
            "fabric.kill(no_restart=False) is unsupported: fabric "
            "actors are never restarted in place; use "
            "serve.supervisor.FleetSupervisor for replica restarts"
        )
    _client.request(("kill", handle.actor_id))


class ClientPlacementGroup:
    """Client-side proxy to a placement group living on the fabric head."""

    def __init__(
        self, pg_id: str, bundle_node_ids: List[str], strategy: str
    ) -> None:
        self.id = pg_id
        self.bundle_node_ids = bundle_node_ids
        self.strategy = strategy
        self.removed = False


def placement_group(
    bundles: Sequence[Dict[str, float]], strategy: str = "PACK"
) -> ClientPlacementGroup:
    pg_id, node_ids = _client.request(
        ("pg_create", [dict(b) for b in bundles], strategy)
    )
    return ClientPlacementGroup(pg_id, node_ids, strategy)


def remove_placement_group(pg: Any) -> None:
    _client.request(("pg_remove", pg.id))
    pg.removed = True


def nodes() -> List[Dict[str, Any]]:
    return _client.request(("nodes",))


def cluster_resources() -> Dict[str, float]:
    return _client.request(("cluster_resources",))


def available_resources() -> Dict[str, float]:
    return _client.request(("available_resources",))


# ---------------------------------------------------------------------------
# Client-mode queue
# ---------------------------------------------------------------------------
def _rebuild_worker_queue(proxy_blob: bytes) -> Any:
    # Runs inside server-spawned workers, which carry the server's mp
    # authkey — the manager proxy authenticates directly there.
    return cloudpickle.loads(proxy_blob)


class ClientQueue:
    """Queue living on the fabric head.

    The client drives it via RPC (its mp authkey differs from the server's,
    so the manager proxy is unusable client-side); when pickled into worker
    closures it rebuilds as the direct manager-proxy queue.
    """

    def __init__(self, maxsize: int = 0) -> None:
        self._qid, self._proxy_blob = _client.request(("queue_create", maxsize))

    def put(self, item: Any, block: bool = True, timeout: Optional[float] = None) -> None:
        _client.request(("queue_op", self._qid, "put", (item, block, timeout)))

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        return _client.request(("queue_op", self._qid, "get", (block, timeout)))

    def get_nowait(self) -> Any:
        return _client.request(("queue_op", self._qid, "get_nowait", ()))

    def empty(self) -> bool:
        return _client.request(("queue_op", self._qid, "empty", ()))

    def qsize(self) -> int:
        return _client.request(("queue_op", self._qid, "qsize", ()))

    def shutdown(self) -> None:
        # Release the head-side queue + its registry entry; without this a
        # long-lived head leaks one manager queue per tune trial.
        if _client is not None:
            try:
                _client.request(("queue_delete", self._qid))
            except Exception:  # noqa: BLE001 - head may already be gone
                pass

    def __reduce__(self):
        return (_rebuild_worker_queue, (self._proxy_blob,))
