"""RayShardedStrategy: ZeRO-style sharded data parallelism via GSPMD.

Parity target: the reference's ``RayShardedStrategy``
(/root/reference/ray_lightning/ray_ddp_sharded.py:11-13), whose entire
implementation is inherited from FairScale through PTL's
``DDPSpawnShardedStrategy`` (optimizer-state + gradient sharding). The
TPU-native design needs no external sharded optimizer: ZeRO-1 is a
NamedSharding rule on the optimizer pytree, ZeRO-3 additionally shards the
parameters themselves (FSDP-style); XLA inserts the reduce-scatter /
all-gather traffic into the compiled step (SURVEY.md §2b FairScale row).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ray_lightning_tpu.strategies.ddp import RayTPUStrategy


class RayShardedStrategy(RayTPUStrategy):
    """Sharded-DP strategy.

    Args (beyond RayTPUStrategy's):
      zero_stage: 1 shards optimizer state only (grads are reduced then
        consumed shard-wise); 3 also shards parameters across the data axis
        (XLA all-gathers them per-use, the FSDP recipe).
    """

    strategy_name = "ddp_sharded_ray"

    def __init__(self, *args: Any, zero_stage: int = 1, **kwargs: Any) -> None:
        if zero_stage not in (1, 2, 3):
            raise ValueError(f"zero_stage must be 1, 2 or 3, got {zero_stage}")
        # Stage 2's gradient sharding happens inside the compiled step under
        # GSPMD (reduce-scatter fusion); state-wise it equals stage 1.
        self.zero_stage = zero_stage
        super().__init__(*args, **kwargs)

    # -- shardings ------------------------------------------------------
    def param_sharding(self, params: Any) -> Any:
        from ray_lightning_tpu.parallel.zero import replicated, tree_shardings

        if self.zero_stage >= 3:
            return tree_shardings(params, self.mesh)
        return replicated(self.mesh)

    def opt_sharding(self, opt_state: Any, params: Any) -> Any:
        from ray_lightning_tpu.parallel.zero import tree_shardings

        return tree_shardings(opt_state, self.mesh)

    # -- state movement -------------------------------------------------
    # The jitted all-gather must run on every process (see base attr).
    gather_is_collective = True

    def gather_state(self, tree: Any) -> Any:
        """All-gather sharded leaves to full host arrays for checkpointing
        (SURVEY.md §7 'checkpoint of sharded state' hard part)."""
        from ray_lightning_tpu.parallel.zero import gather_to_host

        return gather_to_host(tree, self.mesh)
