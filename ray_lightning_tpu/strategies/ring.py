"""RingTPUStrategy: explicit per-rank collective scheduling (Horovod flavor).

Parity target: ``HorovodRayStrategy`` (/root/reference/ray_lightning/
ray_horovod.py:32-183), whose value over plain DDP is a *different
collective protocol* (Horovod's C++ ring-allreduce wrapping the optimizer).
On TPU the distinction is the programming model, not the wire protocol: this
strategy builds the step with ``shard_map`` — each device runs a per-rank
program on its local batch shard and gradients are averaged with an explicit
``lax.pmean`` over the "data" axis — instead of letting GSPMD infer the
collective from sharding propagation. The emitted ICI all-reduce is
identical in the common case; the explicit schedule is the escape hatch when
manual control over collective placement beats the partitioner.
"""
from __future__ import annotations

from typing import Any, Callable

from ray_lightning_tpu.strategies.ddp import RayTPUStrategy
from ray_lightning_tpu.utils.rank_zero import rank_zero_warn


class RingTPUStrategy(RayTPUStrategy):
    strategy_name = "horovod_ray"

    def compile_train_step(
        self,
        module: Any,
        tx: Any,
        log_grad_norm: bool = False,
        fold_steps: int = 1,
        fold_stacked: bool = False,
    ) -> Callable:
        import jax
        import jax.numpy as jnp
        import optax
        from jax.sharding import PartitionSpec as P

        from ray_lightning_tpu.utils.compat import shard_map

        mesh = self.mesh
        prep = self._prep_compute(module)

        def per_rank_step(params, opt_state, batch, rng):
            # Runs per device on its batch shard; params/opt replicated in.
            def loss_fn(p):
                p, b = prep(p, batch)
                loss, logs = module.training_step(p, b, rng)
                return loss, dict(logs)

            (loss, logs), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            # Explicit ring/tree all-reduce over the data axis — the
            # hvd.DistributedOptimizer analog (ray_horovod_launcher.py:202).
            grads = jax.lax.pmean(grads, "data")
            if log_grad_norm:
                # Post-allreduce: the same global norm every rank logs.
                logs["grad_norm"] = optax.global_norm(grads)
            logs.setdefault("loss", loss)
            logs = jax.tree_util.tree_map(
                lambda x: jax.lax.pmean(x, "data"), logs
            )
            updates, opt_state2 = tx.update(grads, opt_state, params)
            params2 = optax.apply_updates(params, updates)
            return params2, opt_state2, logs

        sharded = shard_map(
            per_rank_step,
            mesh=mesh,
            in_specs=(P(), P(), P("data"), P()),
            out_specs=(P(), P(), P()),
        )

        def step(params, opt_state, batch, rng, step_idx):
            rng = jax.random.fold_in(rng, step_idx)
            return sharded(params, opt_state, batch, rng)

        if fold_steps > 1:
            return self._fold_train_step(step, fold_steps, stacked=fold_stacked)
        return jax.jit(step, donate_argnums=(0, 1))

    def compile_eval_step(self, module: Any, stage: str) -> Callable:
        """Per-rank masked eval: each device reduces its real samples
        locally, then one explicit ``psum`` merges (sums, count) — same
        (sums, count) contract as the base strategy's GSPMD version."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from ray_lightning_tpu.utils.compat import shard_map

        if stage == "predict":
            return super().compile_eval_step(module, stage)

        fn = module.validation_step if stage in ("val", "validate") else module.test_step
        prep = self._prep_compute(module)

        if not getattr(module, "supports_per_sample_eval", True):

            def per_rank_batched(params, batch, mask):
                params, batch = prep(params, batch)
                logs = dict(fn(params, batch))
                count = jax.lax.psum(mask.astype(jnp.float32).sum(), "data")
                # Whole-batch metric: weight each rank's mean by its count.
                local = mask.astype(jnp.float32).sum()
                sums = {
                    k: jax.lax.psum(jnp.asarray(v, jnp.float32) * local, "data")
                    for k, v in logs.items()
                }
                return sums, count

            sharded = shard_map(
                per_rank_batched,
                mesh=self.mesh,
                in_specs=(P(), P("data"), P("data")),
                out_specs=(P(), P()),
            )
            return jax.jit(sharded)

        def per_rank_eval(params, batch, mask):
            params, batch = prep(params, batch)

            def per_sample(b):
                one = jax.tree_util.tree_map(lambda x: x[None], b)
                return {k: jnp.asarray(v) for k, v in dict(fn(params, one)).items()}

            vals = jax.vmap(per_sample)(batch)
            m = mask.astype(jnp.float32)
            count = jax.lax.psum(m.sum(), "data")
            sums = {
                k: jax.lax.psum((v.astype(jnp.float32).reshape(-1) * m).sum(), "data")
                for k, v in vals.items()
            }
            return sums, count

        sharded = shard_map(
            per_rank_eval,
            mesh=self.mesh,
            in_specs=(P(), P("data"), P("data")),
            out_specs=(P(), P()),
        )
        return jax.jit(sharded)


class HorovodRayStrategy(RingTPUStrategy):
    """Compat-named ring strategy with the reference's ctor surface
    (num_workers/num_cpus_per_worker/use_gpu, ray_horovod.py:73-91)."""

    def __init__(
        self,
        num_workers: int = 1,
        num_cpus_per_worker: float = 1,
        use_gpu: bool = False,
        **kwargs: Any,
    ) -> None:
        if use_gpu:
            rank_zero_warn(
                "use_gpu=True is a CUDA concept; falling back to accelerator "
                "auto-detection."
            )
        kwargs.setdefault("use_tpu", "auto" if use_gpu else False)
        super().__init__(
            num_workers=num_workers,
            num_cpus_per_worker=num_cpus_per_worker,
            **kwargs,
        )
