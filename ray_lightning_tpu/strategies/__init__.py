"""Distributed strategies.

Public surface parity with /root/reference/ray_lightning/__init__.py:1-5
(RayStrategy, HorovodRayStrategy, RayShardedStrategy) plus the TPU-native
names. Sharded/ring variants land with their milestones.
"""
from ray_lightning_tpu.strategies.base import SingleDeviceStrategy, Strategy
from ray_lightning_tpu.strategies.ddp import RayStrategy, RayTPUStrategy
from ray_lightning_tpu.strategies.gspmd import GSPMDStrategy
from ray_lightning_tpu.strategies.ring import HorovodRayStrategy, RingTPUStrategy
from ray_lightning_tpu.strategies.sharded import RayShardedStrategy

__all__ = [
    "Strategy",
    "SingleDeviceStrategy",
    "RayStrategy",
    "RayTPUStrategy",
    "RayShardedStrategy",
    "RingTPUStrategy",
    "HorovodRayStrategy",
    "GSPMDStrategy",
]
