"""Data-parallel strategies: RayTPUStrategy (+ RayStrategy compat alias).

Feature-parity target: the reference's ``RayStrategy(DDPSpawnStrategy)``
(/root/reference/ray_lightning/ray_ddp.py:23-333) — N-worker data
parallelism launched on actors, sampler sharding, rank bookkeeping, driver
recovery of rank-0 results. TPU-native execution: instead of per-parameter
NCCL allreduce hooks, the global batch is sharded over the mesh's "data"
axis and XLA inserts a single fused gradient all-reduce over ICI into the
compiled step.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ray_lightning_tpu.strategies.base import Strategy
from ray_lightning_tpu.utils.rank_zero import rank_zero_warn


class RayTPUStrategy(Strategy):
    """DP over TPU chips (or virtual CPU devices) via actor-launched workers.

    Args mirror the reference ctor (ray_ddp.py:69-75):
      num_workers: data-parallel ranks == total chips.
      num_cpus_per_worker: CPUs reserved per worker actor.
      use_tpu: True/False/"auto" — accelerator selection (the reference's
        ``use_gpu``).
      num_hosts: worker processes to spread chips over (auto on TPU pods).
      init_hook: callable run on each worker after spawn, before training
        (ray_launcher.py:79-83) — e.g. dataset download with a FileLock.
      resources_per_worker: extra custom logical resources per actor
        (tested by the reference at test_ddp.py:117-135).
    """

    strategy_name = "ray_tpu"

    def __init__(
        self,
        num_workers: int = 1,
        num_cpus_per_worker: float = 1,
        use_tpu: Any = "auto",
        num_hosts: Optional[int] = None,
        init_hook: Optional[Callable[[], None]] = None,
        resources_per_worker: Optional[Dict[str, float]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_workers=num_workers,
            num_cpus_per_worker=num_cpus_per_worker,
            use_tpu=use_tpu,
            num_hosts=num_hosts,
            init_hook=init_hook,
            resources_per_worker=resources_per_worker,
            **kwargs,
        )


class RayStrategy(RayTPUStrategy):
    """Compat-named DP strategy accepting the reference's ``use_gpu`` kwarg.

    ``RayStrategy(num_workers=2, use_gpu=False)`` (BASELINE.md config 1)
    runs CPU-device DP; ``use_gpu=True`` has no CUDA meaning on a TPU stack
    and maps to accelerator auto-detection with a warning.
    """

    strategy_name = "ddp_ray"

    def __init__(
        self,
        num_workers: int = 1,
        num_cpus_per_worker: float = 1,
        use_gpu: bool = False,
        **kwargs: Any,
    ) -> None:
        if use_gpu:
            rank_zero_warn(
                "use_gpu=True is a CUDA concept; this framework targets TPU. "
                "Falling back to accelerator auto-detection."
            )
        kwargs.setdefault("use_tpu", "auto" if use_gpu else False)
        super().__init__(
            num_workers=num_workers,
            num_cpus_per_worker=num_cpus_per_worker,
            **kwargs,
        )
