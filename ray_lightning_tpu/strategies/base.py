"""Strategy base: resource planning (driver side) + compiled execution (worker side).

The reference's strategies subclass PTL Strategy classes and configure
launchers + process groups (ray_ddp.py:23-126). Here a Strategy owns both
sides explicitly:

- driver: plan worker actors (count, resources, env) and pick the launcher —
  the analog of ``_configure_launcher`` + resource bookkeeping
  (ray_ddp.py:84-126);
- worker: rendezvous (``jax.distributed.initialize`` — replacing
  ``init_process_group``, ray_ddp.py:192-196), build the device Mesh, place
  params/optimizer/batch with NamedShardings, and compile the train/eval
  steps. Gradient averaging is *not* a per-parameter hook like DDP: the loss
  is the mean over the globally-sharded batch, so XLA's SPMD partitioner
  inserts the all-reduce into the compiled step itself.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_lightning_tpu.parallel.env import DistEnv


@dataclass
class WorkerPlan:
    """Placement request for one worker actor."""

    host_rank: int
    resources: Dict[str, float]
    env: Dict[str, str]
    num_cpus: float = 1.0


class Strategy:
    """Base distributed strategy."""

    strategy_name = "base"

    def __init__(
        self,
        num_workers: int = 1,
        num_cpus_per_worker: float = 1,
        use_tpu: Any = "auto",
        num_hosts: Optional[int] = None,
        init_hook: Optional[Callable[[], None]] = None,
        resources_per_worker: Optional[Dict[str, float]] = None,
        **kwargs: Any,
    ) -> None:
        if num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        self.num_workers = int(num_workers)  # chip-level DP ranks
        self.num_cpus_per_worker = num_cpus_per_worker
        self.use_tpu = use_tpu
        self._num_hosts = num_hosts
        self.init_hook = init_hook
        self.resources_per_worker = dict(resources_per_worker or {})
        self.extra_kwargs = kwargs
        # Worker-side state (populated in setup_worker)
        self.mesh = None
        self.dist_env: Optional[DistEnv] = None
        self._is_remote = False
        self._module: Optional[Any] = None

    # ------------------------------------------------------------------
    # Driver side
    # ------------------------------------------------------------------
    def _resolve_use_tpu(self) -> bool:
        if self.use_tpu == "auto":
            from ray_lightning_tpu import fabric

            try:
                return fabric.cluster_resources().get("TPU", 0) >= 1
            except Exception:  # noqa: BLE001
                return False
        return bool(self.use_tpu)

    def _resolve_num_hosts(self, use_tpu: bool) -> int:
        if self._num_hosts is not None:
            if self.num_workers % self._num_hosts:
                raise ValueError(
                    f"num_workers={self.num_workers} not divisible by "
                    f"num_hosts={self._num_hosts}"
                )
            return self._num_hosts
        if use_tpu:
            from ray_lightning_tpu import fabric

            # One actor per TPU host; chips_per_host from the node with TPUs.
            per_node = [
                n["Resources"].get("TPU", 0) for n in fabric.nodes() if n["Resources"].get("TPU", 0) > 0
            ]
            chips_per_host = int(per_node[0]) if per_node else 1
            if self.num_workers % chips_per_host == 0:
                return max(1, self.num_workers // chips_per_host)
            return self.num_workers  # fall back to 1 chip per actor
        return 1  # CPU: one process with N virtual devices

    def plan_workers(self) -> Tuple[List[WorkerPlan], bool]:
        """Compute actor placements. Returns (plans, use_tpu)."""
        use_tpu = self._resolve_use_tpu()
        num_hosts = self._resolve_num_hosts(use_tpu)
        chips_per_host = self.num_workers // num_hosts
        plans: List[WorkerPlan] = []
        for host_rank in range(num_hosts):
            resources = dict(self.resources_per_worker)
            env: Dict[str, str] = {}
            if use_tpu:
                resources["TPU"] = float(chips_per_host)
            else:
                # CPU mode: the actor simulates its chips with virtual XLA
                # host devices (the test strategy from SURVEY.md §4).
                env["JAX_PLATFORMS"] = "cpu"
                flags = os.environ.get("XLA_FLAGS", "")
                import re

                flags = re.sub(
                    r"--xla_force_host_platform_device_count=\d+", "", flags
                ).strip()
                env["XLA_FLAGS"] = (
                    f"{flags} --xla_force_host_platform_device_count={chips_per_host}"
                ).strip()
            plans.append(
                WorkerPlan(
                    host_rank=host_rank,
                    resources=resources,
                    env=env,
                    num_cpus=self.num_cpus_per_worker,
                )
            )
        return plans, use_tpu

    def _configure_launcher(self, trainer: Any):
        from ray_lightning_tpu.launchers.tpu_launcher import TPULauncher

        return TPULauncher(self, trainer)

    # Rank properties, valid on the driver before launch (the reference's
    # driver-side fallbacks, ray_horovod.py:110-141) and inside workers after
    # setup_worker.
    @property
    def world_size(self) -> int:
        return self.num_workers

    @property
    def global_rank(self) -> int:
        return self.dist_env.host_rank if self.dist_env else 0

    @property
    def local_rank(self) -> int:
        return self.dist_env.local_rank if self.dist_env else 0

    @property
    def node_rank(self) -> int:
        return self.dist_env.node_rank if self.dist_env else 0

    def set_remote(self, remote: bool) -> None:
        self._is_remote = remote

    # ------------------------------------------------------------------
    # Worker side
    # ------------------------------------------------------------------
    def setup_worker(self, dist_env: DistEnv) -> None:
        """Rendezvous + build the mesh. Called once inside each worker."""
        import jax

        from ray_lightning_tpu.parallel import mesh as mesh_lib

        self.dist_env = dist_env
        self._is_remote = True
        mesh_lib.setup_distributed(dist_env)
        n_devices = len(jax.devices())
        if n_devices != dist_env.world_size:
            raise RuntimeError(
                f"strategy expected {dist_env.world_size} global devices "
                f"(num_workers), found {n_devices}"
            )
        self.mesh = self.build_mesh()

    def bind_module(self, module: Any) -> None:
        """Give the strategy the user module before state placement, so
        sharding rules can consult module hooks (``param_logical_axes``,
        ``bind_mesh``). Called by the loop once the mesh exists."""
        self._module = module

    def build_mesh(self):
        from ray_lightning_tpu.parallel.mesh import build_mesh

        return build_mesh(axis_names=("data",))

    # -- shardings ------------------------------------------------------
    def param_sharding(self, params: Any) -> Any:
        """Sharding (pytree or single) for model params: replicated for DP."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P())

    def opt_sharding(self, opt_state: Any, params: Any) -> Any:
        """Sharding for optimizer state: replicated for plain DP."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P())

    def batch_sharding(self) -> Any:
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P("data"))

    @staticmethod
    def _place_tree(tree: Any, sharding: Any) -> Any:
        """device_put a pytree without aliasing caller-held buffers.

        Placed arrays are donated by the compiled step; device_put can reuse
        the source buffer even with may_alias=False (observed on the CPU
        backend), which would delete the caller's arrays on donation. A host
        round-trip guarantees fresh device buffers; placement happens once
        per run so the copy cost is setup-only.
        """
        import jax
        import numpy as np

        def place(x, s):
            host = x if isinstance(x, np.ndarray) else np.asarray(jax.device_get(x))
            return jax.device_put(host, s)

        if isinstance(sharding, jax.sharding.Sharding):
            return jax.tree_util.tree_map(lambda x: place(x, sharding), tree)
        return jax.tree_util.tree_map(place, tree, sharding)

    def place_params(self, params: Any) -> Any:
        return self._place_tree(params, self.param_sharding(params))

    def place_opt_state(self, opt_state: Any, params: Any) -> Any:
        return self._place_tree(opt_state, self.opt_sharding(opt_state, params))

    def make_global_batch(self, host_batch: Any) -> Any:
        """Host-local numpy batch -> globally sharded jax.Array pytree."""
        import jax

        sharding = self.batch_sharding()
        return jax.tree_util.tree_map(
            lambda x: jax.make_array_from_process_local_data(sharding, x),
            host_batch,
        )

    # -- compiled steps -------------------------------------------------
    def compile_train_step(self, module: Any, tx: Any) -> Callable:
        """Build the jitted train step.

        The whole optimization step — fwd, bwd, (XLA-inserted) grad
        all-reduce, optimizer update — is one compiled program, the TPU
        equivalent of the reference's ★ HOT LOOP (SURVEY.md §3.1) where
        DDP hooks overlap allreduce with backward.
        """
        import jax
        import optax

        def step(params, opt_state, batch, rng, step_idx):
            # Per-step rng derivation happens *inside* the compiled program
            # (the loop passes the base key + step counter), avoiding a
            # separate fold_in dispatch on the host every step.
            rng = jax.random.fold_in(rng, step_idx)

            def loss_fn(p):
                loss, logs = module.training_step(p, batch, rng)
                return loss, dict(logs)

            (loss, logs), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            updates, opt_state2 = tx.update(grads, opt_state, params)
            params2 = optax.apply_updates(params, updates)
            # Pin outputs to the strategy's shardings: without the
            # constraint GSPMD may pick a different layout for the updated
            # state, causing a reshard every step (observed on multi-axis
            # meshes). Sharding rules only need shapes, so they work on
            # tracers.
            params2 = jax.lax.with_sharding_constraint(
                params2, self.param_sharding(params2)
            )
            opt_state2 = jax.lax.with_sharding_constraint(
                opt_state2, self.opt_sharding(opt_state2, params2)
            )
            logs.setdefault("loss", loss)
            return params2, opt_state2, logs

        return jax.jit(step, donate_argnums=(0, 1))

    def compile_eval_step(self, module: Any, stage: str) -> Callable:
        import jax

        if stage == "predict":

            def pstep(params, batch):
                return module.predict_step(params, batch)

            # Replicate predictions so every host can fetch the full result.
            from jax.sharding import NamedSharding, PartitionSpec as P

            return jax.jit(
                pstep, out_shardings=NamedSharding(self.mesh, P())
            )

        fn = module.validation_step if stage in ("val", "validate") else module.test_step

        def estep(params, batch):
            return dict(fn(params, batch))

        return jax.jit(estep)

    # -- state movement -------------------------------------------------
    def gather_state(self, tree: Any) -> Any:
        """Device pytree -> host numpy pytree (full, unsharded).

        DP state is replicated so this is a plain device_get; sharded
        strategies override with an all-gather (SURVEY.md §7 "checkpoint of
        sharded state").
        """
        import jax
        import numpy as np

        return jax.tree_util.tree_map(lambda x: np.asarray(jax.device_get(x)), tree)

    def sampler_kwargs(self) -> Dict[str, int]:
        """Dataset sharding is per *host process*; in-host distribution across
        chips happens via the batch sharding (contrast with the reference's
        per-worker-process sampler, ray_ddp.py:315-324)."""
        env = self.dist_env
        if env is None:
            return {"num_replicas": 1, "rank": 0}
        return {"num_replicas": env.num_hosts, "rank": env.host_rank}

    @property
    def batch_multiplier(self) -> int:
        """Local chips per host: host batch = batch_size * this."""
        env = self.dist_env
        return env.local_chips if env else 1

    def teardown_worker(self) -> None:
        import jax

        if self.dist_env is not None and self.dist_env.is_distributed:
            try:
                jax.distributed.shutdown()
            except Exception:  # noqa: BLE001
                pass


class SingleDeviceStrategy(Strategy):
    """In-process strategy used when Trainer has no distributed strategy.

    Runs on the default local device set (1-chip TPU or N virtual CPU
    devices) without any launcher — the non-distributed baseline that
    ``bench.py`` compares distributed throughput against.
    """

    strategy_name = "single_device"

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(num_workers=1, **kwargs)

    def setup_worker(self, dist_env: DistEnv) -> None:
        import jax

        self.dist_env = dist_env
        n = len(jax.local_devices())
        dist_env.world_size = n
        dist_env.local_chips = n
        self.mesh = self.build_mesh()
