"""Strategy base: resource planning (driver side) + compiled execution (worker side).

The reference's strategies subclass PTL Strategy classes and configure
launchers + process groups (ray_ddp.py:23-126). Here a Strategy owns both
sides explicitly:

- driver: plan worker actors (count, resources, env) and pick the launcher —
  the analog of ``_configure_launcher`` + resource bookkeeping
  (ray_ddp.py:84-126);
- worker: rendezvous (``jax.distributed.initialize`` — replacing
  ``init_process_group``, ray_ddp.py:192-196), build the device Mesh, place
  params/optimizer/batch with NamedShardings, and compile the train/eval
  steps. Gradient averaging is *not* a per-parameter hook like DDP: the loss
  is the mean over the globally-sharded batch, so XLA's SPMD partitioner
  inserts the all-reduce into the compiled step itself.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_lightning_tpu.parallel.env import DistEnv


@dataclass
class WorkerPlan:
    """Placement request for one worker actor."""

    host_rank: int
    resources: Dict[str, float]
    env: Dict[str, str]
    num_cpus: float = 1.0


class Strategy:
    """Base distributed strategy."""

    strategy_name = "base"

    def __init__(
        self,
        num_workers: int = 1,
        num_cpus_per_worker: float = 1,
        use_tpu: Any = "auto",
        num_hosts: Optional[int] = None,
        init_hook: Optional[Callable[[], None]] = None,
        resources_per_worker: Optional[Dict[str, float]] = None,
        **kwargs: Any,
    ) -> None:
        if num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        self.num_workers = int(num_workers)  # chip-level DP ranks
        self.num_cpus_per_worker = num_cpus_per_worker
        self.use_tpu = use_tpu
        self._num_hosts = num_hosts
        self.init_hook = init_hook
        self.resources_per_worker = dict(resources_per_worker or {})
        self.extra_kwargs = kwargs
        # Worker-side state (populated in setup_worker)
        self.mesh = None
        self.dist_env: Optional[DistEnv] = None
        self._is_remote = False
        self._module: Optional[Any] = None

    # ------------------------------------------------------------------
    # Driver side
    # ------------------------------------------------------------------
    def _resolve_use_tpu(self) -> bool:
        if self.use_tpu == "auto":
            from ray_lightning_tpu import fabric

            try:
                return fabric.cluster_resources().get("TPU", 0) >= 1
            except Exception:  # noqa: BLE001
                return False
        return bool(self.use_tpu)

    def _resolve_num_hosts(self, use_tpu: bool) -> int:
        if self._num_hosts is not None:
            if self.num_workers % self._num_hosts:
                raise ValueError(
                    f"num_workers={self.num_workers} not divisible by "
                    f"num_hosts={self._num_hosts}"
                )
            return self._num_hosts
        if use_tpu:
            from ray_lightning_tpu import fabric
            from ray_lightning_tpu.utils.rank_zero import rank_zero_warn

            # One actor per TPU host. chips_per_host must hold on EVERY
            # host we place on, so a heterogeneous pod (unequal per-node
            # chip counts) plans against the minimum rather than trusting
            # whichever node happens to be listed first.
            per_node = [
                int(n["Resources"].get("TPU", 0))
                for n in fabric.nodes()
                if n["Resources"].get("TPU", 0) > 0
            ]
            if not per_node:
                return self.num_workers  # no TPU nodes visible yet: 1 chip/actor
            if len(set(per_node)) > 1:
                rank_zero_warn(
                    f"TPU nodes report unequal chip counts {sorted(set(per_node))}; "
                    f"planning with chips_per_host={min(per_node)} so every "
                    "worker actor fits on any TPU node"
                )
            chips_per_host = min(per_node)
            if self.num_workers % chips_per_host == 0:
                num_hosts = self.num_workers // chips_per_host
                # One whole-host actor per node in this branch.
                if num_hosts > len(per_node):
                    rank_zero_warn(
                        f"planning {num_hosts} TPU worker actors of "
                        f"{chips_per_host} chips each but only "
                        f"{len(per_node)} TPU nodes are visible; placement "
                        "will fail unless more nodes join"
                    )
            else:
                num_hosts = self.num_workers  # fall back to 1 chip per actor
                # Single-chip actors pack many-per-node; feasibility is
                # bounded by total chips, not node count.
                if self.num_workers > sum(per_node):
                    rank_zero_warn(
                        f"planning {self.num_workers} single-chip TPU worker "
                        f"actors but only {sum(per_node)} chips are visible; "
                        "placement will fail unless more chips join"
                    )
            return max(1, num_hosts)
        return 1  # CPU: one process with N virtual devices

    def plan_workers(self) -> Tuple[List[WorkerPlan], bool]:
        """Compute actor placements. Returns (plans, use_tpu)."""
        from ray_lightning_tpu.utils.rank_zero import rank_zero_warn

        req_tpu = self.resources_per_worker.get("TPU")
        if req_tpu is not None and float(req_tpu) != int(req_tpu):
            # Reference behavior for fractional accelerators
            # (ray_ddp.py:84-100): a fraction means chip SHARING, which PJRT
            # cannot isolate — warn loudly rather than fail mysteriously.
            rank_zero_warn(
                f"requesting a fractional TPU per worker (TPU={req_tpu}): "
                "TPU chips cannot be shared between XLA runtimes; expect "
                "workers to contend for the same chip. Use whole chips."
            )
        use_tpu = self._resolve_use_tpu()
        num_hosts = self._resolve_num_hosts(use_tpu)
        chips_per_host = self.num_workers // num_hosts
        plans: List[WorkerPlan] = []
        for host_rank in range(num_hosts):
            resources = dict(self.resources_per_worker)
            env: Dict[str, str] = {}
            if use_tpu:
                resources["TPU"] = float(chips_per_host)
            else:
                # CPU mode: the actor simulates its chips with virtual XLA
                # host devices (the test strategy from SURVEY.md §4).
                env["JAX_PLATFORMS"] = "cpu"
                flags = os.environ.get("XLA_FLAGS", "")
                import re

                flags = re.sub(
                    r"--xla_force_host_platform_device_count=\d+", "", flags
                ).strip()
                env["XLA_FLAGS"] = (
                    f"{flags} --xla_force_host_platform_device_count={chips_per_host}"
                ).strip()
            plans.append(
                WorkerPlan(
                    host_rank=host_rank,
                    resources=resources,
                    env=env,
                    num_cpus=self.num_cpus_per_worker,
                )
            )
        return plans, use_tpu

    def _configure_launcher(self, trainer: Any):
        from ray_lightning_tpu.launchers.tpu_launcher import TPULauncher

        return TPULauncher(self, trainer)

    # Rank properties, valid on the driver before launch (the reference's
    # driver-side fallbacks, ray_horovod.py:110-141) and inside workers after
    # setup_worker.
    @property
    def world_size(self) -> int:
        return self.num_workers

    @property
    def global_rank(self) -> int:
        return self.dist_env.host_rank if self.dist_env else 0

    @property
    def local_rank(self) -> int:
        return self.dist_env.local_rank if self.dist_env else 0

    @property
    def node_rank(self) -> int:
        return self.dist_env.node_rank if self.dist_env else 0

    def set_remote(self, remote: bool) -> None:
        self._is_remote = remote

    # ------------------------------------------------------------------
    # Worker side
    # ------------------------------------------------------------------
    def setup_worker(self, dist_env: DistEnv) -> None:
        """Rendezvous + build the mesh. Called once inside each worker."""
        import jax

        from ray_lightning_tpu.parallel import mesh as mesh_lib

        self.dist_env = dist_env
        self._is_remote = True
        mesh_lib.setup_distributed(dist_env)
        n_devices = len(jax.devices())
        if n_devices != dist_env.world_size:
            raise RuntimeError(
                f"strategy expected {dist_env.world_size} global devices "
                f"(num_workers), found {n_devices}"
            )
        self.mesh = self.build_mesh()

    def bind_module(self, module: Any) -> None:
        """Give the strategy the user module before state placement, so
        sharding rules can consult module hooks (``param_logical_axes``,
        ``bind_mesh``). Called by the loop once the mesh exists."""
        self._module = module

    def build_mesh(self):
        from ray_lightning_tpu.parallel.mesh import build_mesh

        return build_mesh(axis_names=("data",))

    # -- shardings ------------------------------------------------------
    def param_sharding(self, params: Any) -> Any:
        """Sharding (pytree or single) for model params: replicated for DP."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P())

    def opt_sharding(self, opt_state: Any, params: Any) -> Any:
        """Sharding for optimizer state: replicated for plain DP."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P())

    def batch_sharding(self) -> Any:
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P("data"))

    @staticmethod
    def _place_tree(tree: Any, sharding: Any) -> Any:
        """device_put a pytree without aliasing caller-held buffers.

        Placed arrays are donated by the compiled step; device_put can reuse
        the source buffer even with may_alias=False (observed on the CPU
        backend), which would delete the caller's arrays on donation. A host
        round-trip guarantees fresh device buffers; placement happens once
        per run so the copy cost is setup-only.
        """
        import jax
        import numpy as np

        def place(x, s):
            host = x if isinstance(x, np.ndarray) else np.asarray(jax.device_get(x))
            return jax.device_put(host, s)

        if isinstance(sharding, jax.sharding.Sharding):
            return jax.tree_util.tree_map(lambda x: place(x, sharding), tree)
        return jax.tree_util.tree_map(place, tree, sharding)

    def place_params(self, params: Any) -> Any:
        return self._place_tree(params, self.param_sharding(params))

    def place_opt_state(self, opt_state: Any, params: Any) -> Any:
        return self._place_tree(opt_state, self.opt_sharding(opt_state, params))

    @staticmethod
    def _shift_spec(sharding: Any) -> Any:
        """THE fold-axis rule, in one place: a (K, batch, ...) stacked
        chunk replicates the leading fold axis and shifts the per-step
        spec right by one."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(sharding.mesh, P(None, *tuple(sharding.spec)))

    def stacked_batch_sharding(self) -> Any:
        """Sharding for a (K, batch, ...) step-folded chunk (see
        :meth:`_shift_spec`). Strategies whose ``batch_sharding`` returns
        a per-leaf callable (GSPMDStrategy) override this accordingly."""
        return self._shift_spec(self.batch_sharding())

    def make_global_batch(self, host_batch: Any, stacked: bool = False) -> Any:
        """Host-local numpy batch -> globally sharded jax.Array pytree.

        ``stacked=True``: the leaves carry a leading fold axis (K, B, ...)
        — one transfer covering K steps (see ``stage_batches(stack=K)``).
        """
        import jax

        sharding = (
            self.stacked_batch_sharding() if stacked else self.batch_sharding()
        )
        if self.dist_env is None or not self.dist_env.is_distributed:
            # Single-process: plain device_put carries the same semantics
            # with less per-call bookkeeping than the multi-host assembler.
            return jax.tree_util.tree_map(
                lambda x: jax.device_put(x, sharding), host_batch
            )
        return jax.tree_util.tree_map(
            lambda x: jax.make_array_from_process_local_data(sharding, x),
            host_batch,
        )

    def stage_batches(
        self, host_batches: Any, depth: int = 3, stack: int = 0
    ) -> Any:
        """Iterate device-resident global batches, overlapping host->device
        transfer with compute.

        Over a tunneled/remote PJRT backend a blocking ``device_put`` costs a
        full round trip; a small thread pool keeps ``depth`` transfers in
        flight (order-preserving) so the step stream never stalls on H2D.
        This is the TPU analog of the reference relying on torch DataLoader
        ``pin_memory`` + async ``.cuda()`` copies in its hot loop.

        ``stack=K > 1`` (the trainer's steps_per_execution path) stacks K
        host batches into ONE (K, batch, ...) transfer, so a folded chunk
        costs a single H2D round trip instead of K; yields ``(n, batch)``
        pairs where full chunks have ``n == K`` and the epoch tail arrives
        as ``n == 1`` singles.
        """
        import collections
        from concurrent.futures import ThreadPoolExecutor

        import numpy as np

        def chunks():
            if stack <= 1:
                for hb in host_batches:
                    yield 1, False, hb
                return
            buf = []
            for hb in host_batches:
                buf.append(hb)
                if len(buf) == stack:
                    yield stack, True, buf  # stacked IN the executor task
                    buf = []
            for hb in buf:  # tail shorter than the fold: plain singles
                yield 1, False, hb

        def assemble(payload, stacked):
            # The K-batch host stack runs here, on a staging thread — the
            # consuming (step-dispatching) thread never pays the memcpy.
            if stacked:
                import jax

                payload = jax.tree_util.tree_map(
                    lambda *xs: np.stack(xs), *payload
                )
            return self.make_global_batch(payload, stacked)

        ex = ThreadPoolExecutor(max_workers=depth, thread_name_prefix="rlt-stage")
        pending: "collections.deque" = collections.deque()
        try:
            for n, stacked, hb in chunks():
                pending.append((n, ex.submit(assemble, hb, stacked)))
                while len(pending) >= depth:
                    n0, fut = pending.popleft()
                    yield (n0, fut.result()) if stack > 1 else fut.result()
            while pending:
                n0, fut = pending.popleft()
                yield (n0, fut.result()) if stack > 1 else fut.result()
        finally:
            ex.shutdown(wait=False, cancel_futures=True)

    # -- precision ------------------------------------------------------
    @staticmethod
    def _compute_dtype(module: Any):
        """Trainer-level mixed precision: params stay fp32 masters; the
        compute graph (params AND batch as seen by the module's step) is
        cast to bfloat16 — grads come back fp32 through the cast transpose.
        bf16 is TPU-native, so fp16 requests map to bf16 too (no loss
        scaling needed)."""
        import jax.numpy as jnp

        p = str(getattr(module, "precision", "fp32") or "fp32").lower()
        if p in ("fp32", "32", "32-true", "float32"):
            return None
        if p in ("bf16", "bf16-mixed", "bfloat16", "16", "16-mixed",
                 "fp16", "float16"):
            return jnp.bfloat16
        if p in ("bf16-true", "16-true"):
            # True-half (params/opt state STORED in bf16) is a memory-layout
            # choice the module owns (e.g. GPTConfig.compute_dtype); quietly
            # running it as mixed would break its memory promise.
            raise ValueError(
                f"precision {p!r} (true half) is not a trainer-level option; "
                "use 'bf16-mixed', or store low-precision params in the "
                "module itself"
            )
        raise ValueError(f"unsupported precision {p!r}")

    def _prep_compute(self, module: Any) -> Callable:
        """One shared cast policy for every compiled program: returns
        ``prep(params, batch) -> (params, batch)`` applying the trainer's
        mixed-precision dtype (no-op for fp32)."""
        cdt = self._compute_dtype(module)
        if cdt is None:
            return lambda params, batch: (params, batch)
        cast = self._cast_floating
        return lambda params, batch: (cast(params, cdt), cast(batch, cdt))

    @staticmethod
    def _cast_floating(tree: Any, dtype: Any) -> Any:
        import jax
        import jax.numpy as jnp

        def cast(x):
            x = jnp.asarray(x)
            return x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x

        return jax.tree_util.tree_map(cast, tree)

    # -- compiled steps -------------------------------------------------
    def compile_train_step(
        self,
        module: Any,
        tx: Any,
        log_grad_norm: bool = False,
        fold_steps: int = 1,
        fold_stacked: bool = False,
    ) -> Callable:
        """Build the jitted train step.

        The whole optimization step — fwd, bwd, (XLA-inserted) grad
        all-reduce, optimizer update — is one compiled program, the TPU
        equivalent of the reference's ★ HOT LOOP (SURVEY.md §3.1) where
        DDP hooks overlap allreduce with backward.

        ``log_grad_norm`` adds the pre-clip global gradient norm to the
        step's logs — computed in-graph (one reduction XLA fuses into the
        backward), not a host-side hook.

        ``fold_steps=K > 1`` returns a FOLDED step (the trainer's
        ``steps_per_execution``): one executable that ``lax.scan``s K
        optimizer steps, taking a tuple of K staged batches (stacked
        in-graph) and returning per-step logs stacked on a leading K
        axis. One device dispatch then covers K steps — on a
        high-latency link to the chip (remote PJRT), dispatch/transfer
        round trips stop bounding steps/sec. Per-step math is identical
        to the unfolded step (same per-step rng fold; asserted in
        tests/test_trainer.py).
        """
        import jax
        import optax

        prep = self._prep_compute(module)

        def step(params, opt_state, batch, rng, step_idx):
            # Per-step rng derivation happens *inside* the compiled program
            # (the loop passes the base key + step counter), avoiding a
            # separate fold_in dispatch on the host every step.
            rng = jax.random.fold_in(rng, step_idx)

            def loss_fn(p):
                p, b = prep(p, batch)
                loss, logs = module.training_step(p, b, rng)
                return loss, dict(logs)

            (loss, logs), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            if log_grad_norm:
                logs["grad_norm"] = optax.global_norm(grads)
            updates, opt_state2 = tx.update(grads, opt_state, params)
            params2 = optax.apply_updates(params, updates)
            # Pin outputs to the strategy's shardings: without the
            # constraint GSPMD may pick a different layout for the updated
            # state, causing a reshard every step (observed on multi-axis
            # meshes). Sharding rules only need shapes, so they work on
            # tracers.
            params2 = jax.lax.with_sharding_constraint(
                params2, self.param_sharding(params2)
            )
            opt_state2 = jax.lax.with_sharding_constraint(
                opt_state2, self.opt_sharding(opt_state2, params2)
            )
            logs.setdefault("loss", loss)
            return params2, opt_state2, logs

        if fold_steps <= 1:
            return jax.jit(step, donate_argnums=(0, 1))
        return self._fold_train_step(step, fold_steps, stacked=fold_stacked)

    @staticmethod
    def _fold_train_step(
        step: Callable, fold_steps: int, stacked: bool = False
    ) -> Callable:
        """Jit a ``(params, opt, batch, rng, step_idx)`` step body into the
        K-folded executable (``compile_train_step``'s ``fold_steps``
        contract): scans the step over K batches, returns per-step logs
        stacked on a leading K axis.

        ``stacked=False``: takes a K-tuple of separately staged batches and
        stacks them in-graph. ``stacked=True``: takes ONE (K, batch, ...)
        pytree straight off the stacked staging path
        (``stage_batches(stack=K)``) — the flag exists because a K-tuple
        of batch tuples and a single stacked batch tuple are structurally
        ambiguous at the pytree level.
        """
        import jax
        import jax.numpy as jnp

        K = int(fold_steps)

        def kstep(params, opt_state, batches, rng, step_idx):
            if stacked:
                xs = batches  # already (K, batch, ...) leaves
            else:
                # Stack the K staged batches INSIDE the compiled program:
                # one executable dispatch, no separate concat kernel.
                xs = jax.tree_util.tree_map(
                    lambda *bs: jnp.stack(bs), *batches
                )

            def body(carry, x):
                p, o = carry
                i, b = x
                p, o, logs = step(p, o, b, rng, step_idx + i)
                return (p, o), logs

            (params2, opt_state2), logs = jax.lax.scan(
                body, (params, opt_state), (jnp.arange(K), xs)
            )
            return params2, opt_state2, logs

        return jax.jit(kstep, donate_argnums=(0, 1))

    @staticmethod
    def compile_folded_eval_step(eval_step: Callable) -> Callable:
        """Fold a compiled ``(params, batch, mask) -> (sums, count)`` eval
        step over a stacked (K, ...) chunk: one dispatch scans K eval
        batches and returns their summed (sums, count). ``jax.jit``
        retraces per distinct leading-dim K, so this costs one compile
        per fold size actually seen — in practice exactly one, because
        ``stage_batches`` emits a single stack size and routes tail
        batches to the unfolded ``eval_step``. Masked sums/counts accumulate
        associatively, so chunking preserves the epoch means up to fp32
        summation order (the on-device partial sums reassociate the
        reduction; equal to the unfolded path within float tolerance,
        asserted in tests). Unlike the train fold there are no host
        cadences to quantize. Works for any strategy's val/test step (the
        inner jitted step inlines when traced)."""
        import jax

        def feval(params, batches, masks):
            sums, counts = jax.lax.map(
                lambda x: eval_step(params, x[0], x[1]), (batches, masks)
            )
            return (
                jax.tree_util.tree_map(lambda v: v.sum(0), sums),
                counts.sum(),
            )

        return jax.jit(feval)

    def compile_eval_step(self, module: Any, stage: str) -> Callable:
        """Compile the eval program.

        predict: ``(params, batch, mask) -> (preds, mask)`` replicated, so
        every host can fetch and trim padding rows.

        val/test: ``(params, batch, mask) -> (sums, count)`` where ``sums``
        holds per-key metric totals over REAL samples only and ``count`` the
        real-sample total. The user step still computes per-batch means (the
        reference contract); exactness comes from vmapping it over singleton
        batches — XLA fuses the vmap back into the same batched program — and
        mask-weighting, so wrap-around padding (trainer/data.py tail) never
        contaminates metrics. Modules whose metrics are not per-sample means
        can set ``supports_per_sample_eval = False`` to keep whole-batch
        evaluation (batch-count weighted)."""
        import jax
        import jax.numpy as jnp

        prep = self._prep_compute(module)

        if stage == "predict":
            from jax.sharding import NamedSharding, PartitionSpec as P

            def pstep(params, batch, mask):
                params, batch = prep(params, batch)
                return module.predict_step(params, batch), mask

            # Replicate predictions so every host can fetch the full result.
            return jax.jit(
                pstep, out_shardings=NamedSharding(self.mesh, P())
            )

        fn = module.validation_step if stage in ("val", "validate") else module.test_step

        if not getattr(module, "supports_per_sample_eval", True):

            def estep_batched(params, batch, mask):
                params, batch = prep(params, batch)
                logs = dict(fn(params, batch))
                count = mask.astype(jnp.float32).sum()
                return (
                    {k: jnp.asarray(v, jnp.float32) * count for k, v in logs.items()},
                    count,
                )

            return jax.jit(estep_batched)

        def estep(params, batch, mask):
            params, batch = prep(params, batch)

            def per_sample(b):
                one = jax.tree_util.tree_map(lambda x: x[None], b)
                return {k: jnp.asarray(v) for k, v in dict(fn(params, one)).items()}

            vals = jax.vmap(per_sample)(batch)
            m = mask.astype(jnp.float32)
            count = m.sum()
            sums = {
                k: (v.astype(jnp.float32).reshape(-1) * m).sum()
                for k, v in vals.items()
            }
            return sums, count

        return jax.jit(estep)

    # -- state movement -------------------------------------------------
    #: Whether gather_state is a COLLECTIVE every process must enter
    #: (sharded/GSPMD override with True). Callers use this to decide
    #: whether non-zero ranks must participate in checkpoint gathers
    #: (collective: skipping deadlocks) or can skip them (plain
    #: device_get: participating is wasted D2H traffic).
    gather_is_collective = False

    def gather_state(self, tree: Any) -> Any:
        """Device pytree -> host numpy pytree (full, unsharded).

        DP state is replicated so this is a plain device_get; sharded
        strategies override with an all-gather (SURVEY.md §7 "checkpoint of
        sharded state").
        """
        import jax
        import numpy as np

        return jax.tree_util.tree_map(lambda x: np.asarray(jax.device_get(x)), tree)

    def barrier(self, name: str = "barrier") -> None:
        """Block until every process reaches this point.

        Cross-process ordering (e.g. "all ranks finished their checkpoint
        writes before rank 0 deletes a directory") must not rest on
        library-internal synchronization; this is the explicit primitive.
        TPU-native: a named tiny collective over all global devices
        (``sync_global_devices``); single-process runs need no sync.
        """
        import jax

        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(name)

    def sampler_kwargs(self) -> Dict[str, int]:
        """Dataset sharding is per *host process*; in-host distribution across
        chips happens via the batch sharding (contrast with the reference's
        per-worker-process sampler, ray_ddp.py:315-324)."""
        env = self.dist_env
        if env is None:
            return {"num_replicas": 1, "rank": 0}
        return {"num_replicas": env.num_hosts, "rank": env.host_rank}

    @property
    def batch_multiplier(self) -> int:
        """Local chips per host: host batch = batch_size * this."""
        env = self.dist_env
        return env.local_chips if env else 1

    def teardown_worker(self) -> None:
        import jax

        if self.dist_env is not None and self.dist_env.is_distributed:
            try:
                jax.distributed.shutdown()
            except Exception:  # noqa: BLE001
                pass


class SingleDeviceStrategy(Strategy):
    """In-process strategy used when Trainer has no distributed strategy.

    Runs on the default local device set (1-chip TPU or N virtual CPU
    devices) without any launcher — the non-distributed baseline that
    ``bench.py`` compares distributed throughput against.
    """

    strategy_name = "single_device"

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(num_workers=1, **kwargs)

    def setup_worker(self, dist_env: DistEnv) -> None:
        import jax

        self.dist_env = dist_env
        n = len(jax.local_devices())
        dist_env.world_size = n
        dist_env.local_chips = n
        self.mesh = self.build_mesh()
