"""GSPMDStrategy: multi-axis mesh parallelism (dp x fsdp x tp x sp).

Beyond-parity strategy (the reference's surface is pure DP variants,
SURVEY.md §2c): one strategy that expresses data parallelism, ZeRO/FSDP
parameter sharding, megatron-style tensor parallelism, and ring-attention
sequence parallelism as *mesh axes* — the GSPMD recipe from the scaling
playbook. Models opt in by providing ``param_logical_axes()`` (see
``parallel.logical``); models without it degrade to FSDP-by-largest-axis
(the ZeRO rule from ``parallel.zero``).

The compiled step is identical to the DP one — XLA's partitioner inserts
all-reduce / reduce-scatter / all-gather traffic from the input shardings,
riding ICI within a slice and DCN across slices.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

from ray_lightning_tpu.strategies.ddp import RayTPUStrategy

_AXES = ("data", "fsdp", "model", "seq", "ep", "pp")


class GSPMDStrategy(RayTPUStrategy):
    """Args (beyond RayTPUStrategy's):

    mesh_shape: dict axis-name -> size over {"data","fsdp","model","seq",
        "ep","pp"} (data parallel, ZeRO/FSDP, tensor, sequence, expert,
        pipeline). Sizes must multiply to ``num_workers``. Missing axes get
        size 1; if *no* axis is given, everything lands on "data" (pure DP).
    logical_axis_rules: override for ``parallel.logical.DEFAULT_RULES``.
    sequence_parallel: shard the sequence dim of inputs over the "seq"
        axis and switch mesh-aware models to ring attention (mutually
        exclusive with a pp axis > 1).
    """

    strategy_name = "gspmd_ray"

    def __init__(
        self,
        *args: Any,
        mesh_shape: Optional[Dict[str, int]] = None,
        logical_axis_rules: Optional[Sequence[Tuple[str, Optional[str]]]] = None,
        sequence_parallel: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(*args, **kwargs)
        shape = dict(mesh_shape or {})
        for ax in shape:
            if ax not in _AXES:
                raise ValueError(f"unknown mesh axis {ax!r}; valid: {_AXES}")
        total = 1
        for ax in _AXES:
            total *= shape.get(ax, 1)
        if mesh_shape and total != self.num_workers:
            raise ValueError(
                f"mesh_shape {shape} covers {total} devices but "
                f"num_workers={self.num_workers}"
            )
        if not mesh_shape:
            shape = {"data": self.num_workers}
        if sequence_parallel and shape.get("seq", 1) < 2:
            raise ValueError(
                "sequence_parallel=True needs mesh_shape['seq'] >= 2"
            )
        if sequence_parallel and shape.get("pp", 1) > 1:
            raise ValueError(
                "sequence_parallel cannot be combined with pipeline "
                "parallelism (ring attention inside the pp shard_map)"
            )
        self.mesh_shape = shape
        self.logical_axis_rules = logical_axis_rules
        self.sequence_parallel = sequence_parallel

    # -- mesh -----------------------------------------------------------
    def build_mesh(self):
        from ray_lightning_tpu.parallel.mesh import build_mesh

        sizes = tuple(self.mesh_shape.get(ax, 1) for ax in _AXES)
        return build_mesh(axis_shape=sizes, axis_names=_AXES)

    # -- module hook ----------------------------------------------------
    def bind_module(self, module: Any) -> None:
        super().bind_module(module)
        if hasattr(module, "bind_mesh"):
            module.bind_mesh(
                self.mesh, "seq" if self.sequence_parallel else None
            )

    # -- shardings ------------------------------------------------------
    def param_sharding(self, params: Any) -> Any:
        module = getattr(self, "_module", None)
        if module is not None and hasattr(module, "param_logical_axes"):
            from ray_lightning_tpu.parallel.logical import (
                tree_logical_shardings,
            )

            return tree_logical_shardings(
                params,
                module.param_logical_axes(),
                self.mesh,
                rules=self.logical_axis_rules,
            )
        # Fallback: FSDP-by-largest-divisible-axis over "fsdp" (ZeRO-3 rule),
        # replicated if the fsdp axis is trivial.
        from ray_lightning_tpu.parallel.zero import replicated, tree_shardings

        if self.mesh.shape["fsdp"] > 1:
            return tree_shardings(params, self.mesh, axis_name="fsdp")
        return replicated(self.mesh)

    def opt_sharding(self, opt_state: Any, params: Any) -> Any:
        """Moment trees (optax state subtrees with the params' treedef, e.g.
        adam mu/nu) inherit the param shardings leaf-for-leaf; everything
        else (counts, schedule state) replicates. Matching by structure
        rather than shape avoids collisions between same-shape params with
        different layouts (e.g. wi/wo2 when d_ff == d_model)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        param_shardings = self.param_sharding(params)
        params_def = jax.tree_util.tree_structure(params)
        rep = NamedSharding(self.mesh, P())

        def is_param_tree(node: Any) -> bool:
            try:
                return jax.tree_util.tree_structure(node) == params_def
            except Exception:  # noqa: BLE001
                return False

        def node_sharding(node: Any) -> Any:
            return param_shardings if is_param_tree(node) else rep

        return jax.tree_util.tree_map(
            node_sharding, opt_state, is_leaf=is_param_tree
        )

    def batch_sharding(self) -> Any:
        from jax.sharding import NamedSharding, PartitionSpec as P

        def spec_for(x: Any) -> NamedSharding:
            import numpy as np

            shape = np.shape(x)
            batch_axes: Tuple[str, ...] = tuple(
                ax for ax in ("data", "fsdp") if self.mesh.shape[ax] > 1
            )
            spec: list = [batch_axes or None]
            if (
                self.sequence_parallel
                and len(shape) >= 2
                and shape[1] % self.mesh.shape["seq"] == 0
            ):
                spec.append("seq")
            spec += [None] * (len(shape) - len(spec))
            return NamedSharding(self.mesh, P(*spec))

        return spec_for

    def stacked_batch_sharding(self) -> Any:
        """Per-leaf callable (this strategy's batch_sharding contract):
        the per-step spec is computed on the inner shape — where the
        seq-axis rule looks at dim 1 — then shifted by the shared
        fold-axis rule (Strategy._shift_spec)."""
        spec_for = self.batch_sharding()
        return lambda x: self._shift_spec(spec_for(x[0]))

    def make_global_batch(self, host_batch: Any, stacked: bool = False) -> Any:
        import jax

        spec_for = (
            self.stacked_batch_sharding() if stacked else self.batch_sharding()
        )
        return jax.tree_util.tree_map(
            lambda x: jax.make_array_from_process_local_data(spec_for(x), x),
            host_batch,
        )

    # -- state movement -------------------------------------------------
    # The jitted all-gather must run on every process (see base attr).
    gather_is_collective = True

    def gather_state(self, tree: Any) -> Any:
        from ray_lightning_tpu.parallel.zero import gather_to_host

        return gather_to_host(tree, self.mesh)

    # -- dp sizing ------------------------------------------------------
    def sampler_kwargs(self) -> Dict[str, int]:
        """Dataset sharding must follow the *data-parallel extent*, not the
        host count: when tp/sp span hosts (dp < num_hosts), host groups
        sharing one dp shard must load IDENTICAL rows — otherwise
        make_array_from_process_local_data would silently assemble
        divergent "replicated" batches and gradients would drift per host.
        """
        env = self.dist_env
        if env is None:
            return {"num_replicas": 1, "rank": 0}
        dp = self.mesh_shape.get("data", 1) * self.mesh_shape.get("fsdp", 1)
        if dp % env.num_hosts == 0:
            return {"num_replicas": env.num_hosts, "rank": env.host_rank}
        if env.num_hosts % dp == 0:
            # dp axes lead the mesh (row-major device order), so host h's
            # devices all live in dp shard h*dp//num_hosts.
            return {
                "num_replicas": dp,
                "rank": env.host_rank * dp // env.num_hosts,
            }
        raise ValueError(
            f"data-parallel extent {dp} and num_hosts {env.num_hosts} must "
            f"divide one another for consistent per-host data sharding"
        )

    @property
    def batch_multiplier(self) -> int:
        """Global batch = per-replica batch x (data x fsdp) ranks; model/seq
        axes do not multiply the batch."""
        env = self.dist_env
        if env is None:
            return 1
        dp = self.mesh_shape.get("data", 1) * self.mesh_shape.get("fsdp", 1)
        # The loop multiplies the host-local loader batch; scale by this
        # host's share of the dp extent.
        return max(1, dp // env.num_hosts)
