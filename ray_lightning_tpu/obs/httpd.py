"""Tiny obs HTTP endpoint: /metrics, /stats, /healthz, /debug/bundle,
/fleet, /events, /traces, /journal, /why.

Standard-library only (http.server in a daemon thread). The handler
calls the collector functions PER REQUEST, so a scrape always sees
current values; collectors must therefore be thread-safe (the fabric's
driver surface, :class:`obs.registry.MetricsRegistry`, and
:class:`obs.health.Watchdog` all are).

Used by ``rlt serve --serve.metrics_port`` (driver-side, aggregating
replica scrapes) and usable standalone next to any registry::

    srv = MetricsHTTPServer(collect_text=registry.render, port=9400)
    srv.start()           # -> srv.port (0 picks a free port)
    ...
    srv.close()

``/healthz`` is a REAL readiness probe when ``collect_health`` is
wired: the callable returns ``(healthy, report_dict)`` and the endpoint
answers 200 with the JSON report while healthy, 503 with the same
report (the reason, machine-readable) when not — so an external load
balancer can act on it. Without a collector it keeps the legacy
unconditional ``ok`` (a liveness probe: the process answers HTTP).
``/debug/bundle`` triggers ``collect_bundle`` — a flight-recorder dump
returning its manifest (and, typically, the bundle files inline) — the
transport behind ``rlt doctor --doctor.bundle``.

The fleet routes (PR 8): ``/fleet`` serves ``collect_fleet`` (the
latest :class:`obs.fleet.FleetSnapshot` + history ring — ``rlt top``'s
feed), ``/events`` serves ``collect_events`` as JSONL (the merged
structured event rings — ``?level=``, ``?subsystem=``, and ``?n=``
query filters apply server-side via :func:`filter_events_jsonl`), and
``/traces`` serves ``collect_traces`` (the stitched cross-process
Chrome trace — save it and open in Perfetto). ``/journal`` serves
``collect_journal`` as JSONL — the workload journal (obs.journal),
directly consumable by ``rlt replay``. All are collector-gated exactly
like the others: an endpoint without the collector 404s.

``/why?id=<request_id>`` (PR 19) serves ``collect_why(id)`` — the
request's assembled anatomy phase ledger
(:func:`obs.anatomy.assemble_anatomy` over the live fleet's rings) as
JSON; 400 without an id, 404 when the id is unknown to every ring
(``found: false`` rides the body either way). ``rlt why <addr> <id>``
is the rendering client.

The watchtower routes (PR 20): ``/query?series=&since=&step=`` serves
``collect_query(params)`` — one retained TSDB series
(:class:`obs.tsdb.RingTSDB`) as ``[(ts, value), ...]`` JSON (400
without a series name, 404 with ``found: false`` + a name sample for
an unknown one — ``rlt plot``'s feed); ``/alerts`` serves
``collect_alerts()`` — the alert engine's rules/states/firing payload
(``rlt alerts``'s feed). ``/events`` additionally honors a
``?since=<seq>`` cursor over the per-ring monotonic sequence, so
tails resume incrementally.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs

CONTENT_TYPE_PROM = "text/plain; version=0.0.4; charset=utf-8"


def filter_events_jsonl(text: str, query: Dict[str, List[str]]) -> str:
    """Apply ``/events`` query filters to a JSONL body: ``level=`` and
    ``subsystem=`` keep matching rows (repeatable — values OR),
    ``since=<seq>`` keeps rows whose per-ring sequence is NEWER than the
    cursor (rows without a ``seq`` are dropped by a since filter — a
    cursor client can't position them), ``n=`` keeps the newest n AFTER
    filtering. Unparseable lines are dropped rather than crashing a
    scrape; no recognized params = passthrough."""
    levels = set(query.get("level") or [])
    subsystems = set(query.get("subsystem") or [])
    n = None
    if query.get("n"):
        n = int(query["n"][0])
    since = None
    if query.get("since"):
        since = int(query["since"][0])
    if not levels and not subsystems and n is None and since is None:
        return text
    kept: List[str] = []
    for ln in text.splitlines():
        if not ln.strip():
            continue
        try:
            row = json.loads(ln)
        except ValueError:
            continue
        if levels and row.get("level") not in levels:
            continue
        if subsystems and row.get("subsystem") not in subsystems:
            continue
        if since is not None and not (
            isinstance(row.get("seq"), int) and row["seq"] > since
        ):
            continue
        kept.append(ln)
    if n is not None:
        kept = kept[-n:]
    return "\n".join(kept) + ("\n" if kept else "")


class MetricsHTTPServer:
    def __init__(
        self,
        collect_text: Callable[[], str],
        collect_json: Optional[Callable[[], Dict[str, Any]]] = None,
        collect_health: Optional[
            Callable[[], Tuple[bool, Dict[str, Any]]]
        ] = None,
        collect_bundle: Optional[Callable[[], Dict[str, Any]]] = None,
        collect_fleet: Optional[Callable[[], Dict[str, Any]]] = None,
        collect_events: Optional[Callable[[], str]] = None,
        collect_traces: Optional[Callable[[], Dict[str, Any]]] = None,
        collect_journal: Optional[Callable[[], str]] = None,
        collect_why: Optional[
            Callable[[str], Dict[str, Any]]
        ] = None,
        collect_query: Optional[
            Callable[[Dict[str, List[str]]], Dict[str, Any]]
        ] = None,
        collect_alerts: Optional[Callable[[], Dict[str, Any]]] = None,
        host: str = "127.0.0.1",
        port: int = 0,
    ) -> None:
        self._collect_text = collect_text
        self._collect_json = collect_json
        self._collect_health = collect_health
        self._collect_bundle = collect_bundle
        self._collect_fleet = collect_fleet
        self._collect_events = collect_events
        self._collect_traces = collect_traces
        self._collect_journal = collect_journal
        self._collect_why = collect_why
        self._collect_query = collect_query
        self._collect_alerts = collect_alerts
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args: Any) -> None:  # noqa: ARG002
                pass  # scrapes must not spam stderr

            def do_GET(self) -> None:  # noqa: N802 - http.server API
                path, _, query = self.path.partition("?")
                code = 200
                try:
                    if path in ("/metrics", "/"):
                        body = outer._collect_text().encode()
                        ctype = CONTENT_TYPE_PROM
                    elif path == "/stats" and outer._collect_json is not None:
                        body = json.dumps(outer._collect_json()).encode()
                        ctype = "application/json"
                    elif path == "/healthz":
                        if outer._collect_health is None:
                            body, ctype = b"ok\n", "text/plain"
                        else:
                            healthy, report = outer._collect_health()
                            body = json.dumps(report, default=str).encode()
                            ctype = "application/json"
                            code = 200 if healthy else 503
                    elif (
                        path == "/debug/bundle"
                        and outer._collect_bundle is not None
                    ):
                        body = json.dumps(
                            outer._collect_bundle(), default=str
                        ).encode()
                        ctype = "application/json"
                    elif (
                        path == "/fleet"
                        and outer._collect_fleet is not None
                    ):
                        body = json.dumps(
                            outer._collect_fleet(), default=str
                        ).encode()
                        ctype = "application/json"
                    elif (
                        path == "/events"
                        and outer._collect_events is not None
                    ):
                        body = filter_events_jsonl(
                            outer._collect_events(), parse_qs(query)
                        ).encode()
                        ctype = "application/x-ndjson"
                    elif (
                        path == "/journal"
                        and outer._collect_journal is not None
                    ):
                        body = outer._collect_journal().encode()
                        ctype = "application/x-ndjson"
                    elif (
                        path == "/why"
                        and outer._collect_why is not None
                    ):
                        rid = (parse_qs(query).get("id") or [None])[0]
                        if not rid:
                            self.send_error(
                                400, "missing ?id=<request_id>"
                            )
                            return
                        ledger = outer._collect_why(rid)
                        if not ledger.get("found"):
                            code = 404
                        body = json.dumps(ledger, default=str).encode()
                        ctype = "application/json"
                    elif (
                        path == "/query"
                        and outer._collect_query is not None
                    ):
                        params = parse_qs(query)
                        if not params.get("series"):
                            self.send_error(
                                400, "missing ?series=<name>"
                            )
                            return
                        result = outer._collect_query(params)
                        if not result.get("found"):
                            code = 404
                        body = json.dumps(result, default=str).encode()
                        ctype = "application/json"
                    elif (
                        path == "/alerts"
                        and outer._collect_alerts is not None
                    ):
                        body = json.dumps(
                            outer._collect_alerts(), default=str
                        ).encode()
                        ctype = "application/json"
                    elif (
                        path == "/traces"
                        and outer._collect_traces is not None
                    ):
                        body = json.dumps(
                            outer._collect_traces(), default=str
                        ).encode()
                        ctype = "application/json"
                    else:
                        self.send_error(404)
                        return
                except Exception as exc:  # noqa: BLE001 - scrape-visible
                    self.send_error(500, str(exc)[:200])
                    return
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MetricsHTTPServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"obs-metrics-http-{self.port}",
            daemon=True,
        )
        self._thread.start()
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def close(self) -> None:
        # shutdown() handshakes with a RUNNING serve_forever loop (it
        # blocks on an event that loop sets); when start() was never
        # called — e.g. a caller erroring out between construction and
        # start — it would wait forever. Only the socket close is needed
        # then. Idempotent: a second close() is a no-op.
        if self._thread is not None:
            self._httpd.shutdown()
            self._thread.join(timeout=5.0)
            self._thread = None
        self._httpd.server_close()
