"""On-demand jax.profiler capture.

Wraps ``jax.profiler`` start/stop into (1) a context manager used by the
tools (tools/gpt_profile.py traces a known span of work) and (2)
:func:`capture_profile` — the duration-based form behind the
``profile(duration_s)`` RPC on serve replicas and TrainWorkers: start a
trace, sleep while the process's OWN worker threads keep the device
busy, stop, report the artifact files. The captured trace opens in
Perfetto / TensorBoard's profile plugin.

Everything degrades gracefully: when the profiler is unavailable (or a
capture is already running — jax allows one at a time per process) the
result says so instead of raising, because a profile RPC against a busy
replica must never take the replica down.
"""
from __future__ import annotations

import contextlib
import os
import tempfile
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

#: One capture at a time per process (jax.profiler's own constraint).
_ACTIVE = threading.Lock()


def profiler_available() -> bool:
    try:
        import jax.profiler  # noqa: F401

        return True
    except Exception:  # noqa: BLE001 - any import-time failure
        return False


def _trace_files(outdir: str) -> List[str]:
    found: List[str] = []
    for root, _, files in os.walk(outdir):
        for f in files:
            found.append(os.path.join(root, f))
    return sorted(found)


@contextlib.contextmanager
def trace(outdir: str) -> Iterator[str]:
    """``with obs.profiling.trace(dir):`` — jax.profiler.trace with the
    one-capture lock held, so overlapping callers queue instead of
    crashing each other."""
    import jax

    with _ACTIVE:
        with jax.profiler.trace(outdir):
            yield outdir


def capture_profile(
    duration_s: float = 1.0, outdir: Optional[str] = None
) -> Dict[str, Any]:
    """Capture ``duration_s`` of whatever this process's threads are
    doing; returns ``{ok, dir, files, duration_s}`` (or ``{ok: False,
    error}``). The caller's thread only sleeps — the work being profiled
    runs on the process's other threads (serve loop, train loop)."""
    duration_s = max(0.01, float(duration_s))
    if not profiler_available():
        return {"ok": False, "error": "jax.profiler unavailable"}
    if not _ACTIVE.acquire(blocking=False):
        return {"ok": False, "error": "a profile capture is already running"}
    try:
        import jax

        out = outdir or tempfile.mkdtemp(prefix="rlt_profile_")
        os.makedirs(out, exist_ok=True)
        try:
            jax.profiler.start_trace(out)
            time.sleep(duration_s)
        finally:
            jax.profiler.stop_trace()
        return {
            "ok": True,
            "dir": out,
            "files": _trace_files(out),
            "duration_s": duration_s,
        }
    except Exception as exc:  # noqa: BLE001 - report, never kill the host
        return {"ok": False, "error": f"{type(exc).__name__}: {exc}"}
    finally:
        _ACTIVE.release()
