"""Fleet aggregation: one queryable surface over N serving replicas.

PR 4/5 made each PROCESS observable (its own registry, tracer, event
ring, watchdog); this module rolls the fleet up. A driver-side
:class:`FleetPoller` periodically pulls every replica's stats snapshot
and health verdict (plus the fabric heartbeat table) through one
``pull_fn`` and condenses them into a :class:`FleetSnapshot`:

- ``replicas``: one compact row per replica — queue depth, active
  slots, tokens/s, TTFT p50/p95, spec accept rate, prefix hit rate,
  health verdict, and goodput (emitted tokens per device-second, from
  the cost ledger) — the exact surface a router/autoscaler consumes;
- ``fleet``: the roll-up — replica/healthy counts, total queue depth,
  aggregate tokens/s, fleet goodput (sum of emitted tokens over sum of
  device-seconds, NOT a mean of ratios), worst TTFT p95;
- ``heartbeats``: the fabric's worker heartbeat table, verbatim.

Snapshots land in a bounded history ring (so ``/fleet`` can show a
short trend without unbounded memory) and, when a registry is wired,
in ``rlt_fleet_*`` gauges next to the per-replica series. The poller
owns one daemon thread; a pull that raises is recorded (``errors``
counter + an event) and skipped — a dead replica must not kill the
control plane that would report it dead.

Consumed by ``rlt serve --serve.metrics_port`` (the ``/fleet`` route),
``rlt top`` (the live terminal dashboard), and ``rlt doctor`` bundles
(``fleet.json``). The observer effect of an aggressive poll cadence is
benched as ``fleet_overhead`` next to ``obs_overhead``.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

#: Map verdict strings onto the rlt_fleet_replica_health gauge.
_VERDICT_SCORE = {"healthy": 1.0, "degraded": 0.5, "unhealthy": 0.0}


def summarize_replica(
    stats: Dict[str, Any],
    health: Optional[Dict[str, Any]] = None,
    index: int = 0,
) -> Dict[str, Any]:
    """One replica's dashboard row from its stats snapshot + health
    report — the compact, stable schema FleetSnapshot.replicas carries
    (full snapshots stay on the replica; the fleet plane only ships
    what a router/autoscaler/dashboard acts on)."""
    cost = dict(stats.get("cost") or {})
    verdict = (health or {}).get("verdict")
    if verdict is None:
        verdict = stats.get("health", "unknown")
    # Tiered prefix cache: fraction of block probes each tier served
    # (device probes = the walk's total, since device is probed first).
    tiers = dict((stats.get("prefix") or {}).get("tiers") or {})
    dev = tiers.get("device") or {}
    probes = int(dev.get("hits", 0)) + int(dev.get("misses", 0))
    tier_hit = {
        t: (round(int(r.get("hits", 0)) / probes, 4) if probes else 0.0)
        for t, r in tiers.items()
    } or None
    # Effective cache size: resident prefix bytes summed over every
    # enabled tier (device + host + disk) — a replica's capacity to
    # hold warm prefixes, the router's affinity tiebreaker.
    prefix_bytes = sum(int(r.get("bytes", 0)) for r in tiers.values())
    kvf = stats.get("kvfleet")
    kvs = stats.get("kvstore")
    return {
        "replica": int(index),
        "health": str(verdict),
        # Fleet KV plane: the replica's role (prefill/decode/mixed)
        # plus a compact transfer row — what `rlt top`'s role/fetch
        # columns and the role-aware router/autoscaler consume.
        "role": str(stats.get("role") or "mixed"),
        "kvfleet": (
            {
                k: kvf.get(k, 0)
                for k in (
                    "fetches", "fetch_bytes", "fetch_timeouts",
                    "fetch_stale", "ships", "served_fetches",
                    "pending_fetches", "store_fetches",
                    "store_fetch_misses", "layerwise", "layer_ships",
                    "ship_partial_drops",
                )
            }
            if isinstance(kvf, dict)
            else None
        ),
        # Persistent object-store tier: counters for dashboards PLUS
        # the recent_writes/recent_dropped rings verbatim — the router
        # refresh loop reads those rings off this row to keep the
        # directory's store-held half current, so they must survive
        # summarization.
        "kvstore": (
            {
                k: kvs.get(k)
                for k in (
                    "backend", "budget_mb", "hits", "misses", "writes",
                    "write_errors", "bytes_written", "bytes_read",
                    "evictions", "corrupt", "recent_writes",
                    "recent_dropped",
                )
            }
            if isinstance(kvs, dict)
            else None
        ),
        # Quality signals for the router/autoscaler: cumulative
        # SLO-breach count (PR 5's declarative rules) and the engine's
        # dropped-digest report (the directory's eviction feed).
        "slo_breaches": int(stats.get("slo_breaches") or 0),
        "kv_dropped": stats.get("kv_dropped"),
        "queue_depth": int(stats.get("queue_depth", 0)),
        "active_slots": int(stats.get("active_slots", 0)),
        "num_slots": int(stats.get("num_slots", 0)),
        "occupancy": float(stats.get("occupancy", 0.0)),
        "tokens_per_sec": float(stats.get("tokens_per_sec", 0.0)),
        "decode_tokens_per_sec": float(
            stats.get("decode_tokens_per_sec", 0.0)
        ),
        "ttft_p50_s": stats.get("ttft_p50_s"),
        "ttft_p95_s": stats.get("ttft_p95_s"),
        "spec_accept_rate": stats.get("spec_accept_rate"),
        "prefix_hit_rate": stats.get("prefix_hit_rate"),
        "prefix_tier_hit_rate": tier_hit,
        "prefix_bytes": prefix_bytes,
        # Paged KV: pool state + occupancy (None on dense replicas) —
        # the capacity signal a page-aware router/autoscaler reads.
        "kv_pages": (
            {
                k: kv[k]
                for k in (
                    "free", "resident", "aliased", "occupancy",
                    "fragmentation_tokens",
                )
            }
            if isinstance(kv := stats.get("kv_pages"), dict)
            else None
        ),
        # Fused-dispatch row: piggybacked prefill traffic + the fold
        # ladder's per-depth dispatch counts (None when piggyback is
        # off / the ladder has one rung) — `rlt top`'s pb column.
        "piggyback": (
            {
                "chunks": pb.get("chunks", 0),
                "dispatches": pb.get("dispatches", 0),
                "chunk_rows": pb.get("chunk_rows", 0),
            }
            if isinstance(pb := stats.get("piggyback"), dict)
            else None
        ),
        "fold_k": (
            {
                "ladder": fk.get("ladder") or [],
                "dispatches": fk.get("dispatches") or {},
            }
            if isinstance(fk := stats.get("fold_k"), dict)
            else None
        ),
        "submitted": int(stats.get("submitted", 0)),
        "finished": int(stats.get("finished", 0)),
        "compiles_since_init": int(stats.get("compiles_since_init", 0)),
        # Anatomy latency decomposition: the replica's windowed
        # per-phase percentile block verbatim (None when the phase
        # ledger is off or idle) — aggregate_fleet folds these into the
        # fleet-wide decomposition `rlt top` and `/fleet` show.
        "phases": stats.get("phases"),
        # Active SLO-breach reasons (with their phase attribution
        # suffix) — the fleet roll-up surfaces the first one as the
        # dashboard's `why:` line.
        "slo_reasons": [
            reason
            for name, ch in sorted(
                ((health or {}).get("components") or {}).items()
            )
            if name.startswith("slo:")
            and ch.get("verdict") == "unhealthy"
            for reason in ch.get("reasons", [])
        ] or None,
        # Goodput inputs ride along so the fleet ratio can be computed
        # as sum/sum instead of a mean of per-replica ratios.
        "cost_emitted_tokens": int(cost.get("emitted_tokens", 0)),
        "cost_device_seconds": float(cost.get("device_seconds", 0.0)),
        "goodput_tokens_per_device_s": float(
            cost.get("goodput_tokens_per_device_s", 0.0)
        ),
    }


def aggregate_fleet(rows: List[Dict[str, Any]]) -> Dict[str, Any]:
    """The fleet roll-up over per-replica rows (sum/sum goodput, worst
    TTFT p95, healthy count)."""
    toks = sum(r["cost_emitted_tokens"] for r in rows)
    dev = sum(r["cost_device_seconds"] for r in rows)
    p95s = [r["ttft_p95_s"] for r in rows if r.get("ttft_p95_s") is not None]
    kvf_rows = [r.get("kvfleet") or {} for r in rows]
    kvs_rows = [r.get("kvstore") or {} for r in rows]
    phases_block = _aggregate_phase_rows(rows)
    breach = next(
        (
            reason
            for r in rows
            for reason in (r.get("slo_reasons") or ())
        ),
        None,
    )
    return {
        "replicas": len(rows),
        "healthy": sum(1 for r in rows if r["health"] == "healthy"),
        # Fleet KV plane roll-up: cross-replica fetch/ship traffic
        # (zeros on fleets without the plane).
        "kvfleet_fetches": sum(
            int(k.get("fetches", 0)) for k in kvf_rows
        ),
        "kvfleet_fetch_timeouts": sum(
            int(k.get("fetch_timeouts", 0)) + int(k.get("fetch_stale", 0))
            for k in kvf_rows
        ),
        "kvfleet_ships": sum(int(k.get("ships", 0)) for k in kvf_rows),
        # Fused-dispatch roll-up: prefill chunk rows that rode decode
        # folds fleet-wide (zeros when piggybacking is off).
        "piggyback_dispatches": sum(
            int((r.get("piggyback") or {}).get("dispatches", 0))
            for r in rows
        ),
        "piggyback_chunk_rows": sum(
            int((r.get("piggyback") or {}).get("chunk_rows", 0))
            for r in rows
        ),
        # Persistent store roll-up (zeros on storeless fleets). Note:
        # replicas sharing one store dir each count their own traffic,
        # so these are fleet I/O totals, not unique-entry counts.
        "kvstore_hits": sum(
            int(k.get("hits") or 0) for k in kvs_rows
        ),
        "kvstore_misses": sum(
            int(k.get("misses") or 0) for k in kvs_rows
        ),
        "kvstore_writes": sum(
            int(k.get("writes") or 0) for k in kvs_rows
        ),
        "kvstore_write_errors": sum(
            int(k.get("write_errors") or 0) for k in kvs_rows
        ),
        "kvstore_evictions": sum(
            int(k.get("evictions") or 0) for k in kvs_rows
        ),
        "queue_depth": sum(r["queue_depth"] for r in rows),
        "active_slots": sum(r["active_slots"] for r in rows),
        "num_slots": sum(r["num_slots"] for r in rows),
        "tokens_per_sec": round(
            sum(r["tokens_per_sec"] for r in rows), 3
        ),
        "emitted_tokens": toks,
        "device_seconds": round(dev, 6),
        "goodput_tokens_per_device_s": (
            round(toks / dev, 3) if dev > 0 else 0.0
        ),
        "ttft_p95_s_worst": max(p95s) if p95s else None,
        # Anatomy decomposition roll-up: per-phase p50 (count-weighted
        # mean of replica p50s), p95/p99 (MAX across replicas — tails
        # don't average), hot_phase = the fleet's single largest p95 —
        # `rlt top`'s phase hot-spot column. None when no replica has a
        # phase window.
        "phases": phases_block,
        # The first active SLO-breach reason (attribution suffix
        # included) — `rlt top`'s `why:` line; None when nothing is
        # breaching.
        "breach_attribution": breach,
    }


def _aggregate_phase_rows(
    rows: List[Dict[str, Any]],
) -> Optional[Dict[str, Any]]:
    """Fold per-replica ``phases`` blocks into the fleet decomposition:
    weighted-mean centers, max tails, per-role split when the fleet is
    disaggregated."""
    by_phase: Dict[str, Dict[str, float]] = {}
    by_role: Dict[str, Dict[str, Dict[str, float]]] = {}
    for r in rows:
        blk = (r.get("phases") or {}).get("by_phase") or {}
        role = str(r.get("role") or "mixed")
        for phase, row in blk.items():
            if not isinstance(row, dict):
                continue
            c = int(row.get("count", 0))
            if c <= 0:
                continue
            agg = by_phase.setdefault(phase, {
                "count": 0, "_mean_w": 0.0, "_p50_w": 0.0,
                "p95_s": 0.0, "p99_s": 0.0,
            })
            agg["count"] += c
            agg["_mean_w"] += float(row.get("mean_s", 0.0)) * c
            agg["_p50_w"] += float(row.get("p50_s", 0.0)) * c
            agg["p95_s"] = max(agg["p95_s"], float(row.get("p95_s", 0.0)))
            agg["p99_s"] = max(agg["p99_s"], float(row.get("p99_s", 0.0)))
            role_agg = by_role.setdefault(role, {}).setdefault(
                phase, {"count": 0, "p95_s": 0.0}
            )
            role_agg["count"] += c
            role_agg["p95_s"] = max(
                role_agg["p95_s"], float(row.get("p95_s", 0.0))
            )
    if not by_phase:
        return None
    out_phases = {
        phase: {
            "p50_s": round(agg["_p50_w"] / agg["count"], 6),
            "p95_s": round(agg["p95_s"], 6),
            "p99_s": round(agg["p99_s"], 6),
            "mean_s": round(agg["_mean_w"] / agg["count"], 6),
            "count": int(agg["count"]),
        }
        for phase, agg in by_phase.items()
    }
    hot_phase, hot_row = max(
        out_phases.items(), key=lambda kv: kv[1]["p95_s"]
    )
    out: Dict[str, Any] = {
        "by_phase": out_phases,
        "hot_phase": hot_phase,
        "hot_phase_p95_s": hot_row["p95_s"],
    }
    if len(by_role) > 1:
        out["by_role"] = {
            role: {
                phase: {
                    "p95_s": round(agg["p95_s"], 6),
                    "count": int(agg["count"]),
                }
                for phase, agg in phases.items()
            }
            for role, phases in by_role.items()
        }
    return out


@dataclass
class FleetSnapshot:
    """One poll of the whole fleet (the ``/fleet`` payload unit)."""

    ts: float
    replicas: List[Dict[str, Any]]
    fleet: Dict[str, Any]
    heartbeats: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "ts": self.ts,
            "replicas": self.replicas,
            "fleet": self.fleet,
            "heartbeats": self.heartbeats,
        }


#: pull_fn contract: () -> (stats_list, health_list_or_None,
#: heartbeats_or_None); stats_list[i] is replica i's stats snapshot.
PullFn = Callable[
    [],
    Tuple[
        List[Dict[str, Any]],
        Optional[List[Dict[str, Any]]],
        Optional[Dict[str, Any]],
    ],
]


class FleetPoller:
    """Background fleet aggregator: pull -> condense -> ring + gauges.

    ``history`` bounds the ring; ``interval_s`` is the poll cadence
    (production default seconds — the bench runs it 100x faster to
    measure the observer effect). ``to_dict()`` is the ``/fleet``
    payload: the latest snapshot plus the history ring.

    ``supervisor_fn`` (optional, zero-arg -> list of rows — typically
    ``FleetSupervisor.rows``) embeds the recovery plane's per-replica
    state table in the ``/fleet`` payload, so ``rlt top`` and dashboards
    show restarts/draining next to the health/throughput rows.
    ``router_fn`` (optional, zero-arg -> dict — typically
    ``serve.router.Router.rows``) embeds the routing plane the same
    way: per-replica weights/routability plus the routed/shed totals.
    ``alerts_fn`` (optional, zero-arg -> dict — typically
    ``obs.watchtower.Watchtower.fleet_block``) embeds the alert
    engine's firing summary, so ``rlt top`` shows firing alerts
    without a second request.
    """

    def __init__(
        self,
        pull_fn: PullFn,
        interval_s: float = 2.0,
        history: int = 128,
        registry: Optional[Any] = None,
        events: Optional[Any] = None,
        supervisor_fn: Optional[
            Callable[[], List[Dict[str, Any]]]
        ] = None,
        router_fn: Optional[Callable[[], Dict[str, Any]]] = None,
        alerts_fn: Optional[Callable[[], Dict[str, Any]]] = None,
    ) -> None:
        self._pull = pull_fn
        self._supervisor_fn = supervisor_fn
        self._router_fn = router_fn
        self._alerts_fn = alerts_fn
        self.interval_s = float(interval_s)
        self.history = max(1, int(history))
        self._events = events
        self._lock = threading.Lock()
        self._ring: List[Dict[str, Any]] = []
        self._errors = 0
        self._polls = 0
        self._reg = None
        if registry is not None:
            self._reg = {
                "replicas": registry.gauge(
                    "rlt_fleet_replicas", "Replicas in the fleet snapshot"
                ),
                "healthy": registry.gauge(
                    "rlt_fleet_replicas_healthy",
                    "Replicas whose verdict is healthy",
                ),
                "queue": registry.gauge(
                    "rlt_fleet_queue_depth", "Fleet-wide queued requests"
                ),
                "tps": registry.gauge(
                    "rlt_fleet_tokens_per_sec",
                    "Fleet-wide emitted tokens per second",
                ),
                "goodput": registry.gauge(
                    "rlt_fleet_goodput_tokens_per_device_second",
                    "Fleet emitted tokens per estimated device-second",
                ),
                "health": registry.gauge(
                    "rlt_fleet_replica_health",
                    "Per-replica health (1 healthy, 0.5 degraded, "
                    "0 unhealthy)",
                ),
                "phase_p95": registry.gauge(
                    "rlt_fleet_phase_p95_seconds",
                    "Fleet-wide anatomy phase p95 (max across "
                    "replicas), by phase",
                ),
                "polls": registry.counter(
                    "rlt_fleet_polls_total", "Fleet snapshot pulls"
                ),
                "errors": registry.counter(
                    "rlt_fleet_poll_errors_total",
                    "Fleet pulls that raised and were skipped",
                ),
            }
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- one poll ---------------------------------------------------------
    def poll_now(self) -> FleetSnapshot:
        """Pull + condense one snapshot NOW (raises on pull failure —
        the loop wraps it; direct callers see the real error)."""
        stats_list, health_list, heartbeats = self._pull()
        health_list = health_list or []
        rows = [
            summarize_replica(
                stats,
                health_list[i] if i < len(health_list) else None,
                index=i,
            )
            for i, stats in enumerate(stats_list)
        ]
        snap = FleetSnapshot(
            ts=time.time(),
            replicas=rows,
            fleet=aggregate_fleet(rows),
            heartbeats=dict(heartbeats or {}),
        )
        with self._lock:
            self._ring.append(snap.to_dict())
            if len(self._ring) > self.history:
                del self._ring[: len(self._ring) - self.history]
            self._polls += 1
        if self._reg is not None:
            f = snap.fleet
            self._reg["replicas"].set(f["replicas"])
            self._reg["healthy"].set(f["healthy"])
            self._reg["queue"].set(f["queue_depth"])
            self._reg["tps"].set(f["tokens_per_sec"])
            self._reg["goodput"].set(f["goodput_tokens_per_device_s"])
            for r in rows:
                self._reg["health"].set(
                    _VERDICT_SCORE.get(r["health"], 0.0),
                    replica=r["replica"],
                )
            for phase, row in (
                (f.get("phases") or {}).get("by_phase") or {}
            ).items():
                self._reg["phase_p95"].set(row["p95_s"], phase=phase)
            self._reg["polls"].inc(1)
        return snap

    # -- read side --------------------------------------------------------
    def latest(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return dict(self._ring[-1]) if self._ring else None

    def history_list(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def to_dict(self) -> Dict[str, Any]:
        """The ``/fleet`` payload: latest snapshot + bounded history."""
        with self._lock:
            ring = list(self._ring)
            errors = self._errors
            polls = self._polls
        out = {
            "latest": ring[-1] if ring else None,
            "history": ring,
            "polls": polls,
            "errors": errors,
            "interval_s": self.interval_s,
        }
        if self._supervisor_fn is not None:
            try:
                out["supervisor"] = self._supervisor_fn()
            except Exception:  # noqa: BLE001 - the fleet payload must
                pass  # survive a supervisor mid-teardown
        if self._router_fn is not None:
            try:
                out["router"] = self._router_fn()
            except Exception:  # noqa: BLE001 - same for the router
                pass
        if self._alerts_fn is not None:
            try:
                out["alerts"] = self._alerts_fn()
            except Exception:  # noqa: BLE001 - and the alert engine
                pass
        return out

    # -- thread lifecycle -------------------------------------------------
    def start(self) -> "FleetPoller":
        self._thread = threading.Thread(
            target=self._loop, name="obs-fleet-poller", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_now()
            except Exception as exc:  # noqa: BLE001 - a dead replica
                # must not kill the plane that would report it dead.
                with self._lock:
                    self._errors += 1
                if self._reg is not None:
                    self._reg["errors"].inc(1)
                if self._events is not None:
                    self._events.record(
                        "fleet", "poll_error", level="warn",
                        error=f"{type(exc).__name__}: {exc}"[:200],
                    )
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
