"""Counter/gauge/histogram registry with Prometheus text rendering.

The metrics half of the observability layer (obs/trace.py is the tracing
half): serve, trainer, and fabric code record into a
:class:`MetricsRegistry`, and any surface that wants the numbers renders
them — the ``rlt serve --serve.metrics_port`` HTTP endpoint and
``ServeReplica.metrics_text()`` ship the Prometheus text exposition
format; ``stats()`` embeds :meth:`MetricsRegistry.to_dict`.

Design constraints (why not prometheus_client):

- zero dependencies — the container only has what it has;
- recording must be cheap enough for the serve hot loop (a dict update
  under one lock, no string formatting until render time);
- one process-global default registry (:func:`get_registry`), because
  the scrape surface is per-process (each replica actor renders its own
  registry; the driver renders its own and concatenates).

Label support is deliberately minimal: labels are passed as kwargs at
record time and become part of the sample key. Series are born on first
touch, exactly like Prometheus client libraries.
"""
from __future__ import annotations

import math
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

#: Default histogram buckets: latency-flavored, seconds.
DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

_RESERVED = {"le"}


def _label_key(labels: Dict[str, Any]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _fmt_labels(key: Tuple[Tuple[str, str], ...], extra: str = "") -> str:
    parts = [f'{k}="{_escape(v)}"' for k, v in key]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    return repr(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


class _Metric:
    """Shared sample-map plumbing; subclasses define semantics."""

    kind = "untyped"

    def __init__(self, name: str, help_: str, lock: threading.Lock) -> None:
        self.name = name
        self.help = help_
        self._lock = lock
        #: label-key tuple -> float (counters/gauges)
        self._samples: Dict[Tuple[Tuple[str, str], ...], float] = {}

    def _check_labels(self, labels: Dict[str, Any]) -> None:
        bad = _RESERVED.intersection(labels)
        if bad:
            raise ValueError(f"reserved label name(s) {sorted(bad)}")

    def value(self, **labels: Any) -> float:
        with self._lock:
            return self._samples.get(_label_key(labels), 0.0)

    def samples(self) -> Dict[Tuple[Tuple[str, str], ...], float]:
        with self._lock:
            return dict(self._samples)

    def remove(self, **labels: Any) -> bool:
        """Drop one labelled series (e.g. a dead worker's gauges) so a
        scrape stops reporting stale values forever; returns whether the
        series existed. Series re-appear on the next record, exactly
        like first touch."""
        key = _label_key(labels)
        with self._lock:
            return self._samples.pop(key, None) is not None

    def render(self) -> List[str]:
        out = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} {self.kind}",
        ]
        for key, val in sorted(self.samples().items()):
            out.append(f"{self.name}{_fmt_labels(key)} {_fmt_value(val)}")
        return out


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels: Any) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        self._check_labels(labels)
        key = _label_key(labels)
        with self._lock:
            self._samples[key] = self._samples.get(key, 0.0) + amount


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels: Any) -> None:
        self._check_labels(labels)
        with self._lock:
            self._samples[_label_key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels: Any) -> None:
        self._check_labels(labels)
        key = _label_key(labels)
        with self._lock:
            self._samples[key] = self._samples.get(key, 0.0) + amount


class Histogram(_Metric):
    kind = "histogram"

    def __init__(
        self,
        name: str,
        help_: str,
        lock: threading.Lock,
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ) -> None:
        super().__init__(name, help_, lock)
        bs = sorted(float(b) for b in buckets)
        if not bs:
            raise ValueError("need at least one bucket bound")
        self.buckets = tuple(bs)
        #: label-key -> [per-bucket counts..., +Inf count]; _samples holds
        #: the sums, _counts the observation counts.
        self._bucket_counts: Dict[Tuple[Tuple[str, str], ...], List[int]] = {}
        self._counts: Dict[Tuple[Tuple[str, str], ...], int] = {}

    def observe(self, value: float, **labels: Any) -> None:
        self._check_labels(labels)
        v = float(value)
        key = _label_key(labels)
        with self._lock:
            counts = self._bucket_counts.get(key)
            if counts is None:
                counts = self._bucket_counts[key] = [0] * (
                    len(self.buckets) + 1
                )
            # Non-cumulative per-bucket tallies; cumulated at render time
            # so the hot path is one index bump.
            for i, b in enumerate(self.buckets):
                if v <= b:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            self._samples[key] = self._samples.get(key, 0.0) + v
            self._counts[key] = self._counts.get(key, 0) + 1

    def count(self, **labels: Any) -> int:
        with self._lock:
            return self._counts.get(_label_key(labels), 0)

    def remove(self, **labels: Any) -> bool:
        key = _label_key(labels)
        with self._lock:
            found = self._bucket_counts.pop(key, None) is not None
            self._samples.pop(key, None)
            self._counts.pop(key, None)
            return found

    def render(self) -> List[str]:
        out = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} {self.kind}",
        ]
        with self._lock:
            items = sorted(self._bucket_counts.items())
            sums = dict(self._samples)
            counts = dict(self._counts)
        for key, per_bucket in items:
            cum = 0
            for bound, n in zip(self.buckets, per_bucket):
                cum += n
                le = _fmt_labels(key, f'le="{_fmt_value(bound)}"')
                out.append(f"{self.name}_bucket{le} {cum}")
            cum += per_bucket[-1]
            le = _fmt_labels(key, 'le="+Inf"')
            out.append(f"{self.name}_bucket{le} {cum}")
            out.append(
                f"{self.name}_sum{_fmt_labels(key)} "
                f"{_fmt_value(sums.get(key, 0.0))}"
            )
            out.append(
                f"{self.name}_count{_fmt_labels(key)} {counts.get(key, 0)}"
            )
        return out


class MetricsRegistry:
    """Thread-safe named-metric registry.

    Registration is idempotent: asking for an existing name returns the
    existing metric (and raises if the kind differs), so independent
    subsystems can declare the metrics they feed without coordinating.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_make(self, cls: type, name: str, help_: str, **kw: Any):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as {m.kind}"
                    )
                return m
            # Metrics share the registry lock: recording is a dict update
            # under one uncontended-in-practice lock, cheap enough for the
            # serve hot loop.
            m = cls(name, help_, self._lock, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get_or_make(Counter, name, help_)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get_or_make(Gauge, name, help_)

    def histogram(
        self,
        name: str,
        help_: str = "",
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._get_or_make(Histogram, name, help_, buckets=buckets)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def render(self) -> str:
        """Prometheus text exposition format (version 0.0.4)."""
        with self._lock:
            metrics = [self._metrics[n] for n in sorted(self._metrics)]
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + ("\n" if lines else "")

    def to_dict(self) -> Dict[str, Any]:
        """Plain-dict snapshot for JSON surfaces (stats endpoints).

        Labelled series render as ``{label=\"v\"}`` suffixed keys;
        histograms export count/sum only (buckets are a scrape-format
        concern).
        """
        with self._lock:
            metrics = [self._metrics[n] for n in sorted(self._metrics)]
        out: Dict[str, Any] = {}
        for m in metrics:
            if isinstance(m, Histogram):
                for key in m.samples():
                    sfx = _fmt_labels(key)
                    out[f"{m.name}_count{sfx}"] = m.count(
                        **{k: v for k, v in key}
                    )
                    out[f"{m.name}_sum{sfx}"] = m.samples()[key]
            else:
                for key, val in m.samples().items():
                    out[f"{m.name}{_fmt_labels(key)}"] = val
        return out


def relabel_text(text: str, **labels: Any) -> str:
    """Inject extra labels into every sample line of rendered exposition
    text (comments pass through). Used when aggregating several
    processes' registries into one scrape — e.g. per-replica sections
    become ``replica="0"``-labelled series instead of duplicates."""
    if not labels:
        return text
    extra = ",".join(f'{k}="{_escape(str(v))}"' for k, v in sorted(labels.items()))
    out: List[str] = []
    for line in text.splitlines():
        stripped = line.strip()
        if not stripped or stripped.startswith("#"):
            out.append(line)
            continue
        name_part, sep, val_part = stripped.rpartition(" ")
        if not sep:
            out.append(line)
            continue
        if name_part.endswith("}"):
            body = name_part[:-1]
            joiner = "," if not body.endswith("{") else ""
            out.append(f"{body}{joiner}{extra}}} {val_part}")
        else:
            out.append(f"{name_part}{{{extra}}} {val_part}")
    return "\n".join(out) + ("\n" if text.endswith("\n") else "")


def parse_prometheus_text(text: str) -> Dict[str, Dict[str, float]]:
    """Parse exposition-format text into {metric: {labelstr: value}}.

    Round-trip companion to :meth:`MetricsRegistry.render` — used by the
    tests and scrape tooling to assert counter values survive the wire.
    The label string is the rendered ``{k="v",...}`` form ("" when bare).
    """
    out: Dict[str, Dict[str, float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name_part, _, val_part = line.rpartition(" ")
        if not name_part:
            continue
        if "{" in name_part:
            name, _, rest = name_part.partition("{")
            labels = "{" + rest
        else:
            name, labels = name_part, ""
        val = float(val_part) if val_part not in ("+Inf", "-Inf") else (
            math.inf if val_part == "+Inf" else -math.inf
        )
        out.setdefault(name, {})[labels] = val
    return out


#: Process-global default registry: each process (driver, replica actor,
#: training worker) records into its own and exposes it whole.
_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _REGISTRY
