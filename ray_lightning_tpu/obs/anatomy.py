"""Request anatomy: the per-request phase ledger.

One request's latency hides in many places: the driver's batch window
and route planning, the scheduler queue, a transfer-pending park while
warm KV pages fetch from a peer or the persistent store, the prefill
(solo chunks or piggybacked inside decode folds), a disaggregated
prefill→decode ship, the decode itself, and the stream's final hop back
to the caller. A DistServe-style fleet spreads those phases over three
or more processes, so no single ring can answer "where did the time
go?" — this module is the joining layer.

:func:`assemble_anatomy` stitches the :class:`~.trace.RequestTracer`
dumps of every process (client + replicas + followers), the driver-side
journal entries, and the typed event ring under ONE request id into a
**phase ledger**: a chronological list of phase rows, each attributed
to the process it ran on, drawn from the canonical vocabulary

    client_wait   driver→replica handoff (RPC transit, re-drives)
    batch_window  coalescing wait inside the driver's batcher
    route_plan    driver routing/planning (plan → submit RPC)
    queue         scheduler queue (submit → admission decision)
    transfer_park re-queued wait after a KV transfer landed
    kv_fetch      parked on a warm-page fetch (detail: peer | store)
    prefill       slot entry → first token (detail: solo | piggyback)
    ship          disaggregated prefill→decode KV handoff (export,
                  transit, decode-side import)
    decode        first token → terminal
    stream_gap    replica terminal → the client observing the end

with ``hedged`` / ``migrated`` / ``failover`` markers riding alongside
(they are occurrences, not durations — their time lands in the phases
that contain them).

**Coverage contract**: the rows are clipped to a single non-overlapping
timeline (a hedged loser's spans never double-count), so

    observed_s == accounted_s + unaccounted_s        (exactly)

where ``observed_s`` is the client-observed latency (first client event
→ journal outcome, when available). Unattributed time is reported as
``unaccounted`` — never silently absorbed into a neighboring phase —
and ``coverage`` is the accounted fraction; callers state a tolerance
(default 10%) and ``covered`` says whether the ledger met it. A ring
that wrapped over part of the request's history flags ``truncated`` and
the missing span shows up as unaccounted WITH provenance, not as a
mis-attribution.

The compact per-request ``{phase: seconds}`` maps the scheduler folds
into journal outcome records and the metrics window are the same
vocabulary one layer down: :func:`aggregate_phases` rolls them into
percentile blocks (the fleet decomposition, the replay diff) and
:func:`breach_attribution` turns a block into "kv_fetch 58%, queue
22%" — the Watchdog's SLO-breach verdicts name their top contributing
phases with it.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_lightning_tpu.obs import trace as _trace

#: Canonical phase order (rendering + aggregation stability).
PHASES = (
    "client_wait",
    "batch_window",
    "route_plan",
    "queue",
    "transfer_park",
    "kv_fetch",
    "prefill",
    "ship",
    "decode",
    "stream_gap",
)

#: Marker names (occurrences, not durations).
MARKERS = ("hedged", "migrated", "failover")

#: Default coverage tolerance: phases + unaccounted always sum exactly;
#: ``covered`` is whether unaccounted stayed under this fraction.
DEFAULT_TOLERANCE = 0.10

_PHASE_ORDER = {p: i for i, p in enumerate(PHASES)}

_FETCH_SPANS = (_trace.SPAN_KV_FETCH, _trace.SPAN_KVSTORE_FETCH)
_START_SPANS = (_trace.SPAN_SUBMIT, _trace.SPAN_QUEUED)


def _first(evs: Sequence[Dict[str, Any]], spans: Tuple[str, ...],
           after: float = float("-inf")) -> Optional[Dict[str, Any]]:
    for ev in evs:
        if ev["span"] in spans and ev["t"] >= after:
            return ev
    return None


def _event_rid(ev: Dict[str, Any]) -> Optional[str]:
    rid = ev.get("request_id")
    if rid is None:
        rid = (ev.get("kv") or {}).get("request_id")
    return None if rid is None else str(rid)


class _Segment:
    """One visit of the request to one scheduler process: submit (or an
    early ship-land) through a terminal span."""

    def __init__(self, proc: str, evs: List[Dict[str, Any]]) -> None:
        self.proc = proc
        self.evs = evs
        self.t_sub = (_first(evs, _START_SPANS) or {}).get("t")
        term = _first(evs, _trace.TERMINAL_SPANS)
        self.t_term = term.get("t") if term else None
        self.end_span = term.get("span") if term else None
        self.t_ship_land = (
            _first(evs, (_trace.SPAN_KV_SHIP_LAND,)) or {}
        ).get("t")
        ts = [ev["t"] for ev in evs]
        self.t_start = min(ts)
        self.t_end = max(ts)

    def order_key(self) -> float:
        return self.t_sub if self.t_sub is not None else self.t_start

    def intervals(self) -> List[Tuple[float, float, str, str, str]]:
        """Phase intervals within this segment: (start, end, phase,
        process, detail)."""
        evs = self.evs
        out: List[Tuple[float, float, str, str, str]] = []
        t_sub = self.t_sub
        fetch = _first(evs, _FETCH_SPANS)
        t_fetch = fetch.get("t") if fetch else None
        src = None
        if fetch is not None:
            src = (
                "store"
                if fetch["span"] == _trace.SPAN_KVSTORE_FETCH
                else "peer"
            )
        land = _first(
            evs, (_trace.SPAN_KV_LAND,),
            after=t_fetch if t_fetch is not None else float("-inf"),
        )
        t_land = land.get("t") if land else None
        if land is not None and land.get("source"):
            src = str(land["source"])
        admit = _first(evs, (_trace.SPAN_ADMITTED,))
        t_admit = admit.get("t") if admit else None
        first = _first(evs, (_trace.SPAN_FIRST_TOKEN,))
        t_first = first.get("t") if first else None
        ship = _first(evs, (_trace.SPAN_SHIPPED,))
        t_ship = ship.get("t") if ship else None
        t_term = self.t_term

        def _next(*cands: Optional[float]) -> Optional[float]:
            real = [c for c in cands if c is not None]
            return min(real) if real else None

        if t_sub is not None:
            e = _next(t_fetch, t_admit, t_ship, t_term)
            if e is not None and e > t_sub:
                out.append((t_sub, e, "queue", self.proc, ""))
        if t_fetch is not None:
            e = _next(t_land, t_admit, t_term)
            if e is not None and e > t_fetch:
                out.append(
                    (t_fetch, e, "kv_fetch", self.proc, src or "")
                )
        if t_land is not None:
            e = _next(t_admit, t_term)
            if e is not None and e > t_land:
                out.append((t_land, e, "transfer_park", self.proc, ""))
        if t_admit is not None:
            e = _next(t_first, t_ship, t_term)
            if e is not None and e > t_admit:
                detail = str((first or {}).get("mode") or "")
                if not detail and self.t_ship_land is not None:
                    detail = "warm"
                out.append((t_admit, e, "prefill", self.proc, detail))
        if t_ship is not None:
            s = _next(t_first)
            if s is None or s > t_ship:
                s = t_admit
            if s is not None and t_ship > s:
                out.append((s, t_ship, "ship", self.proc, "export"))
        if (
            t_first is not None
            and t_term is not None
            and t_term > t_first
            and (t_ship is None or t_ship >= t_term)
        ):
            out.append((t_first, t_term, "decode", self.proc, ""))
        return out


def _split_segments(
    proc: str, evs: List[Dict[str, Any]]
) -> List[_Segment]:
    """Split one process's events for a request into visit segments: a
    fresh ``submit`` after a terminal span starts a new visit (the same
    process can see a request twice — e.g. a migration bouncing back)."""
    segs: List[_Segment] = []
    cur: List[Dict[str, Any]] = []
    terminal_seen = False
    for ev in evs:
        if (
            ev["span"] in _START_SPANS
            and terminal_seen
            and cur
        ):
            segs.append(_Segment(proc, cur))
            cur, terminal_seen = [], False
        cur.append(ev)
        if ev["span"] in _trace.TERMINAL_SPANS:
            terminal_seen = True
    if cur:
        segs.append(_Segment(proc, cur))
    return segs


def assemble_anatomy(
    request_id: str,
    processes: Sequence[Dict[str, Any]],
    journal: Optional[Sequence[Dict[str, Any]]] = None,
    events: Optional[Sequence[Dict[str, Any]]] = None,
    tolerance: float = DEFAULT_TOLERANCE,
) -> Dict[str, Any]:
    """Stitch one request's cross-process phase ledger.

    ``processes`` is the ``ServeClient.trace_dumps()`` wire form: a list
    of ``{"name", "wall_offset", "traces", ["truncated"]}`` dicts (the
    :meth:`RequestTracer.dump` shape plus a display name). ``journal``
    is the driver-side journal's entries (its ``outcome`` record pins
    the client-observed end; its ``submit`` record is a start
    fallback). ``events`` is a merged typed-event list (wall-clock) —
    the hedge/failover/migration markers live there.

    Returns the ledger dict: ``phases`` rows (chronological, clipped to
    one non-overlapping timeline), ``totals`` per phase, ``observed_s``
    == ``accounted_s`` + ``unaccounted_s`` exactly, ``coverage``,
    ``covered`` (against ``tolerance``), ``markers``, the ``outcome``
    chain, ``processes`` seen, and truncation ``provenance``.
    """
    rid = str(request_id)
    per_proc: List[Tuple[str, List[Dict[str, Any]]]] = []
    truncated_procs: List[str] = []
    for i, proc in enumerate(processes):
        name = str(proc.get("name") or f"process{i}")
        off = float(proc.get("wall_offset") or 0.0)
        evs = (proc.get("traces") or {}).get(rid) or []
        if not evs:
            continue
        if rid in (proc.get("truncated") or ()) or any(
            ev.get("truncated") for ev in evs
        ):
            truncated_procs.append(name)
        per_proc.append((
            name,
            sorted(
                (dict(ev, t=float(ev["t"]) + off) for ev in evs),
                key=lambda e: e["t"],
            ),
        ))

    jr_submit_wall: Optional[float] = None
    jr_outcome_wall: Optional[float] = None
    jr_outcome: Optional[str] = None
    jr_phases: Optional[Dict[str, Any]] = None
    for entry in journal or ():
        if str(entry.get("request_id")) != rid:
            continue
        kind = entry.get("kind")
        if kind == "submit" and entry.get("t_wall") is not None:
            t = float(entry["t_wall"])
            if jr_submit_wall is None or t < jr_submit_wall:
                jr_submit_wall = t
        elif kind == "outcome" and entry.get("t_wall") is not None:
            t = float(entry["t_wall"])
            if jr_outcome_wall is None or t > jr_outcome_wall:
                jr_outcome_wall = t
                jr_outcome = entry.get("outcome")
                jr_phases = entry.get("phases")

    if not per_proc and jr_phases:
        # Offline journal-only mode: no rings survive (a captured
        # incident autopsied cold) — the outcome record's compact
        # ledger is the whole story.
        return ledger_from_phase_map(
            rid, jr_phases, outcome=jr_outcome or "unknown"
        )
    if not per_proc:
        return {"request_id": rid, "found": False}

    # -- client milestones + scheduler segments -------------------------
    client_proc = None
    t_recv = t_plan = t_csub = None
    segments: List[_Segment] = []
    for name, evs in per_proc:
        ev = _first(evs, (_trace.SPAN_CLIENT_RECV,))
        if ev is not None and t_recv is None:
            t_recv, client_proc = ev["t"], name
        ev = _first(evs, (_trace.SPAN_CLIENT_PLAN,))
        if ev is not None and t_plan is None:
            t_plan = ev["t"]
            client_proc = client_proc or name
        ev = _first(evs, (_trace.SPAN_CLIENT_SUBMIT,))
        if ev is not None and t_csub is None:
            t_csub = ev["t"]
            client_proc = client_proc or name
        sched_evs = [
            e for e in evs
            if e["span"] not in (
                _trace.SPAN_CLIENT_RECV,
                _trace.SPAN_CLIENT_PLAN,
                _trace.SPAN_CLIENT_SUBMIT,
            )
        ]
        if sched_evs and _first(sched_evs, _START_SPANS) is not None:
            segments.extend(_split_segments(name, sched_evs))
    segments.sort(key=_Segment.order_key)
    client_proc = client_proc or "client"

    # -- candidate intervals --------------------------------------------
    cand: List[Tuple[float, float, str, str, str]] = []
    if t_recv is not None:
        e = t_plan if t_plan is not None else t_csub
        if e is not None and e > t_recv:
            cand.append((t_recv, e, "batch_window", client_proc, ""))
    if t_plan is not None and t_csub is not None and t_csub > t_plan:
        cand.append((t_plan, t_csub, "route_plan", client_proc, ""))
    if t_csub is not None and segments:
        t0 = segments[0].order_key()
        if t0 > t_csub:
            cand.append((t_csub, t0, "client_wait", client_proc, "rpc"))
    for seg in segments:
        cand.extend(seg.intervals())
    # Inter-segment gaps: a shipped handoff becomes the ship transit
    # (split at the decode side's import mark when it exists); any
    # other re-drive (migration, failover, hedge) is client_wait.
    for prev, nxt in zip(segments, segments[1:]):
        t_from = prev.t_term if prev.t_term is not None else prev.t_end
        t_to = nxt.order_key()
        if t_to <= t_from:
            continue
        if prev.end_span == _trace.SPAN_SHIPPED:
            t_shl = nxt.t_ship_land
            if t_shl is not None and t_from < t_shl <= t_to:
                cand.append(
                    (t_from, t_shl, "ship", nxt.proc, "transit")
                )
                if t_to > t_shl:
                    cand.append((
                        t_shl, t_to, "client_wait", client_proc,
                        "re-drive",
                    ))
            else:
                cand.append((t_from, t_to, "ship", nxt.proc, "transit"))
        else:
            cand.append(
                (t_from, t_to, "client_wait", client_proc, "re-drive")
            )

    # -- observed window -------------------------------------------------
    all_t = [ev["t"] for _, evs in per_proc for ev in evs]
    starts = [
        t for t in (t_recv, t_csub, jr_submit_wall) if t is not None
    ]
    t_start = min(starts) if starts else min(all_t)
    last_term = max(
        (s.t_term for s in segments if s.t_term is not None),
        default=None,
    )
    ends = [t for t in (jr_outcome_wall, last_term) if t is not None]
    t_end = max(ends) if ends else max(all_t)
    if t_end < t_start:
        t_end = t_start
    if (
        last_term is not None
        and jr_outcome_wall is not None
        and jr_outcome_wall > last_term
    ):
        cand.append((
            last_term, jr_outcome_wall, "stream_gap", client_proc, "",
        ))

    # -- clip to one non-overlapping timeline ---------------------------
    cand.sort(key=lambda iv: (iv[0], _PHASE_ORDER.get(iv[2], 99)))
    rows: List[Dict[str, Any]] = []
    cursor = t_start
    for s, e, phase, proc, detail in cand:
        s = max(s, cursor)
        e = min(e, t_end)
        if e <= s:
            continue
        row = {
            "phase": phase,
            "process": proc,
            "start_s": round(s - t_start, 6),
            "duration_s": round(e - s, 6),
        }
        if detail:
            row["detail"] = detail
        rows.append(row)
        cursor = e

    totals: Dict[str, float] = {}
    for row in rows:
        totals[row["phase"]] = round(
            totals.get(row["phase"], 0.0) + row["duration_s"], 6
        )
    observed = round(t_end - t_start, 6)
    accounted = round(sum(r["duration_s"] for r in rows), 6)
    unaccounted = round(max(0.0, observed - accounted), 6)

    # -- markers + outcome chain ----------------------------------------
    markers: List[str] = []
    for ev in events or ():
        if _event_rid(ev) != rid:
            continue
        name = ev.get("name")
        if name == "request_hedged" and "hedged" not in markers:
            markers.append("hedged")
        elif name == "failover" and "failover" not in markers:
            markers.append("failover")
        elif (
            name in ("cancel", "expire")
            and (ev.get("migrated") or (ev.get("kv") or {}).get(
                "migrated"
            ))
            and "migrated" not in markers
        ):
            markers.append("migrated")
    # Overlapping segments without a ship handoff = a hedge raced two
    # replicas (the loser's spans were clipped out of the timeline).
    for prev, nxt in zip(segments, segments[1:]):
        if (
            prev.t_term is not None
            and nxt.order_key() < prev.t_term
            and "hedged" not in markers
        ):
            markers.append("hedged")
    outcome_chain = [
        {
            "process": seg.proc,
            "outcome": {
                _trace.SPAN_FINISH: "finished",
                _trace.SPAN_CANCEL: "cancelled",
                _trace.SPAN_EXPIRE: "expired",
                _trace.SPAN_SHIPPED: "shipped",
            }.get(seg.end_span or "", seg.end_span or "open"),
        }
        for seg in segments
    ]
    if jr_outcome is not None:
        outcome_chain.append(
            {"process": client_proc, "outcome": jr_outcome}
        )

    provenance: List[str] = []
    if truncated_procs:
        provenance.append(
            "ring wrapped on %s: early spans lost; unaccounted time "
            "includes the pre-wrap window" % ", ".join(truncated_procs)
        )

    return {
        "request_id": rid,
        "found": True,
        "phases": rows,
        "totals": totals,
        "observed_s": observed,
        "accounted_s": accounted,
        "unaccounted_s": unaccounted,
        "coverage": round(accounted / observed, 4) if observed else 1.0,
        "covered": (
            unaccounted <= tolerance * observed if observed else True
        ),
        "tolerance": tolerance,
        "markers": markers,
        "outcome": outcome_chain,
        "processes": [name for name, _ in per_proc],
        "truncated": bool(truncated_procs),
        "provenance": provenance,
    }


def ledger_from_phase_map(
    request_id: str,
    phases: Dict[str, Any],
    outcome: str = "unknown",
    process: str = "journal",
) -> Dict[str, Any]:
    """A ledger from ONE compact ``{phase: seconds}`` map (a journal
    outcome record's serialized ledger) — the offline-autopsy shape
    ``rlt why <journal> <id>`` renders with no live fleet. Scheduler-
    local by construction: cross-process phases are absent, and the
    observed window is the map's own sum (coverage is exact)."""
    detail = {
        k: v for k, v in phases.items()
        if not isinstance(v, (int, float))
    }
    rows: List[Dict[str, Any]] = []
    start = 0.0
    for phase in PHASES:
        v = phases.get(phase)
        if not isinstance(v, (int, float)) or v <= 0:
            continue
        row = {
            "phase": phase,
            "process": process,
            "start_s": round(start, 6),
            "duration_s": round(float(v), 6),
        }
        if phase == "kv_fetch" and detail.get("kv_fetch_source"):
            row["detail"] = str(detail["kv_fetch_source"])
        rows.append(row)
        start += float(v)
    observed = round(sum(r["duration_s"] for r in rows), 6)
    return {
        "request_id": str(request_id),
        "found": bool(rows),
        "phases": rows,
        "totals": {r["phase"]: r["duration_s"] for r in rows},
        "observed_s": observed,
        "accounted_s": observed,
        "unaccounted_s": 0.0,
        "coverage": 1.0,
        "covered": True,
        "tolerance": 0.0,
        "markers": [],
        "outcome": [{"process": process, "outcome": outcome}],
        "processes": [process],
        "truncated": False,
        "provenance": [
            "journal outcome record (scheduler-local phases only; "
            "cross-process phases not captured)"
        ],
    }


# -- aggregation (fleet decomposition, replay diff) ---------------------
def aggregate_phases(
    phase_maps: Sequence[Dict[str, Any]],
) -> Dict[str, Dict[str, float]]:
    """Fold compact ``{phase: seconds}`` maps into per-phase percentile
    rows (nearest-rank) — the shape the fleet ``phases`` block and the
    replay phase diff share."""
    by_phase: Dict[str, List[float]] = {}
    for m in phase_maps:
        for phase, v in (m or {}).items():
            if isinstance(v, (int, float)):
                by_phase.setdefault(phase, []).append(float(v))
    out: Dict[str, Dict[str, float]] = {}
    for phase, vals in by_phase.items():
        vals.sort()
        n = len(vals)

        def pct(q: float) -> float:
            return vals[min(n - 1, int(round(q * (n - 1))))]

        out[phase] = {
            "p50_s": round(pct(0.50), 6),
            "p95_s": round(pct(0.95), 6),
            "p99_s": round(pct(0.99), 6),
            "mean_s": round(sum(vals) / n, 6),
            "count": n,
        }
    return out


def breach_attribution(
    phases_block: Optional[Dict[str, Any]],
    top: int = 3,
    min_share: float = 0.05,
) -> List[Tuple[str, float]]:
    """Top contributing phases by share of windowed request time.

    ``phases_block`` is a metrics-snapshot ``phases`` block (or its
    ``by_phase`` sub-dict, or an :func:`aggregate_phases` result).
    Shares weight each phase by its total windowed seconds (mean ×
    count), so a rare-but-huge phase and a common-but-fat one compare
    honestly. Returns ``[(phase, share), ...]`` best-first, dropping
    slivers under ``min_share``.
    """
    if not phases_block:
        return []
    by_phase = phases_block.get("by_phase", phases_block)
    weights: Dict[str, float] = {}
    for phase, row in by_phase.items():
        if not isinstance(row, dict):
            continue
        w = float(row.get("mean_s", 0.0)) * int(row.get("count", 0))
        if w > 0:
            weights[phase] = w
    total = sum(weights.values())
    if total <= 0:
        return []
    ranked = sorted(
        ((p, w / total) for p, w in weights.items()),
        key=lambda kv: -kv[1],
    )
    return [
        (p, round(s, 4)) for p, s in ranked[:top] if s >= min_share
    ]


def format_attribution(shares: Sequence[Tuple[str, float]]) -> str:
    """``[(phase, share)]`` → ``"kv_fetch 58%, queue 22%"``."""
    return ", ".join(f"{p} {round(100 * s)}%" for p, s in shares)


# -- rendering ----------------------------------------------------------
def render_anatomy(ledger: Dict[str, Any]) -> str:
    """The human face of one ledger (``rlt why``): a timeline table with
    per-phase durations, the process each ran on, the outcome chain,
    and the coverage line."""
    rid = ledger.get("request_id", "?")
    if not ledger.get("found"):
        return f"request {rid}: not found (rings rotated or wrong id?)"
    lines: List[str] = []
    chain = " -> ".join(
        f"{o['outcome']}@{o['process']}" for o in ledger["outcome"]
    ) or "open"
    obs_ms = 1e3 * ledger["observed_s"]
    lines.append(f"request {rid} — outcome: {chain}")
    lines.append(
        "observed %.3f ms = accounted %.3f ms + unaccounted %.3f ms "
        "(coverage %.1f%%%s)"
        % (
            obs_ms,
            1e3 * ledger["accounted_s"],
            1e3 * ledger["unaccounted_s"],
            100 * ledger["coverage"],
            "" if ledger.get("covered") else
            " — BELOW tolerance %.0f%%" % (
                100 * (1 - ledger.get("tolerance", DEFAULT_TOLERANCE))
            ),
        )
    )
    if ledger.get("markers"):
        lines.append("markers: " + ", ".join(ledger["markers"]))
    for note in ledger.get("provenance") or ():
        lines.append("note: " + note)
    header = f"  {'phase':<14} {'process':<12} {'start_ms':>10} {'dur_ms':>10}  detail"
    lines.append(header)
    for row in ledger["phases"]:
        lines.append(
            "  %-14s %-12s %10.3f %10.3f  %s"
            % (
                row["phase"],
                row["process"],
                1e3 * row["start_s"],
                1e3 * row["duration_s"],
                row.get("detail", ""),
            )
        )
    if ledger["unaccounted_s"] > 0:
        lines.append(
            "  %-14s %-12s %10s %10.3f  %s"
            % (
                "unaccounted", "-", "-",
                1e3 * ledger["unaccounted_s"],
                "truncated rings" if ledger.get("truncated") else "",
            )
        )
    tot = ledger.get("totals") or {}
    if tot:
        lines.append(
            "totals: " + "  ".join(
                f"{p}={1e3 * tot[p]:.3f}ms"
                for p in PHASES if p in tot
            )
        )
    return "\n".join(lines)


def anatomy_from_client(
    client: Any,
    request_id: str,
    n: int = 64,
    tolerance: float = DEFAULT_TOLERANCE,
) -> Dict[str, Any]:
    """Assemble a ledger from a live :class:`ServeClient`: its
    cross-process trace dumps, its driver-side journal, and the merged
    event rings (driver + replicas) — the ``/why`` route's collector."""
    processes = client.trace_dumps(n)
    journal: List[Dict[str, Any]] = []
    jr = getattr(client, "journal", None)
    if jr is not None:
        try:
            journal = [
                e for e in (jr.dump().get("entries") or ())
                if str(e.get("request_id")) == str(request_id)
            ]
        except Exception:  # noqa: BLE001 - forensics best-effort
            journal = []
    events: List[Dict[str, Any]] = []
    try:
        events = list(client.recent_events(512))
    except Exception:  # noqa: BLE001 - replica rings best-effort
        pass
    ev_log = getattr(client, "_events", None)
    if ev_log is not None:
        try:
            events.extend(ev_log.tail(512))
        except Exception:  # noqa: BLE001
            pass
    return assemble_anatomy(
        request_id, processes, journal=journal, events=events,
        tolerance=tolerance,
    )
