"""Structured event log: a bounded, process-wide ring of typed events.

The forensic substrate of the ACTIVE observability half (obs.health
consumes it for verdict transitions, obs.blackbox ships its tail in
every flight-recorder bundle): serve, trainer, and fabric code record
discrete happenings — admission bursts, cancels, epoch boundaries,
actor deaths, heartbeat gaps, health verdict changes — as
``(ts, level, subsystem, name, kv)`` tuples in one ring buffer.

Recording is a tuple append under one lock (the same hot-path budget as
:class:`obs.trace.RequestTracer`), so the scheduler's fold loop can emit
without measurable cost; rendering (dicts, JSONL) happens at read time.
Unlike the tracer — which answers "what happened to request X" — the
event log answers "what happened to the PROCESS": it is keyed by
subsystem, carries a severity level, and uses wall-clock timestamps so
an exported tail lines up with external logs.

One process-global log (:func:`get_event_log`) mirrors the registry's
process-global default: each process (driver, replica actor, training
worker) accumulates its own and exports it whole.
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

#: Severity levels, mildest first (no filtering on the record path —
#: the ring is small and the reader filters).
LEVELS = ("info", "warn", "error")


class EventLog:
    """Bounded ring of ``(ts, level, subsystem, name, kv)`` events."""

    def __init__(self, capacity: int = 4096, enabled: bool = True) -> None:
        self.capacity = int(capacity)
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=self.capacity)
        # Monotonic per-ring sequence: every recorded event gets the
        # next number, surviving ring rotation — the `/events?since=`
        # cursor external tails (rlt alerts --follow, sinks) resume
        # from without re-downloading the ring.
        self._seq = 0

    # -- hot path ---------------------------------------------------------
    def record(
        self, subsystem: str, name: str, level: str = "info", **kv: Any
    ) -> None:
        """Append one event; ``kv`` must be JSON-serializable scalars
        (they ride into bundles and the JSONL export verbatim)."""
        if not self.enabled:
            return
        with self._lock:
            self._seq += 1
            self._events.append(
                (time.time(), level, subsystem, name, kv or None, self._seq)
            )

    # -- read side --------------------------------------------------------
    def tail(
        self,
        n: Optional[int] = None,
        subsystem: Optional[str] = None,
        name: Optional[str] = None,
    ) -> List[Dict[str, Any]]:
        """The newest ``n`` matching events (oldest first, as dicts)."""
        with self._lock:
            events = list(self._events)
        out = []
        for row in events:
            ts, level, sub, nm, kv = row[:5]
            seq = row[5] if len(row) > 5 else None
            if subsystem is not None and sub != subsystem:
                continue
            if name is not None and nm != name:
                continue
            ev: Dict[str, Any] = {
                "ts": ts, "level": level, "subsystem": sub, "name": nm,
            }
            if seq is not None:
                ev["seq"] = seq
            if kv:
                ev.update(kv)
            out.append(ev)
        return out if n is None else out[-int(n):]

    def to_jsonl(self, n: Optional[int] = None) -> str:
        """JSONL export (one event per line) — the bundle format."""
        return "\n".join(
            json.dumps(ev, default=str) for ev in self.tail(n)
        ) + ("\n" if len(self) else "")

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)


#: Process-global default log (mirrors obs.registry.get_registry()).
_LOG = EventLog()


def get_event_log() -> EventLog:
    return _LOG
