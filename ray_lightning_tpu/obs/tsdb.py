"""Multi-resolution ring TSDB: bounded in-process time-series storage.

Everything observable so far is point-in-time — a ``/metrics`` scrape,
a ``/fleet`` snapshot ring, a stats payload — so "decode rate has been
sagging 2%/hour since the config push" is invisible. This module is the
retention layer under obs.watchtower: a handful of named series, each
kept at several resolutions ("rungs", e.g. 1s x 5min / 10s x 1h /
60s x 12h), every rung a fixed-size ring — memory is bounded by
construction, no matter how long the process lives.

Two ingestion shapes, mirroring Prometheus semantics:

- **gauges** are sampled: :meth:`RingTSDB.record` writes the value into
  the current bucket of every rung (last write in a bucket wins);
- **counters** are stored as rates: :meth:`RingTSDB.ingest_prometheus`
  parses exposition text (:func:`obs.registry.parse_prometheus_text`),
  diffs each ``*_total`` family against the previous ingest, and
  records ``delta/dt`` under ``<family>{labels}:rate`` — the series an
  alert rule can threshold directly. A counter reset (value decreased,
  e.g. a replica restart) restarts the delta from the new value instead
  of producing a negative spike.

Cardinality is capped (``max_series``): series beyond the cap are
dropped and counted, never silently grown — a misbehaving label
explosion degrades retention, not memory.

Reads: :meth:`query` picks the best rung for a requested ``since``/
``step`` and returns ``[(ts, value), ...]`` — the ``/query`` httpd
route and ``rlt plot``'s feed. All clocks are injectable via explicit
``ts`` arguments (the watchtower tests drive a fake clock through).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_lightning_tpu.obs.registry import parse_prometheus_text

#: Default resolution ladder: (bucket seconds, bucket count) — 5 min at
#: 1s, 1 h at 10s, 12 h at 60s. Memory: sum(counts) floats per series.
DEFAULT_RUNGS: Tuple[Tuple[float, int], ...] = (
    (1.0, 300),
    (10.0, 360),
    (60.0, 720),
)


class RingTSDB:
    """Bounded multi-resolution store of named scalar series."""

    def __init__(
        self,
        rungs: Sequence[Tuple[float, int]] = DEFAULT_RUNGS,
        max_series: int = 512,
        registry: Optional[Any] = None,
    ) -> None:
        if not rungs:
            raise ValueError("RingTSDB needs at least one rung")
        self.rungs = tuple(
            (float(step), int(cap)) for step, cap in
            sorted(rungs, key=lambda r: r[0])
        )
        if any(step <= 0 or cap <= 0 for step, cap in self.rungs):
            raise ValueError(f"invalid TSDB rungs {rungs!r}")
        self.max_series = int(max_series)
        self._lock = threading.Lock()
        #: series name -> per-rung ring of (bucket_start_ts, value).
        self._series: Dict[str, List[deque]] = {}
        #: counter-delta state: series key -> (ts, cumulative value).
        self._last_counter: Dict[str, Tuple[float, float]] = {}
        self._dropped = 0
        self._points = 0
        self._reg = None
        if registry is not None:
            self._reg = {
                "series": registry.gauge(
                    "rlt_tsdb_series", "Series resident in the ring TSDB"
                ),
                "points": registry.counter(
                    "rlt_tsdb_points_total", "Samples recorded to the TSDB"
                ),
                "dropped": registry.counter(
                    "rlt_tsdb_dropped_series_total",
                    "Series rejected by the TSDB cardinality cap",
                ),
            }

    # -- write side -------------------------------------------------------
    def record(self, name: str, value: float, ts: Optional[float] = None) -> bool:
        """Sample a gauge: write ``value`` into the current bucket of
        every rung (last write in a bucket wins). Returns False when the
        series was rejected by the cardinality cap."""
        ts = time.time() if ts is None else float(ts)
        value = float(value)
        with self._lock:
            rings = self._series.get(name)
            if rings is None:
                if len(self._series) >= self.max_series:
                    self._dropped += 1
                    if self._reg is not None:
                        self._reg["dropped"].inc(1)
                    return False
                rings = [deque(maxlen=cap) for _, cap in self.rungs]
                self._series[name] = rings
                if self._reg is not None:
                    self._reg["series"].set(len(self._series))
            for (step, _cap), ring in zip(self.rungs, rings):
                bucket = int(ts // step) * step
                if ring and ring[-1][0] == bucket:
                    ring[-1] = (bucket, value)
                else:
                    ring.append((bucket, value))
            self._points += 1
        if self._reg is not None:
            self._reg["points"].inc(1)
        return True

    def record_counter(
        self, name: str, cumulative: float, ts: Optional[float] = None
    ) -> None:
        """Observe a cumulative counter; the stored series is its RATE
        (per second), named ``<name>:rate``. The first observation only
        seeds the delta state; a decrease (counter reset) restarts from
        the new cumulative value."""
        ts = time.time() if ts is None else float(ts)
        cumulative = float(cumulative)
        with self._lock:
            prev = self._last_counter.get(name)
            self._last_counter[name] = (ts, cumulative)
        if prev is None:
            return
        prev_ts, prev_val = prev
        dt = ts - prev_ts
        if dt <= 0:
            return
        delta = cumulative - prev_val
        if delta < 0:  # counter reset: the new process starts from 0
            delta = cumulative
        self.record(f"{name}:rate", delta / dt, ts=ts)

    def ingest_prometheus(
        self,
        text: str,
        ts: Optional[float] = None,
        families: Optional[Sequence[str]] = None,
    ) -> int:
        """One scrape of exposition text into the TSDB: ``*_total``
        families become ``:rate`` series via successive deltas, ``_bucket``
        histogram internals are skipped, everything else is sampled as a
        gauge. ``families`` (optional prefix list) bounds which metric
        families are retained — the watchtower passes the short list it
        alerts on rather than retaining every label of every family.
        Returns the number of samples recorded."""
        ts = time.time() if ts is None else float(ts)
        wrote = 0
        for name, by_label in parse_prometheus_text(text).items():
            if families is not None and not any(
                name.startswith(p) for p in families
            ):
                continue
            if name.endswith("_bucket"):
                continue  # histogram internals: quantiles live upstream
            for labels, value in by_label.items():
                key = f"{name}{labels}"
                if name.endswith("_total") or name.endswith(("_sum", "_count")):
                    self.record_counter(key, value, ts=ts)
                    wrote += 1
                else:
                    wrote += bool(self.record(key, value, ts=ts))
        return wrote

    # -- read side --------------------------------------------------------
    def series_names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def _pick_rung(
        self, since: Optional[float], step: Optional[float], now: float
    ) -> int:
        """Best rung index: honor an explicit ``step`` (smallest rung
        >= it), else the finest rung whose span covers ``since``."""
        if step is not None:
            for i, (s, _cap) in enumerate(self.rungs):
                if s >= float(step) - 1e-9:
                    return i
            return len(self.rungs) - 1
        if since is not None:
            span = now - float(since)
            for i, (s, cap) in enumerate(self.rungs):
                if s * cap >= span:
                    return i
            return len(self.rungs) - 1
        return 0

    def query(
        self,
        series: str,
        since: Optional[float] = None,
        step: Optional[float] = None,
        now: Optional[float] = None,
    ) -> Dict[str, Any]:
        """The ``/query`` payload for one series: the best-rung points
        newer than ``since`` (all retained points when omitted). Unknown
        series answer ``found: false`` plus a bounded name sample so a
        client can self-correct a typo."""
        now = time.time() if now is None else float(now)
        with self._lock:
            rings = self._series.get(series)
            if rings is None:
                return {
                    "series": series,
                    "found": False,
                    "available": sorted(self._series)[:64],
                }
            idx = self._pick_rung(since, step, now)
            pts = [
                [t, v] for t, v in rings[idx]
                if since is None or t >= float(since)
            ]
        return {
            "series": series,
            "found": True,
            "step_s": self.rungs[idx][0],
            "points": pts,
        }

    def values(
        self,
        series: str,
        window_s: float,
        now: Optional[float] = None,
    ) -> List[float]:
        """Just the values in the trailing window (finest rung that
        covers it) — the alert engine's evaluation feed."""
        now = time.time() if now is None else float(now)
        q = self.query(series, since=now - float(window_s), now=now)
        return [v for _t, v in q.get("points", [])] if q["found"] else []

    def latest(
        self, series: str, now: Optional[float] = None
    ) -> Optional[Tuple[float, float]]:
        """Newest (ts, value) across the finest rung, None when the
        series is unknown or empty."""
        with self._lock:
            rings = self._series.get(series)
            if not rings or not rings[0]:
                return None
            return tuple(rings[0][-1])

    def to_dict(self) -> Dict[str, Any]:
        """Compact self-description (rides /alerts and debug bundles)."""
        with self._lock:
            return {
                "rungs": [list(r) for r in self.rungs],
                "series": len(self._series),
                "max_series": self.max_series,
                "dropped_series": self._dropped,
                "points": self._points,
            }
