"""Watchtower: retained telemetry + a burn-rate alert engine + canary.

The layer that notices a regression BEFORE a user does. Three pieces,
composed over :class:`obs.tsdb.RingTSDB`:

1. **Feeds** — each tick the watchtower samples the latest fleet
   snapshot (queue depth, tokens/s, goodput, healthy count, the
   SLO-breach ratio diffed from cumulative counters) into named series,
   and optionally ingests the driver's aggregated ``/metrics`` text so
   counter families land as ``:rate`` series.
2. **Alert engine** — declarative :class:`AlertRule`\\ s (static
   ``threshold``, ``absence``/flatline, and multi-window multi-burn-rate
   over the SLO-breach ratio, the SRE-literature shape: a FAST window
   catches a cliff, a SLOW window must agree so a blip doesn't page)
   evaluated each tick with a pending -> firing -> resolved state
   machine: a rule must breach ``for_ticks`` consecutive evaluations to
   fire (pending hold), stay clean ``resolve_ticks`` to resolve
   (hysteresis), and while firing re-notifies at most every
   ``renotify_s`` (dedup). Transitions emit ``alert_firing`` /
   ``alert_resolved`` events carrying the triggering value AND the top
   anatomy phases (PR 19's breach attribution) — the page says *what*
   and *why* in one line.
3. **Canary lane** — a tiny fixed-seed probe submitted periodically
   under the reserved ``_canary`` tenant at floor priority, its
   TTFT / decode rate / exactness recorded as dedicated series and
   checked against a recorded baseline envelope. A wedged-but-
   heartbeating replica or a perf regression after a weight push is
   caught with zero organic traffic. Canary traffic is excluded from
   organic accounting end to end (cost ledger, goodput, autoscaler
   pressure — see serve.metrics.CANARY_TENANT).

Sinks follow the kvstore ``s3://`` pattern: the :class:`LogSink` is
fully real; the :class:`WebhookSink` is webhook-SHAPED — URL parsing,
payload shaping, and delivery accounting are real so config and
journals round-trip it, but the default transport records the would-be
POST instead of opening a socket (inject ``post_fn`` to make it real).

All clocks are injectable; the engine is driven by ``Watchtower.tick``
(its own daemon thread in ``rlt serve``, a fake clock in tests).
"""
from __future__ import annotations

import json
import logging
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple
from urllib.parse import urlparse

from ray_lightning_tpu.obs.anatomy import (
    breach_attribution,
    format_attribution,
)
from ray_lightning_tpu.obs.tsdb import RingTSDB

logger = logging.getLogger("rlt.watchtower")

#: The reserved canary tenant — must match serve.metrics.CANARY_TENANT
#: (kept as a literal here so obs does not import serve).
CANARY_TENANT = "_canary"

#: Floor priority for canary probes: the pending heap pops the SMALLEST
#: priority first, so the probe never displaces organic work.
CANARY_PRIORITY = 1_000_000

_SEVERITY_RANK = {"error": 0, "warn": 1, "info": 2}

_VERDICT_SCORE = {"healthy": 1.0, "degraded": 0.5, "unhealthy": 0.0}


# -- rules ---------------------------------------------------------------
@dataclass
class AlertRule:
    """One declarative rule. ``kind``:

    - ``threshold``: latest sample of ``series`` (within ``window_s``)
      compared ``op`` (``>`` / ``<``) against ``threshold``;
    - ``absence``: no new sample on ``series`` for ``window_s`` (the
      feed died); with ``flatline=True`` also breaches when samples
      keep arriving but the value has not changed across the window;
    - ``burn_rate``: mean of ``series`` over ``fast_window_s`` exceeds
      ``fast_burn`` AND mean over ``slow_window_s`` exceeds
      ``slow_burn`` — both windows must agree.

    Lifecycle: ``for_ticks`` consecutive breaching evaluations to fire,
    ``resolve_ticks`` consecutive clean ones to resolve, ``renotify_s``
    between repeat notifications while firing.
    """

    name: str
    kind: str
    series: str
    op: str = ">"
    threshold: float = 0.0
    window_s: float = 30.0
    flatline: bool = False
    fast_window_s: float = 60.0
    slow_window_s: float = 600.0
    fast_burn: float = 0.1
    slow_burn: float = 0.05
    for_ticks: int = 2
    resolve_ticks: int = 2
    renotify_s: float = 300.0
    severity: str = "warn"

    def __post_init__(self) -> None:
        if self.kind not in ("threshold", "absence", "burn_rate"):
            raise ValueError(
                f"alert rule {self.name!r}: unknown kind {self.kind!r} "
                "(threshold | absence | burn_rate)"
            )
        if self.op not in (">", "<"):
            raise ValueError(
                f"alert rule {self.name!r}: op must be '>' or '<'"
            )
        if self.severity not in _SEVERITY_RANK:
            raise ValueError(
                f"alert rule {self.name!r}: severity {self.severity!r} "
                f"not in {sorted(_SEVERITY_RANK)}"
            )

    def to_dict(self) -> Dict[str, Any]:
        d = {
            "name": self.name, "kind": self.kind, "series": self.series,
            "for_ticks": self.for_ticks, "resolve_ticks": self.resolve_ticks,
            "renotify_s": self.renotify_s, "severity": self.severity,
        }
        if self.kind == "threshold":
            d.update(op=self.op, threshold=self.threshold,
                     window_s=self.window_s)
        elif self.kind == "absence":
            d.update(window_s=self.window_s, flatline=self.flatline)
        else:
            d.update(fast_window_s=self.fast_window_s,
                     slow_window_s=self.slow_window_s,
                     fast_burn=self.fast_burn, slow_burn=self.slow_burn)
        return d


def parse_alert_rules(obj: Any) -> List[AlertRule]:
    """Rules from config: a list of rule dicts, or a mapping
    ``{name: rule_dict}`` (the name key wins). Unknown fields are
    rejected loudly — a typoed threshold must not silently never fire."""
    if obj is None:
        return []
    rows: List[Dict[str, Any]] = []
    if isinstance(obj, dict):
        for name, row in obj.items():
            if not isinstance(row, dict):
                raise ValueError(
                    f"alert rule {name!r}: expected a mapping, got {row!r}"
                )
            rows.append({"name": str(name), **row})
    elif isinstance(obj, (list, tuple)):
        rows = [dict(r) for r in obj]
    else:
        raise ValueError(
            f"alert rules: expected a list or mapping, got {type(obj).__name__}"
        )
    allowed = set(AlertRule.__dataclass_fields__)
    out = []
    for row in rows:
        unknown = set(row) - allowed
        if unknown:
            raise ValueError(
                f"alert rule {row.get('name', '?')!r}: unknown fields "
                f"{sorted(unknown)} (allowed: {sorted(allowed)})"
            )
        out.append(AlertRule(**row))
    return out


def default_rules() -> List[AlertRule]:
    """The always-on fleet rules ``rlt serve`` installs (overridable
    via ``--serve.alerts_rules``)."""
    return [
        AlertRule(
            name="slo_burn_rate", kind="burn_rate",
            series="fleet.slo_breach_ratio",
            fast_window_s=60.0, slow_window_s=600.0,
            fast_burn=0.1, slow_burn=0.05,
            for_ticks=2, resolve_ticks=2, severity="error",
        ),
        AlertRule(
            name="replica_unhealthy", kind="threshold",
            series="fleet.unhealthy", op=">", threshold=0.0,
            window_s=30.0, for_ticks=3, resolve_ticks=2,
        ),
        AlertRule(
            name="telemetry_absent", kind="absence",
            series="fleet.replicas", window_s=30.0,
            for_ticks=1, resolve_ticks=1,
        ),
        AlertRule(
            name="kvstore_write_errors", kind="threshold",
            series="fleet.kvstore_write_errors:rate", op=">",
            threshold=0.0, window_s=60.0, for_ticks=2,
        ),
    ]


def canary_rules(baseline: Optional[Dict[str, Any]] = None) -> List[AlertRule]:
    """Rules the canary lane adds: exactness is always-on (a wrong
    token is a correctness incident, fires on the first probe), the
    latency/rate envelope rules need a recorded baseline."""
    rules = [
        AlertRule(
            name="canary_exactness", kind="threshold",
            series="canary.exact", op="<", threshold=1.0,
            window_s=900.0, for_ticks=1, resolve_ticks=1,
            severity="error",
        ),
        AlertRule(
            name="canary_absent", kind="absence",
            series="canary.exact", window_s=120.0,
            for_ticks=1, resolve_ticks=1,
        ),
    ]
    if baseline:
        rules.append(AlertRule(
            name="canary_envelope", kind="threshold",
            series="canary.deviation", op=">", threshold=1.0,
            window_s=900.0, for_ticks=2, resolve_ticks=2,
        ))
    return rules


# -- sinks ---------------------------------------------------------------
class LogSink:
    """The real sink: transitions land in the process log (and a small
    ring so ``/alerts`` can show recent deliveries)."""

    name = "log"

    def __init__(self, capacity: int = 256) -> None:
        self.delivered: deque = deque(maxlen=capacity)

    def notify(self, payload: Dict[str, Any]) -> None:
        self.delivered.append(dict(payload))
        msg = (
            f"alert {payload.get('state')}: {payload.get('rule')} "
            f"({payload.get('detail')})"
        )
        if payload.get("state") == "firing":
            logger.warning(msg)
        else:
            logger.info(msg)


class WebhookSink:
    """Webhook-SHAPED sink, stub transport (the kvstore ``s3://``
    pattern): the URL is parsed and validated, every notification is
    shaped into the POST that WOULD go out (json body, content-type)
    and recorded in ``sent`` — but no socket opens unless a real
    ``post_fn(url, body_bytes, headers)`` is injected."""

    name = "webhook"

    def __init__(
        self,
        url: str,
        post_fn: Optional[Callable[[str, bytes, Dict[str, str]], Any]] = None,
        capacity: int = 256,
    ) -> None:
        parsed = urlparse(str(url))
        if parsed.scheme not in ("http", "https") or not parsed.netloc:
            raise ValueError(
                f"webhook sink URL {url!r} is not http(s)://host[/path]"
            )
        self.url = str(url)
        self._post = post_fn
        self.sent: deque = deque(maxlen=capacity)
        self.errors = 0

    def notify(self, payload: Dict[str, Any]) -> None:
        body = json.dumps(payload, default=str).encode()
        headers = {"Content-Type": "application/json"}
        self.sent.append({"url": self.url, "body": body.decode()})
        if self._post is None:
            return  # stub transport: the request is shaped, not sent
        try:
            self._post(self.url, body, headers)
        except Exception as exc:  # noqa: BLE001 - a dead webhook must
            self.errors += 1  # never take down the alert engine
            logger.warning("webhook sink %s failed: %s", self.url, exc)


# -- engine --------------------------------------------------------------
@dataclass
class _RuleState:
    state: str = "ok"  # ok | pending | firing
    consecutive_bad: int = 0
    consecutive_ok: int = 0
    since_ts: Optional[float] = None
    fired_ts: Optional[float] = None
    last_notify_ts: Optional[float] = None
    value: Optional[float] = None
    detail: str = ""
    fires: int = 0
    resolves: int = 0


class AlertEngine:
    """Evaluates rules over the TSDB each tick and owns alert state."""

    def __init__(
        self,
        tsdb: RingTSDB,
        rules: Sequence[AlertRule],
        events: Optional[Any] = None,
        sinks: Sequence[Any] = (),
        registry: Optional[Any] = None,
        attribution_fn: Optional[Callable[[], str]] = None,
        clock: Callable[[], float] = time.time,
    ) -> None:
        self.tsdb = tsdb
        self.rules = list(rules)
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate alert rule names in {names}")
        self._events = events
        self._sinks = list(sinks)
        self._attribution_fn = attribution_fn
        self._clock = clock
        self._lock = threading.Lock()
        self._state: Dict[str, _RuleState] = {
            r.name: _RuleState() for r in self.rules
        }
        self._evaluations = 0
        self._reg = None
        if registry is not None:
            self._reg = {
                "evals": registry.counter(
                    "rlt_alert_evaluations_total",
                    "Alert engine evaluation ticks",
                ),
                "transitions": registry.counter(
                    "rlt_alert_transitions_total",
                    "Alert state transitions, by target state",
                ),
                "firing": registry.gauge(
                    "rlt_alert_firing", "Rules currently in the firing state"
                ),
                "notifications": registry.counter(
                    "rlt_alert_notifications_total",
                    "Alert notifications delivered, by sink",
                ),
            }

    # -- rule conditions --------------------------------------------------
    def _eval_rule(
        self, rule: AlertRule, now: float
    ) -> Tuple[bool, Optional[float], str]:
        if rule.kind == "threshold":
            vals = self.tsdb.values(rule.series, rule.window_s, now=now)
            if not vals:
                return False, None, f"{rule.series}: no samples"
            v = vals[-1]
            bad = v > rule.threshold if rule.op == ">" else v < rule.threshold
            return bad, v, (
                f"{rule.series}={round(v, 6)} {rule.op} {rule.threshold}"
            )
        if rule.kind == "absence":
            last = self.tsdb.latest(rule.series)
            if last is None:
                # Startup grace: a series that never reported is the
                # feed not having started, not the feed having died.
                return False, None, f"{rule.series}: never reported"
            age = now - last[0]
            if age > rule.window_s:
                return True, last[1], (
                    f"{rule.series}: no samples for {round(age, 1)}s "
                    f"(window {rule.window_s}s)"
                )
            if rule.flatline:
                vals = self.tsdb.values(rule.series, rule.window_s, now=now)
                if len(vals) >= 3 and max(vals) == min(vals):
                    return True, vals[-1], (
                        f"{rule.series}: flatlined at {round(vals[-1], 6)} "
                        f"over {rule.window_s}s"
                    )
            return False, last[1], f"{rule.series}: live"
        # burn_rate: both windows must agree.
        fast = self.tsdb.values(rule.series, rule.fast_window_s, now=now)
        slow = self.tsdb.values(rule.series, rule.slow_window_s, now=now)
        if not fast or not slow:
            return False, None, f"{rule.series}: no samples"
        f_mean = sum(fast) / len(fast)
        s_mean = sum(slow) / len(slow)
        bad = f_mean > rule.fast_burn and s_mean > rule.slow_burn
        return bad, f_mean, (
            f"{rule.series}: fast({rule.fast_window_s}s)="
            f"{round(f_mean, 4)} vs {rule.fast_burn}, "
            f"slow({rule.slow_window_s}s)={round(s_mean, 4)} "
            f"vs {rule.slow_burn}"
        )

    # -- the tick ---------------------------------------------------------
    def evaluate(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One evaluation pass; returns the notifications emitted this
        tick (fire / re-notify / resolve payloads, for tests and the
        watchtower's own bookkeeping)."""
        now = self._clock() if now is None else float(now)
        notifications: List[Dict[str, Any]] = []
        with self._lock:
            self._evaluations += 1
            for rule in self.rules:
                st = self._state[rule.name]
                bad, value, detail = self._eval_rule(rule, now)
                st.value, st.detail = value, detail
                if bad:
                    st.consecutive_ok = 0
                    st.consecutive_bad += 1
                    if st.state == "ok":
                        st.state = "pending"
                        st.since_ts = now
                        self._transition("pending")
                    if (
                        st.state == "pending"
                        and st.consecutive_bad >= rule.for_ticks
                    ):
                        st.state = "firing"
                        st.fired_ts = now
                        st.fires += 1
                        st.last_notify_ts = now
                        self._transition("firing")
                        notifications.append(
                            self._notify(rule, st, "firing", now)
                        )
                    elif (
                        st.state == "firing"
                        and now - (st.last_notify_ts or now)
                        >= rule.renotify_s
                    ):
                        st.last_notify_ts = now
                        notifications.append(
                            self._notify(rule, st, "firing", now,
                                         renotify=True)
                        )
                else:
                    st.consecutive_bad = 0
                    if st.state == "pending":
                        st.state = "ok"
                        st.since_ts = None
                        self._transition("ok")
                    elif st.state == "firing":
                        st.consecutive_ok += 1
                        if st.consecutive_ok >= rule.resolve_ticks:
                            st.state = "ok"
                            st.resolves += 1
                            self._transition("ok")
                            notifications.append(
                                self._notify(rule, st, "resolved", now)
                            )
                            st.since_ts = st.fired_ts = None
                            st.last_notify_ts = None
            firing = sum(
                1 for s in self._state.values() if s.state == "firing"
            )
        if self._reg is not None:
            self._reg["evals"].inc(1)
            self._reg["firing"].set(firing)
        return notifications

    def _transition(self, to: str) -> None:
        if self._reg is not None:
            self._reg["transitions"].inc(1, to=to)

    def _notify(
        self,
        rule: AlertRule,
        st: _RuleState,
        state: str,
        now: float,
        renotify: bool = False,
    ) -> Dict[str, Any]:
        attribution = ""
        if self._attribution_fn is not None:
            try:
                attribution = self._attribution_fn() or ""
            except Exception:  # noqa: BLE001 - attribution is garnish;
                pass  # its failure must not eat the page
        payload = {
            "rule": rule.name,
            "kind": rule.kind,
            "series": rule.series,
            "severity": rule.severity,
            "state": state,
            "renotify": renotify,
            "value": st.value,
            "detail": st.detail,
            "since_ts": st.since_ts,
            "duration_s": (
                round(now - st.since_ts, 3) if st.since_ts else 0.0
            ),
            "attribution": attribution,
            "ts": now,
        }
        if self._events is not None:
            self._events.record(
                "watchtower",
                "alert_firing" if state == "firing" else "alert_resolved",
                level=(
                    rule.severity if state == "firing" else "info"
                ),
                rule=rule.name, series=rule.series, value=st.value,
                detail=st.detail, attribution=attribution,
                renotify=renotify, duration_s=payload["duration_s"],
            )
        for sink in self._sinks:
            try:
                sink.notify(payload)
                if self._reg is not None:
                    self._reg["notifications"].inc(
                        1, sink=getattr(sink, "name", "sink")
                    )
            except Exception as exc:  # noqa: BLE001 - one bad sink
                logger.warning(  # must not mute the others
                    "alert sink %s failed: %s",
                    getattr(sink, "name", sink), exc,
                )
        return payload

    # -- read side --------------------------------------------------------
    def firing(self) -> List[Dict[str, Any]]:
        """Currently-firing rules, worst first (severity, then oldest)."""
        by_rule = {r.name: r for r in self.rules}
        with self._lock:
            rows = [
                {"rule": name, "severity": by_rule[name].severity,
                 "series": by_rule[name].series, "value": st.value,
                 "detail": st.detail, "fired_ts": st.fired_ts}
                for name, st in self._state.items()
                if st.state == "firing"
            ]
        rows.sort(key=lambda r: (
            _SEVERITY_RANK.get(r["severity"], 9), r["fired_ts"] or 0.0,
        ))
        return rows

    def to_dict(self) -> Dict[str, Any]:
        with self._lock:
            states = {
                name: {
                    "state": st.state,
                    "consecutive_bad": st.consecutive_bad,
                    "consecutive_ok": st.consecutive_ok,
                    "value": st.value,
                    "detail": st.detail,
                    "since_ts": st.since_ts,
                    "fired_ts": st.fired_ts,
                    "fires": st.fires,
                    "resolves": st.resolves,
                }
                for name, st in self._state.items()
            }
            evaluations = self._evaluations
        return {
            "rules": [r.to_dict() for r in self.rules],
            "states": states,
            "firing": self.firing(),
            "evaluations": evaluations,
        }


# -- canary --------------------------------------------------------------
class CanaryLane:
    """Periodic fixed-seed probe through the REAL serving path.

    The probe is greedy (temperature 0, fixed seed) so its output is
    deterministic: exactness (generated tokens == the reference) is a
    correctness canary, TTFT / decode rate against the baseline
    envelope is a performance canary. The reference tokens come from
    the recorded baseline when one is given, else from the first
    successful probe (self-baseline).

    ``baseline`` (``--serve.canary_baseline``, written by bench.py)::

        {"prompt": [...], "max_new_tokens": n, "tokens": [...],
         "ttft_s": f, "decode_tokens_per_s": f,
         "ttft_mult": 3.0, "decode_frac": 0.33}

    ``deviation`` is the worst envelope ratio (>1 = outside): TTFT over
    ``ttft_s * ttft_mult``, or the decode floor
    ``decode_tokens_per_s * decode_frac`` over the observed rate.
    """

    #: Default probe: a tiny deterministic prompt.
    DEFAULT_PROMPT = (1, 2, 3, 5, 8, 13)

    def __init__(
        self,
        client: Any,
        tsdb: RingTSDB,
        *,
        prompt: Optional[Sequence[int]] = None,
        max_new_tokens: int = 12,
        interval_s: float = 10.0,
        baseline: Optional[Dict[str, Any]] = None,
        timeout_s: float = 60.0,
        events: Optional[Any] = None,
        registry: Optional[Any] = None,
        clock: Callable[[], float] = time.time,
    ) -> None:
        self.client = client
        self.tsdb = tsdb
        self.baseline = dict(baseline) if baseline else None
        if self.baseline and self.baseline.get("prompt"):
            prompt = [int(t) for t in self.baseline["prompt"]]
            max_new_tokens = int(
                self.baseline.get("max_new_tokens", max_new_tokens)
            )
        self.prompt = list(prompt if prompt is not None else
                           self.DEFAULT_PROMPT)
        self.max_new_tokens = int(max_new_tokens)
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self._events = events
        self._clock = clock
        self._reference: Optional[List[int]] = (
            [int(t) for t in self.baseline["tokens"]]
            if self.baseline and self.baseline.get("tokens") else None
        )
        self._last_probe_ts: Optional[float] = None
        self.probes = 0
        self.errors = 0
        self.last: Optional[Dict[str, Any]] = None
        self._reg = None
        if registry is not None:
            self._reg = {
                "probes": registry.counter(
                    "rlt_canary_probes_total", "Canary probes run, by outcome"
                ),
                "ttft": registry.gauge(
                    "rlt_canary_ttft_seconds", "Latest canary probe TTFT"
                ),
                "decode": registry.gauge(
                    "rlt_canary_decode_tokens_per_second",
                    "Latest canary probe decode rate",
                ),
                "exact": registry.gauge(
                    "rlt_canary_exact",
                    "Latest canary probe exactness (1 = bit-exact)",
                ),
                "deviation": registry.gauge(
                    "rlt_canary_deviation",
                    "Latest canary probe worst envelope ratio "
                    "(>1 = outside the baseline envelope)",
                ),
            }

    def tick(self, now: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """Run a probe when one is due (``interval_s`` since the last)."""
        now = self._clock() if now is None else float(now)
        if (
            self._last_probe_ts is not None
            and now - self._last_probe_ts < self.interval_s
        ):
            return None
        return self.probe(now=now)

    def probe(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One probe through the organic submit/stream path, under the
        reserved tenant at floor priority."""
        now = self._clock() if now is None else float(now)
        self._last_probe_ts = now
        self.probes += 1
        t0 = time.monotonic()
        first: Optional[float] = None
        tokens: List[int] = []
        try:
            for tok in self.client.stream(
                self.prompt,
                max_new_tokens=self.max_new_tokens,
                temperature=0.0,
                seed=0,
                priority=CANARY_PRIORITY,
                tenant=CANARY_TENANT,
                timeout_s=self.timeout_s,
            ):
                if first is None:
                    first = time.monotonic()
                tokens.append(int(tok))
        except Exception as exc:  # noqa: BLE001 - a failed probe is a
            # SIGNAL (recorded, alertable), never a watchtower crash.
            self.errors += 1
            if self._reg is not None:
                self._reg["probes"].inc(1, outcome="error")
            if self._events is not None:
                self._events.record(
                    "watchtower", "canary_error", level="warn",
                    error=f"{type(exc).__name__}: {exc}"[:200],
                )
            self.tsdb.record("canary.error", 1.0, ts=now)
            self.last = {
                "ts": now, "ok": False,
                "error": f"{type(exc).__name__}: {exc}"[:200],
            }
            return self.last
        t1 = time.monotonic()
        ttft = (first - t0) if first is not None else (t1 - t0)
        decode_s = (t1 - first) if first is not None else 0.0
        decode_rate = (
            (len(tokens) - 1) / decode_s
            if len(tokens) > 1 and decode_s > 0 else 0.0
        )
        if self._reference is None:
            self._reference = list(tokens)  # self-baseline: first probe
        exact = int(tokens == self._reference)
        deviation = 0.0
        if self.baseline:
            base_ttft = float(self.baseline.get("ttft_s") or 0.0)
            mult = float(self.baseline.get("ttft_mult", 3.0))
            if base_ttft > 0:
                deviation = max(deviation, ttft / (base_ttft * mult))
            base_decode = float(
                self.baseline.get("decode_tokens_per_s") or 0.0
            )
            frac = float(self.baseline.get("decode_frac", 0.33))
            if base_decode > 0 and decode_rate > 0:
                deviation = max(
                    deviation, (base_decode * frac) / decode_rate
                )
        self.tsdb.record("canary.ttft_s", ttft, ts=now)
        self.tsdb.record("canary.decode_tokens_per_s", decode_rate, ts=now)
        self.tsdb.record("canary.exact", float(exact), ts=now)
        self.tsdb.record("canary.deviation", deviation, ts=now)
        if self._reg is not None:
            self._reg["probes"].inc(
                1, outcome="exact" if exact else "mismatch"
            )
            self._reg["ttft"].set(round(ttft, 6))
            self._reg["decode"].set(round(decode_rate, 3))
            self._reg["exact"].set(float(exact))
            self._reg["deviation"].set(round(deviation, 4))
        if not exact and self._events is not None:
            self._events.record(
                "watchtower", "canary_mismatch", level="error",
                tokens=tokens[:16], reference=(self._reference or [])[:16],
            )
        self.last = {
            "ts": now, "ok": True, "exact": exact,
            "ttft_s": round(ttft, 6),
            "decode_tokens_per_s": round(decode_rate, 3),
            "deviation": round(deviation, 4),
            "tokens": len(tokens),
        }
        return self.last

    def to_dict(self) -> Dict[str, Any]:
        return {
            "interval_s": self.interval_s,
            "prompt_tokens": len(self.prompt),
            "max_new_tokens": self.max_new_tokens,
            "baseline": bool(self.baseline),
            "probes": self.probes,
            "errors": self.errors,
            "last": self.last,
        }


# -- the tower -----------------------------------------------------------
class Watchtower:
    """TSDB + alert engine + canary, driven by one periodic tick.

    Feeds:

    - ``fleet_latest_fn`` (zero-arg -> the latest FleetPoller snapshot
      dict, or None): sampled into ``fleet.*`` / ``replica<i>.*``
      gauge series, with the SLO-breach ratio diffed from the
      cumulative breach/finished counters;
    - ``metrics_text_fn`` (zero-arg -> exposition text): counter
      families become ``:rate`` series (bounded by
      ``metrics_families`` prefixes).

    ``tick()`` is the unit of evaluation (tests drive it with a fake
    clock); ``start()`` runs it on a daemon thread every
    ``interval_s`` — the serve driver's wiring.
    """

    #: Metric-family prefixes retained from a /metrics ingest by
    #: default — the families the default rules and dashboards read.
    DEFAULT_FAMILIES = (
        "rlt_kvstore_write_errors",
        "rlt_serve_requests_total",
        "rlt_serve_tokens_emitted_total",
    )

    def __init__(
        self,
        *,
        tsdb: Optional[RingTSDB] = None,
        rules: Optional[Sequence[AlertRule]] = None,
        fleet_latest_fn: Optional[Callable[[], Optional[Dict[str, Any]]]] = None,
        metrics_text_fn: Optional[Callable[[], str]] = None,
        metrics_families: Optional[Sequence[str]] = DEFAULT_FAMILIES,
        canary: Optional[CanaryLane] = None,
        sinks: Sequence[Any] = (),
        events: Optional[Any] = None,
        registry: Optional[Any] = None,
        interval_s: float = 2.0,
        clock: Callable[[], float] = time.time,
    ) -> None:
        self.tsdb = tsdb if tsdb is not None else RingTSDB(registry=registry)
        self.canary = canary
        all_rules = list(
            rules if rules is not None else default_rules()
        )
        if canary is not None:
            have = {r.name for r in all_rules}
            all_rules += [
                r for r in canary_rules(canary.baseline)
                if r.name not in have
            ]
        self.engine = AlertEngine(
            self.tsdb, all_rules, events=events, sinks=sinks,
            registry=registry, attribution_fn=self._attribution,
            clock=clock,
        )
        self._fleet_latest_fn = fleet_latest_fn
        self._metrics_text_fn = metrics_text_fn
        self._families = (
            tuple(metrics_families) if metrics_families else None
        )
        self._events = events
        self._clock = clock
        self.interval_s = float(interval_s)
        self._lock = threading.Lock()
        self._last_snap_ts: Optional[float] = None
        self._last_slo: Optional[Tuple[int, int]] = None
        self._last_phases: Optional[Dict[str, Any]] = None
        self._ticks = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- feeds ------------------------------------------------------------
    def observe_fleet(
        self, snap: Optional[Dict[str, Any]], now: Optional[float] = None
    ) -> None:
        """Sample one fleet snapshot into the TSDB (idempotent per
        snapshot ``ts`` — a tick faster than the poller re-sees the
        same snapshot and must not double-count the SLO delta)."""
        if not snap:
            return
        now = self._clock() if now is None else float(now)
        with self._lock:
            if snap.get("ts") == self._last_snap_ts:
                return
            self._last_snap_ts = snap.get("ts")
        fleet = snap.get("fleet") or {}
        rows = snap.get("replicas") or []
        rec = self.tsdb.record
        rec("fleet.replicas", fleet.get("replicas", len(rows)), ts=now)
        rec("fleet.healthy", fleet.get("healthy", 0), ts=now)
        rec(
            "fleet.unhealthy",
            int(fleet.get("replicas", len(rows)))
            - int(fleet.get("healthy", 0)),
            ts=now,
        )
        rec("fleet.queue_depth", fleet.get("queue_depth", 0), ts=now)
        rec("fleet.tokens_per_sec", fleet.get("tokens_per_sec", 0.0), ts=now)
        rec(
            "fleet.goodput_tokens_per_device_s",
            fleet.get("goodput_tokens_per_device_s", 0.0), ts=now,
        )
        if fleet.get("ttft_p95_s_worst") is not None:
            rec("fleet.ttft_p95_s", fleet["ttft_p95_s_worst"], ts=now)
        phases = fleet.get("phases") or None
        if phases:
            self._last_phases = phases
            if phases.get("hot_phase_p95_s") is not None:
                rec("fleet.hot_phase_p95_s",
                    phases["hot_phase_p95_s"], ts=now)
        self.tsdb.record_counter(
            "fleet.kvstore_write_errors",
            fleet.get("kvstore_write_errors", 0), ts=now,
        )
        self.tsdb.record_counter(
            "fleet.kvfleet_fetch_timeouts",
            fleet.get("kvfleet_fetch_timeouts", 0), ts=now,
        )
        # SLO-breach ratio: breaches opened per request finished over
        # the inter-snapshot interval (cumulative counters diffed).
        breaches = sum(int(r.get("slo_breaches") or 0) for r in rows)
        finished = sum(int(r.get("finished") or 0) for r in rows)
        with self._lock:
            prev = self._last_slo
            self._last_slo = (breaches, finished)
        if prev is not None:
            d_b = max(0, breaches - prev[0])
            d_f = max(0, finished - prev[1])
            ratio = (
                d_b / d_f if d_f > 0 else (1.0 if d_b > 0 else 0.0)
            )
            rec("fleet.slo_breach_ratio", min(1.0, ratio), ts=now)
        for r in rows:
            i = r.get("replica", 0)
            rec(f"replica{i}.queue_depth", r.get("queue_depth", 0), ts=now)
            rec(
                f"replica{i}.tokens_per_sec",
                r.get("tokens_per_sec", 0.0), ts=now,
            )
            rec(
                f"replica{i}.health",
                _VERDICT_SCORE.get(str(r.get("health")), 0.0), ts=now,
            )

    def _attribution(self) -> str:
        """Top anatomy phases for the latest fleet snapshot — rides
        every alert notification so the page names the hot phase."""
        with self._lock:
            phases = self._last_phases
        return format_attribution(breach_attribution(phases))

    # -- the tick ---------------------------------------------------------
    def tick(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """Feed + canary + evaluate: one watchtower cycle. Returns the
        alert notifications emitted."""
        now = self._clock() if now is None else float(now)
        if self._fleet_latest_fn is not None:
            try:
                self.observe_fleet(self._fleet_latest_fn(), now=now)
            except Exception:  # noqa: BLE001 - a feed hiccup must not
                pass  # stop evaluation (absence rules cover a dead feed)
        if self._metrics_text_fn is not None:
            try:
                self.tsdb.ingest_prometheus(
                    self._metrics_text_fn(), ts=now,
                    families=self._families,
                )
            except Exception:  # noqa: BLE001 - same
                pass
        if self.canary is not None:
            self.canary.tick(now=now)
        with self._lock:
            self._ticks += 1
        return self.engine.evaluate(now=now)

    # -- read side --------------------------------------------------------
    def alerts_payload(self) -> Dict[str, Any]:
        """The ``/alerts`` route body."""
        with self._lock:
            ticks = self._ticks
        return {
            "ticks": ticks,
            "interval_s": self.interval_s,
            "alerts": self.engine.to_dict(),
            "canary": self.canary.to_dict() if self.canary else None,
            "tsdb": self.tsdb.to_dict(),
            "series": self.tsdb.series_names(),
        }

    def fleet_block(self) -> Dict[str, Any]:
        """The compact ``alerts`` block embedded in the ``/fleet``
        payload (``rlt top``'s ``alerts:`` line)."""
        firing = self.engine.firing()
        return {
            "firing": len(firing),
            "names": [
                f"{r['rule']}({r['severity']})" for r in firing
            ],
        }

    def query(self, params: Dict[str, List[str]]) -> Dict[str, Any]:
        """The ``/query`` route: ``?series=`` (required), optional
        ``since=`` (unix seconds) and ``step=`` (seconds)."""
        series = (params.get("series") or [None])[0]
        if not series:
            raise ValueError("missing ?series=<name>")
        since = params.get("since")
        step = params.get("step")
        return self.tsdb.query(
            series,
            since=float(since[0]) if since else None,
            step=float(step[0]) if step else None,
        )

    # -- thread lifecycle -------------------------------------------------
    def start(self) -> "Watchtower":
        self._thread = threading.Thread(
            target=self._loop, name="obs-watchtower", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception as exc:  # noqa: BLE001 - the watcher must
                # outlive anything it watches.
                logger.warning("watchtower tick failed: %s", exc)
                if self._events is not None:
                    self._events.record(
                        "watchtower", "tick_error", level="warn",
                        error=f"{type(exc).__name__}: {exc}"[:200],
                    )
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
