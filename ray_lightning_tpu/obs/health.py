"""Watchdog + SLO engine: passive telemetry in, per-component verdicts out.

PR 4 gave the system eyes (traces, metrics, heartbeats); this module
looks through them. A :class:`Watchdog` runs a set of CHECK functions —
each returns one or more :class:`ComponentHealth` verdicts
(``healthy | degraded | unhealthy`` with reasons) — and turns the
results into:

- ``rlt_health{component=...}`` gauges (0/1/2) in the metrics registry,
- ``verdict_change`` events in the process event log on every
  transition,
- an ``on_unhealthy`` callback on the healthy→unhealthy edge (the
  flight-recorder trigger, see :mod:`obs.blackbox`),
- a :class:`HealthReport` that backs the real ``/healthz``: 200 while
  nothing is ``unhealthy``, 503 with the JSON report otherwise
  (``degraded`` stays 200 — an LB should not pull a slow-but-serving
  replica).

The built-in check factories only READ state the hot paths already
publish (registry counters, gauges, heartbeat snapshots, engine slot
counts) — the watchdog adds no instrumentation cost to the fold loop;
the bench measures the residual observer effect as
``watchdog_overhead`` (smoke-pinned < 5%).

Stall detection is flatline-based (:class:`Flatline`): a monotonically
advancing reading (tokens emitted, admits, optimizer steps) that stops
advancing while there is work to advance it is a stall. Every check
takes an injectable ``clock`` so the state machine is unit-testable
without sleeping.

SLO rules are declarative upper bounds evaluated against the serve
metrics snapshot (``--serve.slo.ttft_p95_s 0.5`` means "ttft_p95_s must
stay below 0.5"); each breach increments
``rlt_slo_breaches_total{rule=...}``, records an event, and marks the
rule's component unhealthy until the metric recovers.
"""
from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional

from ray_lightning_tpu.obs.events import EventLog, get_event_log
from ray_lightning_tpu.obs.registry import MetricsRegistry, get_registry

HEALTHY = "healthy"
DEGRADED = "degraded"
UNHEALTHY = "unhealthy"

_RANK = {HEALTHY: 0, DEGRADED: 1, UNHEALTHY: 2}
_LEVEL = {HEALTHY: "info", DEGRADED: "warn", UNHEALTHY: "error"}


@dataclass
class ComponentHealth:
    """One component's verdict with human-readable reasons."""

    component: str
    verdict: str = HEALTHY
    reasons: List[str] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {"verdict": self.verdict, "reasons": list(self.reasons)}


@dataclass
class HealthReport:
    """All components' verdicts at one evaluation instant."""

    components: Dict[str, ComponentHealth]
    ts: float = 0.0

    @property
    def verdict(self) -> str:
        """Worst component verdict (healthy when nothing reported)."""
        worst = HEALTHY
        for ch in self.components.values():
            if _RANK[ch.verdict] > _RANK[worst]:
                worst = ch.verdict
        return worst

    @property
    def healthy(self) -> bool:
        """The /healthz bit: False only on ``unhealthy`` (degraded still
        serves — an LB should not pull it)."""
        return self.verdict != UNHEALTHY

    def reasons(self) -> List[str]:
        return [
            f"{name}: {reason}"
            for name, ch in sorted(self.components.items())
            for reason in ch.reasons
        ]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "verdict": self.verdict,
            "healthy": self.healthy,
            "reasons": self.reasons(),
            "components": {
                name: ch.to_dict()
                for name, ch in sorted(self.components.items())
            },
            "ts": self.ts,
        }


class Flatline:
    """Seconds since a monotonically-advancing reading last changed.

    The stall primitive: ``seconds_flat()`` re-reads the value and
    returns how long it has been unchanged. ``reset()`` restarts the
    clock (used when the precondition for a stall — active work — goes
    away, so idle time never counts toward a stall).
    """

    def __init__(
        self,
        read: Callable[[], Any],
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self._read = read
        self._clock = clock
        self._last_val: Any = None
        self._last_change: Optional[float] = None

    def seconds_flat(self) -> float:
        val = self._read()
        now = self._clock()
        if self._last_change is None or val != self._last_val:
            self._last_val = val
            self._last_change = now
        return now - self._last_change

    def reset(self) -> None:
        self._last_change = None


# ---------------------------------------------------------------------------
# Check factories (each returns a zero-arg callable yielding verdicts)
# ---------------------------------------------------------------------------
def heartbeat_check(
    heartbeats_fn: Callable[[], Dict[str, Dict[str, Any]]],
    interval_s: Optional[float] = None,
    suspect_k: float = 3.0,
    dead_k: float = 6.0,
) -> Callable[[], List[ComponentHealth]]:
    """Fabric worker liveness from heartbeat ages: a worker whose last
    push is older than ``suspect_k x interval`` is suspect (degraded),
    older than ``dead_k x interval`` is presumed dead (unhealthy).
    ``interval_s`` defaults to ``RLT_HEARTBEAT_S`` (the push cadence the
    workers actually use)."""
    if interval_s is None:
        try:
            interval_s = float(os.environ.get("RLT_HEARTBEAT_S", "10"))
        except ValueError:
            interval_s = 10.0
        if interval_s <= 0:
            interval_s = 10.0

    def check() -> List[ComponentHealth]:
        out = []
        for actor_id, hb in heartbeats_fn().items():
            age = float(hb.get("age_s", 0.0) or 0.0)
            name = f"fabric:{actor_id}"
            if age > dead_k * interval_s:
                out.append(ComponentHealth(name, UNHEALTHY, [
                    f"no heartbeat for {age:.1f}s "
                    f"(> {dead_k:g}x the {interval_s:g}s interval); "
                    "worker presumed dead or hung"
                ]))
            elif age > suspect_k * interval_s:
                out.append(ComponentHealth(name, DEGRADED, [
                    f"heartbeat is {age:.1f}s old "
                    f"(> {suspect_k:g}x the {interval_s:g}s interval); "
                    "worker suspect"
                ]))
            else:
                out.append(ComponentHealth(name))
        return out

    return check


def engine_stall_check(
    num_active_fn: Callable[[], int],
    tokens_fn: Callable[[], float],
    stall_s: float,
    clock: Callable[[], float] = time.monotonic,
) -> Callable[[], List[ComponentHealth]]:
    """Decode engine stall: active slots but the emitted-token counter
    flat for ``stall_s`` — the device (or the loop driving it) stopped
    making progress. Idle engines reset the flatline."""
    flat = Flatline(tokens_fn, clock)

    def check() -> List[ComponentHealth]:
        stalled = flat.seconds_flat()
        if num_active_fn() <= 0:
            flat.reset()
            return [ComponentHealth("engine")]
        if stalled > stall_s:
            return [ComponentHealth("engine", UNHEALTHY, [
                f"{num_active_fn()} active slot(s) with no fold progress "
                f"for {stalled:.1f}s (stall_s={stall_s:g})"
            ])]
        return [ComponentHealth("engine")]

    return check


def admission_wedge_check(
    queue_depth_fn: Callable[[], int],
    admits_fn: Callable[[], float],
    stall_s: float,
    free_slots_fn: Optional[Callable[[], int]] = None,
    clock: Callable[[], float] = time.monotonic,
) -> Callable[[], List[ComponentHealth]]:
    """Admission wedge: queued requests with a flat admit counter for
    ``stall_s``. ``free_slots_fn`` gates the verdict on capacity being
    available — a full engine legitimately admits nothing while its
    residents decode (that case is the engine-stall check's to judge)."""
    flat = Flatline(admits_fn, clock)

    def check() -> List[ComponentHealth]:
        stalled = flat.seconds_flat()
        depth = queue_depth_fn()
        if depth <= 0 or (
            free_slots_fn is not None and free_slots_fn() <= 0
        ):
            flat.reset()
            return [ComponentHealth("scheduler")]
        if stalled > stall_s:
            return [ComponentHealth("scheduler", UNHEALTHY, [
                f"{depth} queued request(s) with no admission for "
                f"{stalled:.1f}s despite free slots (stall_s={stall_s:g})"
            ])]
        return [ComponentHealth("scheduler")]

    return check


def compile_storm_check(
    compiles_fn: Callable[[], float],
    window_s: float = 60.0,
    clock: Callable[[], float] = time.monotonic,
) -> Callable[[], List[ComponentHealth]]:
    """Compile storm: the steady-state compile counter (e.g. a replica's
    ``compiles_since_init``) RISING means a shape leaked into the hot
    path and every occurrence pays a recompile. Degraded while the
    counter moved within the last ``window_s`` — a transient flag that
    clears once the storm stops, while the total stays visible in the
    metrics."""
    flat = Flatline(compiles_fn, clock)

    def check() -> List[ComponentHealth]:
        stalled = flat.seconds_flat()
        total = compiles_fn()
        if total > 0 and stalled < window_s:
            return [ComponentHealth("compiler", DEGRADED, [
                f"compile storm: {total:g} steady-state compile(s), "
                f"last within {window_s:g}s — a shape is leaking into "
                "the hot path"
            ])]
        return [ComponentHealth("compiler")]

    return check


def fit_stall_check(
    telemetry: Any,
    stall_s: float,
    clock: Callable[[], float] = time.monotonic,
) -> Callable[[], List[ComponentHealth]]:
    """Trainer stall: mid-fit (telemetry live, fit not done) with no
    chunk recorded for ``stall_s``. Reads the ``TrainTelemetry``
    progress stamps the fit loop already maintains."""

    def check() -> List[ComponentHealth]:
        if getattr(telemetry, "fit_done", False):
            return [ComponentHealth("trainer")]
        last = getattr(telemetry, "last_progress_t", None)
        if last is None:
            last = getattr(telemetry, "created_t", None)
        if last is None:
            return [ComponentHealth("trainer")]
        stalled = clock() - last
        if stalled > stall_s:
            return [ComponentHealth("trainer", UNHEALTHY, [
                f"mid-fit with no optimizer step for {stalled:.1f}s "
                f"(stall_s={stall_s:g})"
            ])]
        return [ComponentHealth("trainer")]

    return check


# ---------------------------------------------------------------------------
# SLO rules
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class SLORule:
    """One upper-bound objective: ``metric`` must stay below ``limit``."""

    metric: str
    limit: float

    @property
    def name(self) -> str:
        return f"{self.metric}<{self.limit:g}"


def parse_slo_rules(spec: Dict[str, Any]) -> List[SLORule]:
    """``{metric: limit}`` (the ``--serve.slo.<metric> <limit>`` form)
    into rules. Every SLO is an upper bound — latencies, error rates,
    expire rates all breach by exceeding."""
    return [
        SLORule(str(metric), float(limit))
        for metric, limit in sorted(spec.items())
    ]


def _derived(snap: Dict[str, Any]) -> Dict[str, Any]:
    """Augment a metrics snapshot with the rate metrics SLOs commonly
    bound: error_rate (cancelled+expired over terminal events) and
    expire_rate."""
    out = dict(snap)
    finished = float(snap.get("finished", 0) or 0)
    cancelled = float(snap.get("cancelled", 0) or 0)
    expired = float(snap.get("expired", 0) or 0)
    terminal = finished + cancelled + expired
    if terminal > 0:
        out.setdefault("error_rate", (cancelled + expired) / terminal)
        out.setdefault("expire_rate", expired / terminal)
    return out


def _breach_shares(snap: Dict[str, Any]) -> str:
    """Breach attribution from the snapshot's anatomy ``phases`` block:
    '"kv_fetch 58%, queue 22%" — the verdict names WHERE the breached
    latency went, not just that it breached. Empty when the ledger is
    off or has no window yet."""
    block = snap.get("phases")
    if not block:
        return ""
    from ray_lightning_tpu.obs.anatomy import (
        breach_attribution, format_attribution,
    )

    return format_attribution(breach_attribution(block))


def slo_check(
    rules: Iterable[SLORule],
    snapshot_fn: Callable[[], Dict[str, Any]],
    registry: Optional[MetricsRegistry] = None,
    events: Optional[EventLog] = None,
) -> Callable[[], List[ComponentHealth]]:
    """Evaluate declarative SLO rules against the serve metrics
    snapshot. A breach marks ``slo:<metric>`` unhealthy, increments
    ``rlt_slo_breaches_total{rule=...}``, records an event, and — when
    the anatomy ledger has a ``phases`` window — appends the top
    contributing phases by share to the reason ("ttft_p95 breach:
    kv_fetch 58%, queue 22%"), so the attribution rides the
    ``verdict_change`` event and the ``/healthz`` body for free; a
    metric with no data yet is healthy (no traffic is not a breach)."""
    rules = list(rules)
    reg = registry or get_registry()
    breaches = reg.counter(
        "rlt_slo_breaches_total", "SLO rule breaches observed by the watchdog"
    )

    def check() -> List[ComponentHealth]:
        snap = _derived(snapshot_fn())
        out = []
        for rule in rules:
            observed = snap.get(rule.metric)
            name = f"slo:{rule.metric}"
            if observed is None:
                out.append(ComponentHealth(name))
                continue
            if float(observed) > rule.limit:
                breaches.inc(1, rule=rule.name)
                attribution = ""
                shares = _breach_shares(snap)
                if shares:
                    attribution = f"; top phases: {shares}"
                if events is not None:
                    events.record(
                        "health", "slo_breach", level="warn",
                        rule=rule.name, observed=float(observed),
                        **({"phases": shares} if shares else {}),
                    )
                out.append(ComponentHealth(name, UNHEALTHY, [
                    f"SLO breach: {rule.metric}={float(observed):g} "
                    f"exceeds {rule.limit:g}{attribution}"
                ]))
                continue
            out.append(ComponentHealth(name))
        return out

    return check


# ---------------------------------------------------------------------------
# The watchdog
# ---------------------------------------------------------------------------
class Watchdog:
    """Run checks, publish verdicts, fire the black box on the edge.

    ``evaluate()`` is the whole state machine: run every check, diff the
    verdicts against the previous evaluation, update the
    ``rlt_health{component=...}`` gauges, record ``verdict_change``
    events, and invoke ``on_unhealthy(component, report)`` once per
    transition INTO unhealthy (the flight-recorder hook). It is safe to
    call both from the background thread (``start()``) and on demand
    (an RPC/scrape wanting a fresh verdict) — evaluations serialize on
    an internal lock.
    """

    def __init__(
        self,
        checks: Iterable[Callable[[], List[ComponentHealth]]] = (),
        interval_s: float = 1.0,
        registry: Optional[MetricsRegistry] = None,
        events: Optional[EventLog] = None,
        on_unhealthy: Optional[Callable[[str, HealthReport], Any]] = None,
    ) -> None:
        self._checks: List[Callable[[], List[ComponentHealth]]] = list(checks)
        self.interval_s = float(interval_s)
        self._registry = registry or get_registry()
        self._events = events if events is not None else get_event_log()
        self._on_unhealthy = on_unhealthy
        self._gauge = self._registry.gauge(
            "rlt_health",
            "Component health verdict (0 healthy, 1 degraded, 2 unhealthy)",
        )
        # Re-entrant: an on_unhealthy hook (flight-recorder dump) may
        # legitimately read health while evaluate() holds the lock.
        self._lock = threading.RLock()
        self._last_verdicts: Dict[str, str] = {}
        self._report = HealthReport(components={}, ts=time.time())
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def add_check(
        self, check: Callable[[], List[ComponentHealth]]
    ) -> "Watchdog":
        self._checks.append(check)
        return self

    def evaluate(self) -> HealthReport:
        with self._lock:
            components: Dict[str, ComponentHealth] = {}
            for check in self._checks:
                try:
                    results = check()
                except Exception as exc:  # noqa: BLE001 - a broken check
                    # must degrade the watchdog, never crash it.
                    results = [ComponentHealth(
                        "watchdog", DEGRADED, [f"check failed: {exc!r}"]
                    )]
                for ch in results:
                    components[ch.component] = ch
            report = HealthReport(components=components, ts=time.time())
            # Publish BEFORE firing transition hooks: an on_unhealthy
            # flight-recorder dump reads report() and must capture the
            # verdict that fired it, not the previous evaluation's.
            self._report = report
            # Publish gauges + transition events; fire on_unhealthy on
            # the healthy/degraded -> unhealthy edge only.
            for name, ch in components.items():
                self._gauge.set(_RANK[ch.verdict], component=name)
                prev = self._last_verdicts.get(name, HEALTHY)
                if ch.verdict != prev:
                    self._events.record(
                        "health", "verdict_change",
                        level=_LEVEL[ch.verdict],
                        component=name, was=prev, now=ch.verdict,
                        reason="; ".join(ch.reasons)[:300],
                    )
                    if (
                        ch.verdict == UNHEALTHY
                        and self._on_unhealthy is not None
                    ):
                        try:
                            self._on_unhealthy(name, report)
                        except Exception:  # noqa: BLE001 - forensics must
                            pass  # never take down the watchdog
            # Vanished components (dead actor removed from heartbeats):
            # drop their gauge series so the scrape doesn't report stale
            # verdicts forever — the same contract as the heartbeat
            # gauges in obs.telemetry.
            for name in set(self._last_verdicts) - set(components):
                self._gauge.remove(component=name)
            self._last_verdicts = {
                name: ch.verdict for name, ch in components.items()
            }
            return report

    def report(self) -> HealthReport:
        """The most recent evaluation (without forcing a new one)."""
        with self._lock:
            return self._report

    # -- background evaluator --------------------------------------------
    def start(self) -> "Watchdog":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="obs-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.evaluate()
            except Exception:  # noqa: BLE001 - keep the evaluator alive
                pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
