"""ray_lightning_tpu.obs — the cross-layer observability subsystem.

The repo's third subsystem (after the trainer and the serving engine):
one place where serve, trainer, and fabric report what they are doing,
and one place operators read it back. Two halves:

PASSIVE (telemetry — the eyes):

- :mod:`obs.trace` — request tracing: typed lifecycle spans in a bounded
  per-replica ring buffer (:class:`RequestTracer`), exported as Chrome
  trace-event JSON (:func:`to_chrome_trace`) that opens in Perfetto.
- :mod:`obs.anatomy` — request anatomy (:func:`assemble_anatomy`,
  :func:`render_anatomy`): one request's cross-process phase ledger
  stitched from every tracer ring + the journal + the event rings, with
  an explicit coverage contract (phases + unaccounted == observed
  latency, exactly) — ``rlt why``'s and ``/why``'s engine, and the
  phase vocabulary behind the fleet latency decomposition and SLO
  breach attribution.
- :mod:`obs.registry` — counter/gauge/histogram registry
  (:class:`MetricsRegistry`, :func:`get_registry` for the process
  default) rendered in Prometheus text format.
- :mod:`obs.events` — structured event log (:class:`EventLog`,
  :func:`get_event_log`): a bounded process-wide ring of typed events
  (admissions, cancels, epoch boundaries, actor deaths, verdicts).
- :mod:`obs.telemetry` — trainer step breakdown, tokens/s + MFU, fabric
  heartbeat aggregation (:class:`TrainTelemetry`).
- :mod:`obs.jaxmon` — JAX compile-event counters
  (:func:`install_compile_listener`): the frozen-compile contract as a
  metric, not just a test.
- :mod:`obs.profiling` — on-demand ``jax.profiler`` capture
  (:func:`capture_profile`) behind the ``profile(duration_s)`` RPCs.

ACTIVE (judgment — something looks through the eyes):

- :mod:`obs.health` — the watchdog + SLO engine (:class:`Watchdog`):
  passive telemetry in, per-component ``healthy|degraded|unhealthy``
  verdicts out, backing a real ``/healthz`` (200/503) and the
  ``rlt_health{component=...}`` gauges.
- :mod:`obs.blackbox` — the flight recorder (:func:`dump_bundle`,
  :class:`FlightRecorder`): self-contained forensic bundles (metrics,
  events, traces, health, stacks) dumped automatically on unhealthy
  transitions and fit crashes, or on demand via ``debug_dump`` RPCs and
  ``rlt doctor``.
- :mod:`obs.httpd` — the /metrics + /stats + /healthz + /debug/bundle
  (+ /fleet + /events + /traces) HTTP endpoint
  (:class:`MetricsHTTPServer`) behind ``rlt serve --serve.metrics_port``.
- :mod:`obs.journal` — deterministic capture & replay
  (:class:`WorkloadJournal`, :func:`load_journal`,
  :func:`replay_journal`): the serve session's externally-sourced
  request stream journaled into a bounded ring (+ optional JSONL
  spill), re-drivable bit-exactly via ``rlt replay`` — every incident
  a local repro, every captured trace a benchmark.
- :mod:`obs.fleet` — the fleet aggregator (:class:`FleetPoller`,
  :class:`FleetSnapshot`): a driver-side puller condensing every
  replica's stats/health into one bounded-history snapshot stream —
  the ``/fleet`` route's and ``rlt top``'s feed, and the signal plane a
  router/autoscaler consumes.

Import cost: everything here is stdlib-only at import time; jax loads
only when profiling/monitoring is actually used, so the fabric can ship
this module into workers whose platform env is not yet applied.
"""
from ray_lightning_tpu.obs.anatomy import (
    assemble_anatomy,
    anatomy_from_client,
    aggregate_phases,
    breach_attribution,
    format_attribution,
    render_anatomy,
)
from ray_lightning_tpu.obs.blackbox import (
    FlightRecorder,
    dump_bundle,
    read_bundle,
)
from ray_lightning_tpu.obs.events import EventLog, get_event_log
from ray_lightning_tpu.obs.fleet import (
    FleetPoller,
    FleetSnapshot,
    aggregate_fleet,
    summarize_replica,
)
from ray_lightning_tpu.obs.health import (
    ComponentHealth,
    HealthReport,
    SLORule,
    Watchdog,
    parse_slo_rules,
)
from ray_lightning_tpu.obs.httpd import MetricsHTTPServer
from ray_lightning_tpu.obs.jaxmon import compile_stats, install_compile_listener
from ray_lightning_tpu.obs.journal import (
    WorkloadJournal,
    load_journal,
    replay_journal,
)
from ray_lightning_tpu.obs.profiling import capture_profile, profiler_available
from ray_lightning_tpu.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
    parse_prometheus_text,
)
from ray_lightning_tpu.obs.telemetry import (
    TrainTelemetry,
    heartbeats_to_registry,
)
from ray_lightning_tpu.obs.trace import (
    RequestTracer,
    merge_chrome_trace,
    to_chrome_trace,
)

__all__ = [
    "ComponentHealth",
    "Counter",
    "EventLog",
    "FleetPoller",
    "FleetSnapshot",
    "FlightRecorder",
    "Gauge",
    "HealthReport",
    "Histogram",
    "MetricsHTTPServer",
    "MetricsRegistry",
    "RequestTracer",
    "SLORule",
    "TrainTelemetry",
    "Watchdog",
    "WorkloadJournal",
    "aggregate_fleet",
    "aggregate_phases",
    "anatomy_from_client",
    "assemble_anatomy",
    "breach_attribution",
    "capture_profile",
    "compile_stats",
    "dump_bundle",
    "format_attribution",
    "get_event_log",
    "get_registry",
    "heartbeats_to_registry",
    "install_compile_listener",
    "load_journal",
    "merge_chrome_trace",
    "parse_prometheus_text",
    "parse_slo_rules",
    "profiler_available",
    "read_bundle",
    "render_anatomy",
    "replay_journal",
    "summarize_replica",
    "to_chrome_trace",
]
