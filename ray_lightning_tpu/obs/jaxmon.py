"""JAX compile-event telemetry via jax.monitoring listeners.

``jax.monitoring.register_event_duration_secs_listener`` reports every
jaxpr trace / MLIR lowering / backend compile with its wall time; this
module folds those into the process registry as::

    rlt_jax_compile_events_total{event="backend_compile"}
    rlt_jax_compile_seconds_total{event="backend_compile"}

and keeps a host-side :class:`CompileStats` counter so code can take
cheap before/after snapshots. That turns contracts like the serve
engine's "compile count frozen after construction" into a METRIC —
``ServeReplica.stats()`` ships ``compiles_since_init``, which must read
0 in steady state — instead of something only the test suite can see.

jax 0.4.x listeners receive (event_name, duration) only — no executable
name — so attribution is per event KIND; per-executable naming waits on
a newer jax. Listener registration is process-global and irrevocable
(there is no unregister short of clearing every listener), hence the
idempotent :func:`install_compile_listener`.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

from ray_lightning_tpu.obs.registry import MetricsRegistry, get_registry

#: jax.monitoring event-name suffix -> short label.
_EVENTS = {
    "/jax/core/compile/backend_compile_duration": "backend_compile",
    "/jax/core/compile/jaxpr_trace_duration": "jaxpr_trace",
    "/jax/core/compile/jaxpr_to_mlir_module_duration": "lowering",
}


class CompileStats:
    """Host-side mirror of the compile counters (cheap snapshots)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        self._secs: Dict[str, float] = {}

    def record(self, label: str, dur: float) -> None:
        with self._lock:
            self._counts[label] = self._counts.get(label, 0) + 1
            self._secs[label] = self._secs.get(label, 0.0) + float(dur)

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                k: {"count": self._counts[k], "total_s": round(self._secs[k], 4)}
                for k in sorted(self._counts)
            }

    def count(self, label: str = "backend_compile") -> int:
        with self._lock:
            return self._counts.get(label, 0)


_STATS: Optional[CompileStats] = None
_INSTALL_LOCK = threading.Lock()


def install_compile_listener(
    registry: Optional[MetricsRegistry] = None,
) -> CompileStats:
    """Install the listener once per process; returns the shared
    :class:`CompileStats`. Safe to call from every subsystem that wants
    compile telemetry (trainer loop, serve replica, tools)."""
    global _STATS
    with _INSTALL_LOCK:
        if _STATS is not None:
            return _STATS
        stats = CompileStats()
        reg = registry or get_registry()
        counter = reg.counter(
            "rlt_jax_compile_events_total",
            "JAX compile-pipeline events by kind",
        )
        seconds = reg.counter(
            "rlt_jax_compile_seconds_total",
            "Wall seconds spent in JAX compile-pipeline events by kind",
        )

        def _listener(name: str, dur: float, **kw: object) -> None:  # noqa: ARG001
            label = _EVENTS.get(name)
            if label is None:
                return
            stats.record(label, dur)
            counter.inc(1, event=label)
            seconds.inc(float(dur), event=label)

        try:
            import jax.monitoring

            jax.monitoring.register_event_duration_secs_listener(_listener)
        except Exception:  # noqa: BLE001 - no monitoring, stats stay zero
            pass
        _STATS = stats
        return stats


def compile_stats() -> Optional[CompileStats]:
    """The installed stats, or None when no listener was installed yet."""
    return _STATS
