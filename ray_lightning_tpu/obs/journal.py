"""Workload journal: deterministic capture & replay for serve sessions.

The serving engine's contracts make execution deterministic given its
inputs — compiles are frozen at construction, greedy output is
bit-identical to solo ``gpt_generate`` regardless of batching/chunking/
spec, and sampled requests draw per-slot rng chains seeded only by
``SamplingParams.seed`` (one split per emitted token, batchmates
independent). The ONLY nondeterminism in a serve session is therefore
the externally-sourced request stream. This module journals exactly
that stream, so any production incident becomes a local repro and any
captured trace doubles as a benchmark:

- :class:`WorkloadJournal` — a bounded in-memory ring (plus optional
  streaming JSONL spill with rotation) of one entry per externally
  sourced input: a config/checkpoint-identity **header**, one
  ``submit`` entry per ``Scheduler.submit`` (prompt tokens, the full
  ``SamplingParams`` including the seed, priority/deadline/tenant/
  request id, monotonic + wall timestamps), one ``cancel`` entry per
  ``Scheduler.cancel``, and one ``outcome`` entry per terminal request
  (the emitted token values + the cost-ledger record, written at the
  ledger close so it rides the same flush as billing).
- :func:`load_journal` — read a journal back from a JSONL file (or a
  spill directory, or replica-tagged ``/journal`` route output).
- :func:`replay_journal` — rebuild an engine/scheduler from the
  recorded header and re-drive the stream, asserting **bit-exact
  per-request token output** against the recorded outcomes with a
  first-divergence report on mismatch; in ``timing="wall"`` mode the
  recorded inter-arrivals are honored and a perf comparison (tokens/s,
  TTFT p50/p95, goodput) against the recorded run's ledger is emitted.

Exposure: ``ServeReplica.journal_dump`` RPC, the ``/journal`` httpd
route, a ``journal.jsonl`` collector in ``obs.blackbox.dump_bundle``
(doctor bundles become replayable), and the ``rlt replay <journal>``
CLI. Hot-path budget matches the tracer/event log: one dict append
under one lock per request lifecycle event — never per token.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Tuple

#: Journal schema version (the header carries it; replay checks it).
JOURNAL_VERSION = 1

#: SamplingParams fields a submit entry records (and replay restores).
SAMPLING_FIELDS = (
    "max_new_tokens", "temperature", "top_k", "top_p", "seed", "eos_token",
)


def checkpoint_identity(ckpt_path: Optional[str]) -> Dict[str, Any]:
    """Cheap checkpoint provenance for the header: the path plus file
    size/mtime when it exists — enough to flag "you are replaying
    against a different checkpoint" without hashing gigabytes."""
    out: Dict[str, Any] = {"ckpt_path": ckpt_path}
    if ckpt_path:
        try:
            st = os.stat(ckpt_path)
            out["ckpt_bytes"] = int(st.st_size)
            out["ckpt_mtime"] = round(st.st_mtime, 3)
        except OSError:
            pass
    return out


def engine_header(
    engine: Any,
    *,
    ckpt_path: Optional[str] = None,
    int8: bool = False,
    spec_draft_ckpt: Optional[str] = None,
    spec_draft_config: Optional[Dict[str, Any]] = None,
    spec_draft_int8: bool = False,
    max_prefills_per_step: int = 1,
    max_prefill_chunks_per_step: int = 1,
    priority_age_s: Optional[float] = None,
    router: Optional[Dict[str, Any]] = None,
    kvfleet: Optional[Dict[str, Any]] = None,
    kvstore: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """The config/checkpoint-identity header from a live engine: the
    RESOLVED knobs (buckets expanded, chunk coerced, mesh normalized),
    so a replay rebuilds a bit-identical engine even when the recorded
    process took defaults."""
    import dataclasses

    header: Dict[str, Any] = {
        "version": JOURNAL_VERSION,
        "created_wall": time.time(),
        "created_mono": time.monotonic(),
        "model_config": dataclasses.asdict(engine.cfg),
        "int8": bool(int8),
        "engine": {
            "num_slots": engine.num_slots,
            "max_seq": engine.max_seq,
            "prefill_buckets": list(engine.prefill_buckets),
            "decode_fold": engine.decode_fold,
            # Fused-dispatch knobs: a replay must rebuild the same
            # pre-lowered fold ladder and piggyback row budget — the
            # per-dispatch K choice and the chunk-rides-the-fold plan
            # are deterministic functions of the op stream, but only on
            # an engine built with the same knobs.
            "fold_ladder": list(getattr(engine, "fold_ladder", ()) or ()),
            "piggyback_chunks": getattr(engine, "piggyback_chunks", 0),
            "pipeline": engine.pipeline,
            "prefill_chunk": engine.prefill_chunk,
            # Paged engines fold the prefix pool into the page allocator:
            # record the PAGED knobs and zero the prefix ones (the engine
            # rejects the combination, and a replay must rebuild the
            # same paged config — page size shapes alias/evict behavior).
            "prefix_blocks": (
                0 if getattr(engine, "paged", False)
                else engine.prefix_blocks
            ),
            "prefix_block": (
                16 if getattr(engine, "paged", False)
                else engine.prefix_block
            ),
            "kv_page": getattr(engine, "kv_page", 0),
            "kv_pages": getattr(engine, "kv_pages", 0),
            # Tiered prefix-cache knobs: a replay must rebuild the same
            # tier config — hit/miss/spill decisions shape admission
            # timing, and a recorded host-tier hit should hit on replay.
            "prefix_host_mb": getattr(engine, "prefix_host_mb", 0.0),
            "prefix_disk_dir": getattr(engine, "prefix_disk_dir", None),
            "prefix_disk_mb": getattr(engine, "prefix_disk_mb", 0.0),
            "spec": engine.spec,
            "spec_depth": engine.spec_depth,
            "spec_window": engine.spec_window,
            "spec_draft_ckpt": spec_draft_ckpt,
            "spec_draft_config": spec_draft_config,
            "spec_draft_int8": bool(spec_draft_int8),
            # Persistent-store knobs ride the ENGINE section (they are
            # engine ctor params, _ENGINE_REBUILD_KEYS carries them into
            # a replay's build_engine) — replaying against the recorded
            # store dir reproduces recorded store hits.
            "kvstore_dir": getattr(engine, "kvstore_dir", None),
            "kvstore_mb": getattr(engine, "kvstore_mb", 0.0),
            # Model-identity namespace: without it a replay against the
            # recorded store dir would derive a namespace from ITS view
            # of the config and could silently miss (or worse, hit a
            # different model's entries).
            "kvstore_namespace": getattr(engine, "kvstore_namespace", ""),
            "mesh": engine.mesh_desc,
        },
        "scheduler": {
            "max_prefills_per_step": int(max_prefills_per_step),
            "max_prefill_chunks_per_step": int(max_prefill_chunks_per_step),
            "priority_age_s": priority_age_s,
        },
    }
    if router is not None:
        # Router/autoscaler knobs (serve.router.ROUTER_HEADER_KEYS):
        # the driver-side policy that shaped this replica's traffic —
        # provenance a replay surfaces (the single-engine replay itself
        # has no fleet to route over).
        header["router"] = dict(router)
    if kvfleet is not None:
        # Fleet-KV/disagg knobs (serve.kvfleet.KVFLEET_HEADER_KEYS):
        # role + transfer budgets. A disaggregated capture replays on
        # one engine — shipped outcomes are recorded truncations (like
        # PR 12's migrations), so the replay stays bit-exact while the
        # section tells the operator what shaped the traffic.
        header["kvfleet"] = dict(kvfleet)
    if kvstore is not None:
        # Persistent-store provenance (serve.kvstore.KVSTORE_HEADER_KEYS):
        # dir/budget/write-through policy — the fleet-shared tier that
        # shaped this capture's hit pattern (`rlt replay` surfaces it as
        # kvstore_config).
        header["kvstore"] = dict(kvstore)
    header.update(checkpoint_identity(ckpt_path))
    return header


class WorkloadJournal:
    """Bounded ring of the externally-sourced serve inputs + outcomes.

    ``capacity`` bounds the in-memory ring (oldest entries rotate out);
    ``spill_dir`` additionally streams every entry to rotating JSONL
    files (``journal-00000.jsonl`` ...), each starting with the header
    line so every kept file is independently replayable. ``spill_keep``
    bounds the rotated set — a long-lived replica cannot fill a disk.
    """

    def __init__(
        self,
        capacity: int = 4096,
        spill_dir: Optional[str] = None,
        spill_max_bytes: int = 8_000_000,
        spill_keep: int = 4,
        enabled: bool = True,
    ) -> None:
        self.capacity = max(1, int(capacity))
        self.enabled = bool(enabled)
        self.spill_dir = spill_dir
        self.spill_max_bytes = max(1, int(spill_max_bytes))
        self.spill_keep = max(1, int(spill_keep))
        self._lock = threading.Lock()
        self._entries: deque = deque(maxlen=self.capacity)
        self._header: Optional[Dict[str, Any]] = None
        #: monotonic -> wall mapping for this process (every entry
        #: carries both stamps so a replay can honor inter-arrivals AND
        #: line up with external logs).
        self._wall_offset = time.time() - time.monotonic()
        # Spill state (guarded by the same lock as the ring).
        self._spill_file: Optional[Any] = None
        self._spill_bytes = 0
        self._spill_index = -1

    # -- spill (under self._lock) ----------------------------------------
    def _spill_rotate(self) -> None:
        if self._spill_file is not None:
            self._spill_file.close()
        self._spill_index += 1
        os.makedirs(self.spill_dir, exist_ok=True)
        # Prune: keep the newest ``spill_keep`` files including the one
        # about to open.
        names = sorted(
            n for n in os.listdir(self.spill_dir)
            if n.startswith("journal-") and n.endswith(".jsonl")
        )
        for stale in names[: max(0, len(names) - (self.spill_keep - 1))]:
            try:
                os.remove(os.path.join(self.spill_dir, stale))
            except OSError:
                pass
        path = os.path.join(
            self.spill_dir, f"journal-{self._spill_index:05d}.jsonl"
        )
        self._spill_file = open(path, "w")
        self._spill_bytes = 0
        if self._header is not None:
            line = json.dumps(
                {"kind": "header", **self._header}, default=str
            ) + "\n"
            self._spill_file.write(line)
            self._spill_bytes += len(line)

    def _spill_line(self, entry: Dict[str, Any]) -> None:
        if self.spill_dir is None:
            return
        if (
            self._spill_file is None
            or self._spill_bytes > self.spill_max_bytes
        ):
            self._spill_rotate()
        line = json.dumps(entry, default=str) + "\n"
        self._spill_file.write(line)
        # Flush at terminal entries only (one flush per completed
        # request, not per submit) — the hot-loop budget. The in-memory
        # ring is what crash bundles read, so a buffered submit can at
        # worst go missing from the SPILL of a hard-killed process.
        if entry.get("kind") != "submit":
            self._spill_file.flush()
        self._spill_bytes += len(line)

    def _append(self, entry: Dict[str, Any]) -> None:
        with self._lock:
            self._entries.append(entry)
            self._spill_line(entry)

    def _stamp(self, t_mono: Optional[float]) -> Dict[str, float]:
        t = time.monotonic() if t_mono is None else float(t_mono)
        return {
            "t_mono": round(t, 6),
            "t_wall": round(t + self._wall_offset, 6),
        }

    # -- recording (the scheduler's hooks) --------------------------------
    def set_header(self, header: Dict[str, Any]) -> None:
        with self._lock:
            self._header = dict(header)

    def record_submit(
        self,
        *,
        request_id: str,
        prompt: Iterable[int],
        sampling: Dict[str, Any],
        priority: int = 0,
        deadline_s: Optional[float] = None,
        tenant: Optional[str] = None,
        t_mono: Optional[float] = None,
    ) -> None:
        if not self.enabled:
            return
        self._append({
            "kind": "submit",
            "request_id": request_id,
            "prompt": [int(t) for t in prompt],
            "sampling": {
                k: sampling.get(k) for k in SAMPLING_FIELDS
            },
            "priority": int(priority),
            "deadline_s": deadline_s,
            "tenant": tenant,
            **self._stamp(t_mono),
        })

    def record_cancel(
        self, request_id: str, known: bool = True,
        t_mono: Optional[float] = None,
    ) -> None:
        if not self.enabled:
            return
        self._append({
            "kind": "cancel",
            "request_id": request_id,
            "known": bool(known),
            **self._stamp(t_mono),
        })

    def record_outcome(
        self, request_id: str, outcome: str,
        cost: Optional[Dict[str, Any]] = None,
        tokens: Optional[List[int]] = None,
        ttft_s: Optional[float] = None,
        phases: Optional[Dict[str, Any]] = None,
    ) -> None:
        """One request reached terminal state: emit its outcome entry —
        the emitted token VALUES the replay asserts against (the
        scheduler accumulates them inline in loops it already runs, so
        the journal adds no per-step pass), plus the cost-ledger record
        and TTFT for the wall-mode perf comparison. ``phases`` is the
        compact anatomy ledger (``{phase: seconds}``) — it makes a
        captured incident autopsy-able offline (``rlt why <journal>
        <id>``) and lets wall-mode replay diff recorded vs replayed
        phase timings."""
        if not self.enabled:
            return
        entry: Dict[str, Any] = {
            "kind": "outcome",
            "request_id": request_id,
            "outcome": outcome,
            "tokens": [int(t) for t in tokens] if tokens else [],
            **self._stamp(None),
        }
        if ttft_s is not None:
            entry["ttft_s"] = round(float(ttft_s), 6)
        if cost is not None:
            entry["cost"] = {
                k: v for k, v in cost.items() if k != "request_id"
            }
        if phases:
            entry["phases"] = dict(phases)
        self._append(entry)

    # -- read side --------------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def dump(self, n: Optional[int] = None) -> Dict[str, Any]:
        """The wire form (``ServeReplica.journal_dump`` ships it):
        header + the newest ``n`` entries (all when None)."""
        with self._lock:
            entries = list(self._entries)
            header = dict(self._header) if self._header else None
        if n is not None:
            entries = entries[-int(n):]
        return {"header": header, "entries": entries}

    def to_jsonl(self, n: Optional[int] = None) -> str:
        """The replayable JSONL form: one header line, one entry per
        line (the ``journal.jsonl`` bundle file and ``/journal`` body)."""
        return dump_to_jsonl(self.dump(n))

    def close(self) -> None:
        with self._lock:
            if self._spill_file is not None:
                self._spill_file.close()
                self._spill_file = None


def dump_to_jsonl(
    dump: Dict[str, Any], replica: Optional[int] = None
) -> str:
    """Serialize one journal dump as JSONL; ``replica`` tags every line
    (the multi-replica ``/journal`` route format — ``load_journal``
    filters the tag back out)."""
    lines: List[str] = []
    if dump.get("header") is not None:
        row = {"kind": "header", **dump["header"]}
        if replica is not None:
            row["replica"] = int(replica)
        lines.append(json.dumps(row, default=str))
    for e in dump.get("entries") or []:
        row = dict(e)
        if replica is not None:
            row["replica"] = int(replica)
        lines.append(json.dumps(row, default=str))
    return "\n".join(lines) + ("\n" if lines else "")


def _read_journal_rows(path: str) -> Tuple[List[Dict[str, Any]], int]:
    """Parse a journal JSONL file (or spill directory) into raw rows +
    a torn-line count — the shared substrate of ``load_journal`` (one
    replica's stream) and ``load_journal_streams`` (every stream)."""
    paths = [path]
    if os.path.isdir(path):
        paths = [
            os.path.join(path, n)
            for n in sorted(os.listdir(path))
            if n.startswith("journal-") and n.endswith(".jsonl")
        ]
        if not paths:
            raise ValueError(f"no journal-*.jsonl files in {path!r}")
    rows: List[Dict[str, Any]] = []
    torn = 0
    for p in paths:
        with open(p, errors="replace") as f:
            for ln in f:
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    row = json.loads(ln)
                except ValueError:
                    torn += 1
                    continue
                if not isinstance(row, dict):
                    torn += 1
                    continue
                rows.append(row)
    return rows, torn


def load_journal_streams(path: str) -> List[Dict[str, Any]]:
    """Read EVERY replica stream from a (possibly replica-tagged)
    journal: one ``{"header", "entries", "replica", "torn_lines"}``
    dump per tag, tag order (an untagged journal yields one stream with
    ``replica`` None) — the multi-replica substrate the router replay
    re-drives."""
    rows, torn = _read_journal_rows(path)
    tags: List[Optional[int]] = sorted(
        {r["replica"] for r in rows if "replica" in r}
    ) or [None]
    out: List[Dict[str, Any]] = []
    for tag in tags:
        header = None
        entries: List[Dict[str, Any]] = []
        for r in rows:
            if tag is not None and r.get("replica", tag) != tag:
                continue
            r = {k: v for k, v in r.items() if k != "replica"}
            if r.get("kind") == "header":
                header = {k: v for k, v in r.items() if k != "kind"}
            else:
                entries.append(r)
        out.append({
            "header": header, "entries": entries, "replica": tag,
            "path": path, "torn_lines": torn,
        })
    return out


def load_journal(
    path: str, replica: Optional[int] = None
) -> Dict[str, Any]:
    """Read a journal back: a JSONL file, or a spill DIRECTORY (the
    rotated files concatenate oldest-first). Replica-tagged lines (the
    multi-replica ``/journal`` body) are filtered to ``replica``
    (default: the lowest tag present); untagged journals ignore it.
    Crash consistency: a journal written by a process that died hard
    (fault-injected kill, OOM, SIGKILL) legitimately ends in a TORN
    line — the spill buffer was cut mid-record. Unparseable lines are
    skipped and counted (``torn_lines`` in the result) instead of
    failing the whole load; the replay/failover machinery must be able
    to read exactly the journals that crashes produce.

    Returns ``{"header": ..., "entries": [...], "torn_lines": n}``."""
    rows, torn = _read_journal_rows(path)
    tags = sorted(
        {r["replica"] for r in rows if "replica" in r}
    )
    if tags:
        want = tags[0] if replica is None else int(replica)
        rows = [r for r in rows if r.get("replica", want) == want]
        for r in rows:
            r.pop("replica", None)
    header = None
    entries: List[Dict[str, Any]] = []
    for r in rows:
        if r.get("kind") == "header":
            header = {k: v for k, v in r.items() if k != "kind"}
        else:
            entries.append(r)
    return {
        "header": header, "entries": entries, "path": path,
        "torn_lines": torn,
    }


def incomplete_requests(journal: Dict[str, Any]) -> List[Dict[str, Any]]:
    """The failover set: ``submit`` entries with no terminal ``outcome``
    entry — exactly the requests a crashed replica stranded (the process
    died before ``_acct_close`` flushed them, or the ring rotated the
    outcome away). An outcome-less submit is DATA, not corruption: it
    carries everything a resubmission needs (prompt, full SamplingParams
    including the seed, priority/deadline/tenant), and seed-chained rng
    makes the replayed request emit bit-identical tokens."""
    entries = journal.get("entries") or []
    done = {
        e.get("request_id")
        for e in entries
        if e.get("kind") == "outcome"
    }
    return [
        e for e in entries
        if e.get("kind") == "submit" and e.get("request_id") not in done
    ]


# ---------------------------------------------------------------------------
# Replay
# ---------------------------------------------------------------------------
#: engine_header keys build_engine accepts verbatim. The header's
#: ``router`` section (driver-side policy knobs, see
#: serve.router.ROUTER_HEADER_KEYS) rebuilds separately through
#: ``serve.router.router_config_from_header`` — replay surfaces it as
#: ``router_config`` so a replayed capture knows the policy that shaped
#: its traffic.
_ENGINE_REBUILD_KEYS = frozenset((
    "num_slots", "max_seq", "prefill_buckets", "decode_fold", "pipeline",
    "prefill_chunk", "prefix_blocks", "prefix_block", "prefix_host_mb",
    "prefix_disk_dir", "prefix_disk_mb", "kvstore_dir", "kvstore_mb",
    "kv_page", "kv_pages",
    "spec", "spec_depth",
    "spec_window", "spec_draft_ckpt", "spec_draft_config",
    "spec_draft_int8", "mesh",
    "fold_ladder", "piggyback_chunks", "kvstore_namespace",
))


def build_replay_scheduler(
    header: Dict[str, Any],
    *,
    ckpt_path: Optional[str] = None,
    model_config: Optional[Dict[str, Any]] = None,
    params: Any = None,
) -> Any:
    """Rebuild an engine + scheduler from a journal header (the replay
    substrate). ``ckpt_path``/``model_config``/``params`` override the
    recorded identity — the ``--replay.ckpt`` knob that turns a
    captured trace into a benchmark for a DIFFERENT engine build."""
    from ray_lightning_tpu.serve.scheduler import Scheduler
    from ray_lightning_tpu.serve.server import build_engine

    eng_cfg = {
        k: v for k, v in (header.get("engine") or {}).items()
        if k in _ENGINE_REBUILD_KEYS
    }
    engine = build_engine(
        ckpt_path=ckpt_path or header.get("ckpt_path"),
        model_config=(
            model_config if model_config is not None
            else header.get("model_config")
        ),
        params=params,
        int8=bool(header.get("int8", False)),
        **eng_cfg,
    )
    sched_cfg = dict(header.get("scheduler") or {})
    return Scheduler(
        engine,
        max_prefills_per_step=int(
            sched_cfg.get("max_prefills_per_step", 1)
        ),
        max_prefill_chunks_per_step=int(
            sched_cfg.get("max_prefill_chunks_per_step", 1)
        ),
        priority_age_s=sched_cfg.get("priority_age_s"),
    )


def _pct(sorted_vals: List[float], q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    idx = min(
        len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1)))
    )
    return sorted_vals[idx]


def _recorded_perf(
    entries: List[Dict[str, Any]], outcomes: Dict[str, Dict[str, Any]]
) -> Dict[str, Any]:
    """The recorded run's perf from its own journal: tokens/s over the
    submit->last-outcome span, TTFT percentiles from the outcome
    entries, goodput (sum/sum) from the embedded ledger records."""
    sub_t = [e["t_mono"] for e in entries if e["kind"] == "submit"]
    out_t = [o["t_mono"] for o in outcomes.values()]
    tokens = sum(len(o.get("tokens") or []) for o in outcomes.values())
    span = (max(out_t) - min(sub_t)) if sub_t and out_t else 0.0
    ttfts = sorted(
        o["ttft_s"] for o in outcomes.values() if o.get("ttft_s") is not None
    )
    dev = sum(
        float((o.get("cost") or {}).get("device_s", 0.0))
        for o in outcomes.values()
    )
    return {
        "tokens": tokens,
        "span_s": round(span, 6),
        "tokens_per_sec": round(tokens / span, 3) if span > 0 else None,
        "ttft_p50_s": _pct(ttfts, 0.50),
        "ttft_p95_s": _pct(ttfts, 0.95),
        "goodput_tokens_per_device_s": (
            round(tokens / dev, 3) if dev > 0 else None
        ),
    }


def replay_journal(
    journal: Dict[str, Any],
    *,
    ckpt_path: Optional[str] = None,
    model_config: Optional[Dict[str, Any]] = None,
    params: Any = None,
    scheduler: Any = None,
    timing: str = "virtual",
    max_steps: int = 200_000,
) -> Dict[str, Any]:
    """Re-drive a recorded stream and assert bit-exact token output.

    ``timing="virtual"`` (default) replays as fast as the engine will
    go: submissions land in recorded order and each recorded
    cancellation fires deterministically once its request has emitted
    the recorded token count — so truncated requests compare exactly
    on their recorded prefix and finished requests compare exactly in
    full. ``timing="wall"`` honors the recorded inter-arrival times
    (submits, cancels, and deadlines fire at their recorded offsets)
    and emits a perf comparison against the recorded run's ledger.

    Returns a verdict dict: ``exact`` (every compared request matched),
    ``divergence`` (first mismatch: request id, token index, expected
    vs got) or None, per-request rows, and ``perf`` in wall mode.
    """
    if timing not in ("virtual", "wall"):
        raise ValueError(
            f"timing must be 'virtual' or 'wall', got {timing!r}"
        )
    header = journal.get("header")
    entries = list(journal.get("entries") or [])
    if scheduler is None:
        if header is None:
            raise ValueError(
                "journal has no header; pass a prebuilt scheduler= or "
                "record with a header (ServeReplica journals always do)"
            )
        scheduler = build_replay_scheduler(
            header,
            ckpt_path=ckpt_path,
            model_config=model_config,
            params=params,
        )
    from ray_lightning_tpu.serve.scheduler import SamplingParams

    submits = [e for e in entries if e.get("kind") == "submit"]
    cancels = [e for e in entries if e.get("kind") == "cancel"]
    outcomes = {
        e["request_id"]: e for e in entries if e.get("kind") == "outcome"
    }
    cancelled_rids = {
        e["request_id"] for e in cancels if e.get("known", True)
    }
    replayed: Dict[str, List[int]] = {}
    replay_outcome: Dict[str, str] = {}
    open_rids = [
        e["request_id"] for e in submits
        if e["request_id"] not in outcomes
    ]

    def _submit(entry: Dict[str, Any], deadline_s: Optional[float]) -> None:
        sp = {
            k: v for k, v in (entry.get("sampling") or {}).items()
            if k in SAMPLING_FIELDS and v is not None
        }
        scheduler.submit(
            entry["prompt"],
            SamplingParams(**sp),
            request_id=entry["request_id"],
            priority=int(entry.get("priority", 0)),
            deadline_s=deadline_s,
            tenant=entry.get("tenant"),
        )

    def _harvest(events: Iterable[Any]) -> None:
        for ev in events:
            if ev.token is not None:
                replayed.setdefault(ev.request_id, []).append(
                    int(ev.token)
                )
            if ev.done:
                replay_outcome[ev.request_id] = (
                    "finished" if ev.reason in ("token", "finished")
                    else ev.reason
                )

    t_replay0 = time.monotonic()
    if timing == "virtual":
        # Deterministic truncation: a recorded cancel/expiry fires once
        # its request has emitted the recorded token count, so the
        # recorded prefix is always covered before eviction.
        cancel_after: Dict[str, int] = {}
        done_cancel: set = set()
        for e in submits:
            rid = e["request_id"]
            out = outcomes.get(rid)
            if out is None:
                continue  # in flight at capture; nothing to compare
            k = len(out.get("tokens") or [])
            if out["outcome"] == "finished":
                _submit(e, None)
            elif k > 0:
                _submit(e, None)
                cancel_after[rid] = k
            elif out["outcome"] == "expired":
                # Queued-expired with zero output: an already-past
                # deadline reproduces the expiry deterministically.
                _submit(e, 0.0)
            else:
                _submit(e, None)
                scheduler.cancel(rid)  # queued-cancel path
        steps = 0
        while scheduler.has_work() and steps < max_steps:
            _harvest(scheduler.step())
            steps += 1
            for rid, k in cancel_after.items():
                if rid not in done_cancel and len(
                    replayed.get(rid, [])
                ) >= k:
                    scheduler.cancel(rid)
                    done_cancel.add(rid)
    else:
        # Wall timing: the recorded stream at its recorded pace.
        stream = sorted(
            [e for e in entries if e.get("kind") in ("submit", "cancel")],
            key=lambda e: e.get("t_mono", 0.0),
        )
        base = stream[0]["t_mono"] if stream else 0.0
        idx = 0
        steps = 0
        while (
            idx < len(stream) or scheduler.has_work()
        ) and steps < max_steps:
            now = time.monotonic() - t_replay0
            while idx < len(stream) and (
                stream[idx].get("t_mono", 0.0) - base
            ) <= now:
                e = stream[idx]
                idx += 1
                if e["kind"] == "submit":
                    _submit(e, e.get("deadline_s"))
                elif e.get("known", True):
                    scheduler.cancel(e["request_id"])
            if scheduler.has_work():
                _harvest(scheduler.step())
                steps += 1
            elif idx < len(stream):
                time.sleep(
                    min(
                        0.002,
                        max(
                            0.0,
                            stream[idx]["t_mono"] - base - (
                                time.monotonic() - t_replay0
                            ),
                        ),
                    )
                )
    replay_span = time.monotonic() - t_replay0

    # -- exactness: first divergence in recorded order --------------------
    divergence: Optional[Dict[str, Any]] = None
    rows: List[Dict[str, Any]] = []
    compared = tokens_compared = 0
    for e in submits:
        rid = e["request_id"]
        out = outcomes.get(rid)
        if out is None:
            continue
        want = [int(t) for t in (out.get("tokens") or [])]
        got = replayed.get(rid, [])
        truncated = out["outcome"] != "finished"
        # Wall-mode truncations re-fire at recorded WALL offsets, so the
        # replayed count may differ; only the common prefix is asserted.
        limit = min(len(want), len(got)) if (
            truncated and timing == "wall"
        ) else len(want)
        row_div = None
        for i in range(min(limit, len(got))):
            if want[i] != got[i]:
                row_div = {
                    "request_id": rid, "token_index": i,
                    "expected": want[i], "got": got[i],
                }
                break
        if row_div is None and len(got) < limit:
            row_div = {
                "request_id": rid, "token_index": len(got),
                "expected": want[len(got)], "got": None,
            }
        if row_div is None and not truncated and len(got) > len(want):
            row_div = {
                "request_id": rid, "token_index": len(want),
                "expected": None, "got": got[len(want)],
            }
        compared += 1
        tokens_compared += limit
        rows.append({
            "request_id": rid,
            "outcome_recorded": out["outcome"],
            "outcome_replayed": replay_outcome.get(rid),
            "tokens_recorded": len(want),
            "tokens_replayed": len(got),
            "match": row_div is None,
        })
        if divergence is None and row_div is not None:
            divergence = row_div
    result: Dict[str, Any] = {
        "exact": divergence is None and compared > 0,
        "divergence": divergence,
        "timing": timing,
        "requests": len(submits),
        "compared": compared,
        "open": len(open_rids),
        "tokens_compared": tokens_compared,
        "replay_span_s": round(replay_span, 6),
        "rows": rows,
    }
    if header and header.get("router"):
        from ray_lightning_tpu.serve.router import (
            router_config_from_header,
        )

        result["router_config"] = router_config_from_header(header)
    if header and header.get("kvfleet"):
        from ray_lightning_tpu.serve.kvfleet import (
            kvfleet_config_from_header,
        )

        result["kvfleet_config"] = kvfleet_config_from_header(header)
    if header and header.get("kvstore"):
        from ray_lightning_tpu.serve.kvstore import (
            kvstore_config_from_header,
        )

        result["kvstore_config"] = kvstore_config_from_header(header)
    if timing == "wall":
        snap = scheduler.metrics.snapshot()
        rep_tokens = sum(len(v) for v in replayed.values())
        recorded = _recorded_perf(entries, outcomes)
        replayed_perf = {
            "tokens": rep_tokens,
            "span_s": round(replay_span, 6),
            "tokens_per_sec": (
                round(rep_tokens / replay_span, 3)
                if replay_span > 0 else None
            ),
            "ttft_p50_s": snap.get("ttft_p50_s"),
            "ttft_p95_s": snap.get("ttft_p95_s"),
            "goodput_tokens_per_device_s": (
                snap.get("cost", {}).get("goodput_tokens_per_device_s")
            ),
        }
        ratio = {}
        for key in ("tokens_per_sec", "goodput_tokens_per_device_s"):
            a, b = replayed_perf.get(key), recorded.get(key)
            if a and b:
                ratio[key] = round(a / b, 4)
        result["perf"] = {
            "recorded": recorded,
            "replayed": replayed_perf,
            "replay_vs_recorded": ratio,
        }
        # Phase-level diff: the recorded outcomes' compact anatomy
        # ledgers vs the ones the replay scheduler just produced —
        # "the incident's kv_fetch was 40x this machine's" is the
        # autopsy answer a throughput ratio can't give.
        rec_phases = [
            o["phases"] for o in outcomes.values()
            if isinstance(o.get("phases"), dict)
        ]
        phase_fn = getattr(scheduler.metrics, "phase_records", None)
        rep_phases = phase_fn() if phase_fn is not None else []
        if rec_phases or rep_phases:
            from ray_lightning_tpu.obs.anatomy import aggregate_phases

            result["perf"]["phases"] = {
                "recorded": aggregate_phases(rec_phases),
                "replayed": aggregate_phases(rep_phases),
            }
    return result


def replay_journal_router(
    journals: List[Dict[str, Any]],
    *,
    ckpt_path: Optional[str] = None,
    model_config: Optional[Dict[str, Any]] = None,
    params: Any = None,
    scheduler: Any = None,
    speed: float = 1.0,
    max_steps: int = 200_000,
    registry: Optional[Any] = None,
) -> Dict[str, Any]:
    """Re-drive a captured MULTI-replica journal through the ROUTER.

    ``journals`` is ``load_journal_streams``'s output: every replica's
    recorded stream. The merged submit stream (deduplicated by request
    id — a failed-over or disagg-shipped request appears in more than
    one stream) replays at recorded wall pace scaled by ``speed``
    (10.0 = ten times faster than recorded), and EVERY submit routes
    through a ``Router.plan`` call rebuilt from the journal header's
    recorded policy knobs — the control plane under load, not just the
    engine. Shedding is forced OFF (a replay must place every request:
    the zero-lost assertion is the point) and recorded truncations fire
    deterministically at their recorded token counts, so exactness does
    not depend on the replay speed. Execution lands on one replay
    scheduler (greedy decode is replica-independent by the seed-chain
    contract, so the token comparison is exact regardless of which
    replica originally decoded).

    Returns a verdict dict: ``exact``, ``divergence``, ``requests`` /
    ``compared`` / ``planned`` / ``lost`` counts (``lost`` MUST be 0 —
    any entry here is a request the router failed to place), ``speed``,
    ``streams``, and the router's own plan-throughput ``router`` rows.
    """
    from ray_lightning_tpu.serve.router import (
        Router,
        router_config_from_header,
    )
    from ray_lightning_tpu.serve.scheduler import SamplingParams

    speed = float(speed)
    if speed <= 0:
        raise ValueError(f"speed must be > 0, got {speed}")
    if not journals:
        raise ValueError("no journal streams to replay")
    header = next(
        (j["header"] for j in journals if j.get("header")), None
    )
    # Merge + dedup: first submit per id wins (the original placement);
    # the outcome with the MOST tokens wins (a shipped/migrated leg
    # records a stub — the finishing replica holds the full stream).
    submits_by_rid: Dict[str, Dict[str, Any]] = {}
    outcomes: Dict[str, Dict[str, Any]] = {}
    for j in journals:
        for e in j.get("entries") or []:
            kind = e.get("kind")
            rid = e.get("request_id")
            if kind == "submit":
                submits_by_rid.setdefault(rid, e)
            elif kind == "outcome":
                prev = outcomes.get(rid)
                if prev is None or len(e.get("tokens") or []) > len(
                    prev.get("tokens") or []
                ):
                    outcomes[rid] = e
    submits = sorted(
        submits_by_rid.values(), key=lambda e: e.get("t_mono", 0.0)
    )
    if scheduler is None:
        if header is None:
            raise ValueError(
                "no journal stream has a header; pass a prebuilt "
                "scheduler= or record with headers"
            )
        scheduler = build_replay_scheduler(
            header,
            ckpt_path=ckpt_path,
            model_config=model_config,
            params=params,
        )
    rcfg = router_config_from_header(header)
    router = Router(
        client=None,  # no live fleet: neutral views over `alive`
        refresh_s=float("inf"),
        affinity=bool(rcfg.get("affinity", True)),
        prefix_block=int(rcfg.get("prefix_block", 16) or 16),
        shed=False,  # zero-lost is the contract under test
        directory_shards=int(rcfg.get("directory_shards", 1) or 1),
        registry=registry,
    )
    alive = list(range(max(1, len(journals))))

    replayed: Dict[str, List[int]] = {}
    replay_outcome: Dict[str, str] = {}
    planned: Dict[str, int] = {}
    lost: List[str] = []

    def _submit(entry: Dict[str, Any], deadline_s: Optional[float]) -> None:
        sp = {
            k: v for k, v in (entry.get("sampling") or {}).items()
            if k in SAMPLING_FIELDS and v is not None
        }
        scheduler.submit(
            entry["prompt"],
            SamplingParams(**sp),
            request_id=entry["request_id"],
            priority=int(entry.get("priority", 0)),
            deadline_s=deadline_s,
            tenant=entry.get("tenant"),
        )

    def _harvest(events: Iterable[Any]) -> None:
        for ev in events:
            if ev.token is not None:
                replayed.setdefault(ev.request_id, []).append(
                    int(ev.token)
                )
            if ev.done:
                replay_outcome[ev.request_id] = (
                    "finished" if ev.reason in ("token", "finished")
                    else ev.reason
                )

    base = submits[0].get("t_mono", 0.0) if submits else 0.0
    cancel_after: Dict[str, int] = {}
    done_cancel: set = set()
    t0 = time.monotonic()
    pos = 0
    steps = 0
    while (pos < len(submits) or scheduler.has_work()) and steps < max_steps:
        now = time.monotonic() - t0
        while pos < len(submits) and (
            (submits[pos].get("t_mono", 0.0) - base) / speed
        ) <= now:
            e = submits[pos]
            pos += 1
            rid = e["request_id"]
            sp = e.get("sampling") or {}
            try:
                plan = router.plan(
                    e["prompt"],
                    max_new_tokens=int(sp.get("max_new_tokens") or 32),
                    priority=int(e.get("priority", 0)),
                    deadline_s=None,  # recorded deadlines scale with
                    alive=alive,      # speed; zero-lost must not
                )
                planned[rid] = int(plan.replica)
                router.observe_route(
                    e["prompt"], int(plan.replica),
                    digests=getattr(plan, "digests", None),
                )
            except Exception:  # noqa: BLE001 - counted, asserted == 0
                lost.append(rid)
                continue
            out = outcomes.get(rid)
            if out is None:
                continue  # open at capture; planned but not compared
            k = len(out.get("tokens") or [])
            if out["outcome"] == "finished":
                _submit(e, None)
            elif k > 0:
                # Deterministic truncation at the recorded count — the
                # same virtual-mode trick replay_journal uses, so 10x
                # replays compare exactly like 1x replays.
                _submit(e, None)
                cancel_after[rid] = k
            elif out["outcome"] == "expired":
                _submit(e, 0.0)
            else:
                _submit(e, None)
                scheduler.cancel(rid)
        if scheduler.has_work():
            _harvest(scheduler.step())
            steps += 1
            for rid, k in cancel_after.items():
                if rid not in done_cancel and len(
                    replayed.get(rid, [])
                ) >= k:
                    scheduler.cancel(rid)
                    done_cancel.add(rid)
        elif pos < len(submits):
            time.sleep(
                min(
                    0.002,
                    max(
                        0.0,
                        (submits[pos].get("t_mono", 0.0) - base) / speed
                        - (time.monotonic() - t0),
                    ),
                )
            )
    replay_span = time.monotonic() - t0

    divergence: Optional[Dict[str, Any]] = None
    rows: List[Dict[str, Any]] = []
    compared = tokens_compared = 0
    for e in submits:
        rid = e["request_id"]
        out = outcomes.get(rid)
        if out is None or rid in lost:
            continue
        want = [int(t) for t in (out.get("tokens") or [])]
        got = replayed.get(rid, [])
        row_div = None
        for i in range(min(len(want), len(got))):
            if want[i] != got[i]:
                row_div = {
                    "request_id": rid, "token_index": i,
                    "expected": want[i], "got": got[i],
                }
                break
        if row_div is None and len(got) < len(want):
            row_div = {
                "request_id": rid, "token_index": len(got),
                "expected": want[len(got)], "got": None,
            }
        if row_div is None and out["outcome"] == "finished" and len(
            got
        ) > len(want):
            row_div = {
                "request_id": rid, "token_index": len(want),
                "expected": None, "got": got[len(want)],
            }
        compared += 1
        tokens_compared += len(want)
        rows.append({
            "request_id": rid,
            "replica_planned": planned.get(rid),
            "outcome_recorded": out["outcome"],
            "outcome_replayed": replay_outcome.get(rid),
            "tokens_recorded": len(want),
            "tokens_replayed": len(got),
            "match": row_div is None,
        })
        if divergence is None and row_div is not None:
            divergence = row_div
    return {
        "exact": divergence is None and compared > 0 and not lost,
        "divergence": divergence,
        "timing": "wall",
        "speed": speed,
        "streams": len(journals),
        "requests": len(submits),
        "planned": len(planned),
        "lost": len(lost),
        "lost_ids": lost,
        "compared": compared,
        "open": len(submits) - len(outcomes),
        "tokens_compared": tokens_compared,
        "replay_span_s": round(replay_span, 6),
        "router": router.rows(),
        "router_config": rcfg,
        "rows": rows,
    }
