"""Trainer + fabric telemetry: step breakdown, tokens/s + MFU, heartbeats.

:class:`TrainTelemetry` is fed by ``TrainingLoop``'s fit loop with one
record per dispatched chunk, split into the three host-observable
segments of a step's wall time::

    data_wait : blocking on the staged-batch iterator (host assembly +
                H2D backpressure — with async dispatch this is also
                where device compute surfaces)
    step      : the compiled-step call (dispatch; near-zero when async)
    drain     : log fetch, callbacks, mid-epoch val — everything between
                the step returning and the next batch pull

The segments are consecutive monotonic-clock intervals, so they sum to
the chunk's wall time by construction (the test asserts it to guard the
instrumentation against drift as the loop evolves). Aggregates feed the
process registry (``rlt_train_*``) and ship to the driver in
``trainer_state["telemetry"]``.

Throughput: when the module exposes ``batch_size`` and a config with
``max_seq`` (GPTLM does), the loop reports tokens/s; with a known chip
peak (utils/flops) that becomes MFU. On CPU / unknown chips MFU is
omitted rather than fabricated.

:func:`heartbeats_to_registry` folds ``fabric.heartbeats()`` payloads
(rss, cpu, last-call age per worker) into the same registry, so one
Prometheus scrape covers serve, trainer, and fabric.
"""
from __future__ import annotations

import time
from typing import Any, Dict, Optional

from ray_lightning_tpu.obs.registry import MetricsRegistry, get_registry


class TrainTelemetry:
    """Per-fit step-time breakdown + throughput, registry-backed."""

    def __init__(self, registry: Optional[MetricsRegistry] = None) -> None:
        reg = registry or get_registry()
        self._steps = reg.counter(
            "rlt_train_steps_total", "Optimizer micro-steps executed"
        )
        self._seg = reg.counter(
            "rlt_train_seconds_total",
            "Fit-loop wall seconds by segment (data_wait/step/drain)",
        )
        self._tps = reg.gauge(
            "rlt_train_tokens_per_sec", "Training throughput (global tokens/s)"
        )
        self._mfu = reg.gauge(
            "rlt_train_mfu", "Model FLOPs utilization (0-1), when peak known"
        )
        # Host mirrors (snapshot() must not depend on registry internals).
        self.steps = 0
        self.chunks = 0
        self.data_wait_s = 0.0
        self.step_s = 0.0
        self.drain_s = 0.0
        self.wall_s = 0.0
        self.tokens_per_sec: Optional[float] = None
        self.mfu: Optional[float] = None
        self.tokens_total = 0
        # Watchdog progress stamps (obs.health.fit_stall_check): the fit
        # is stalled when neither construction nor the last chunk is
        # recent and the fit has not finished.
        self.created_t = time.monotonic()
        self.last_progress_t: Optional[float] = None
        self.fit_done = False

    def record_chunk(
        self, n_steps: int, data_wait: float, step: float, drain: float
    ) -> None:
        self.last_progress_t = time.monotonic()
        self.steps += int(n_steps)
        self.chunks += 1
        self.data_wait_s += data_wait
        self.step_s += step
        self.drain_s += drain
        self.wall_s += data_wait + step + drain
        self._steps.inc(int(n_steps))
        self._seg.inc(data_wait, segment="data_wait")
        self._seg.inc(step, segment="step")
        self._seg.inc(drain, segment="drain")

    def record_throughput(
        self,
        tokens: int,
        wall_s: float,
        flops_per_token: Optional[float] = None,
        peak_flops_total: Optional[float] = None,
    ) -> None:
        """Tokens processed over ``wall_s``; MFU when both the per-token
        FLOPs estimate and the aggregate chip peak are known."""
        if wall_s <= 0 or tokens <= 0:
            return
        self.tokens_total += int(tokens)
        self.tokens_per_sec = round(tokens / wall_s, 3)
        self._tps.set(self.tokens_per_sec)
        if flops_per_token and peak_flops_total:
            self.mfu = round(
                self.tokens_per_sec * flops_per_token / peak_flops_total, 4
            )
            self._mfu.set(self.mfu)

    def snapshot(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "steps": self.steps,
            "chunks": self.chunks,
            "data_wait_s": round(self.data_wait_s, 4),
            "step_s": round(self.step_s, 4),
            "drain_s": round(self.drain_s, 4),
            "wall_s": round(self.wall_s, 4),
        }
        if self.wall_s > 0:
            out["data_wait_frac"] = round(self.data_wait_s / self.wall_s, 4)
            out["step_frac"] = round(self.step_s / self.wall_s, 4)
            out["drain_frac"] = round(self.drain_s / self.wall_s, 4)
        if self.tokens_per_sec is not None:
            out["tokens_per_sec"] = self.tokens_per_sec
            out["tokens_total"] = self.tokens_total
        if self.mfu is not None:
            out["mfu"] = self.mfu
        from ray_lightning_tpu.obs.jaxmon import compile_stats

        stats = compile_stats()
        if stats is not None:
            out["compile_events"] = stats.snapshot()
        return out


def flops_per_token(
    n_params: int, n_layer: int, d_model: int, seq: int
) -> float:
    """PaLM-style training FLOPs/token: 6N + the attention term."""
    return 6.0 * n_params + 12.0 * n_layer * d_model * seq


def peak_flops_total(device_kind: str, n_devices: int) -> Optional[float]:
    """Aggregate peak bf16 FLOP/s across ``n_devices`` chips; None when
    the chip kind is unknown (CPU) — callers skip MFU then."""
    from ray_lightning_tpu.utils.flops import peak_flops_for

    peak = peak_flops_for(device_kind)
    return None if peak is None else peak * max(1, int(n_devices))


def heartbeats_to_registry(
    heartbeats: Dict[str, Dict[str, Any]],
    registry: Optional[MetricsRegistry] = None,
) -> None:
    """Fold ``fabric.heartbeats()`` into worker-labelled gauges."""
    reg = registry or get_registry()
    gauges = {
        "rss_bytes": reg.gauge(
            "rlt_fabric_worker_rss_bytes", "Worker resident set size"
        ),
        "cpu_s": reg.gauge(
            "rlt_fabric_worker_cpu_seconds", "Worker process CPU seconds"
        ),
        "uptime_s": reg.gauge(
            "rlt_fabric_worker_uptime_seconds", "Worker process uptime"
        ),
        "calls_handled": reg.gauge(
            "rlt_fabric_worker_calls_handled", "RPCs completed by the worker"
        ),
        "calls_in_flight": reg.gauge(
            "rlt_fabric_worker_calls_in_flight",
            "RPCs currently executing (0 or 1; the actor loop is serial)",
        ),
        "last_call_age_s": reg.gauge(
            "rlt_fabric_worker_last_call_age_seconds",
            "Seconds since the worker last finished an RPC",
        ),
        "age_s": reg.gauge(
            "rlt_fabric_worker_heartbeat_age_seconds",
            "Driver-side age of the worker's last heartbeat",
        ),
    }
    for actor_id, hb in heartbeats.items():
        for key, gauge in gauges.items():
            val = hb.get(key)
            if val is not None:
                gauge.set(float(val), actor=actor_id)
    # Drop series whose actor is absent from this snapshot: a killed or
    # crashed worker leaves heartbeats(), and its gauges must leave the
    # scrape with it instead of reporting stale values forever.
    for gauge in gauges.values():
        for label_key in gauge.samples():
            labels = dict(label_key)
            actor = labels.get("actor")
            if actor is not None and actor not in heartbeats:
                gauge.remove(**labels)
