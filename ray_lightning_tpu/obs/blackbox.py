"""Flight recorder: self-contained forensic bundles for post-mortems.

When something goes wrong — the watchdog flips a component to
``unhealthy``, the trainer's fit raises, an operator runs ``rlt doctor``
— the process should leave a BLACK BOX: everything needed to diagnose
the failure without reproducing it. :func:`dump_bundle` writes one
bundle directory containing:

- ``metrics.prom``   — the registry rendered in Prometheus text format
- ``events.jsonl``   — the structured event-log tail (obs.events)
- ``trace.json``     — recent request traces as Chrome trace JSON
- ``journal.jsonl``  — the workload journal (obs.journal): the recorded
                       request stream + outcomes, replayable via
                       ``rlt replay``
- ``health.json``    — the health report at dump time (obs.health)
- ``heartbeats.json``— the fabric heartbeat snapshot (driver-side)
- ``config.json``    — the serve/train config the process ran with
- ``versions.json``  — python/platform/jax versions + device kinds
- ``stacks.txt``     — an all-threads stack dump via ``faulthandler``
                       (the "where is it stuck" answer for hangs)
- ``manifest.json``  — reason, timestamp, file list, collector errors

Every artifact is collected independently: a broken collector records
its error in the manifest instead of losing the rest of the bundle.

:class:`FlightRecorder` wraps ``dump_bundle`` with the operational
policy — automatic dumps are rate-limited (``min_interval_s``) and the
output directory keeps only the last ``keep`` bundles, so a flapping
watchdog cannot fill a disk. ``crash_dump`` is the module-level
convenience the trainer's exception path uses (process registry +
event log, ``RLT_BLACKBOX_DIR`` destination).
"""
from __future__ import annotations

import faulthandler
import json
import os
import platform
import re
import sys
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ray_lightning_tpu.obs.events import EventLog, get_event_log
from ray_lightning_tpu.obs.registry import MetricsRegistry, get_registry


def default_blackbox_dir() -> str:
    """``RLT_BLACKBOX_DIR`` or a per-user tempdir fallback."""
    return os.environ.get("RLT_BLACKBOX_DIR") or os.path.join(
        tempfile.gettempdir(), "rlt_blackbox"
    )


def collect_versions() -> Dict[str, Any]:
    """Runtime provenance. jax info only when jax is already imported —
    a forensic dump must never be the thing that initializes a backend."""
    out: Dict[str, Any] = {
        "python": sys.version,
        "platform": platform.platform(),
        "pid": os.getpid(),
    }
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            out["jax"] = jax.__version__
            out["devices"] = [
                f"{d.platform}:{d.device_kind}" for d in jax.devices()
            ]
        except Exception as exc:  # noqa: BLE001 - a wedged backend is
            out["jax_error"] = repr(exc)  # exactly when we're dumping
    return out


def _slug(reason: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]+", "-", reason).strip("-")[:48] or "dump"


def dump_bundle(
    outdir: str,
    *,
    registry: Optional[MetricsRegistry] = None,
    events: Optional[EventLog] = None,
    tracer: Optional[Any] = None,
    journal: Optional[Any] = None,
    health: Optional[Any] = None,
    heartbeats: Optional[Dict[str, Any]] = None,
    config: Optional[Dict[str, Any]] = None,
    reason: str = "manual",
    trace_n: int = 16,
    events_n: int = 512,
) -> Dict[str, Any]:
    """Write one forensic bundle under ``outdir``; returns its manifest
    (``dir``, ``files``, per-collector ``errors``). ``health`` may be a
    dict or an :class:`obs.health.HealthReport`; ``tracer`` a
    :class:`obs.trace.RequestTracer`."""
    ts = time.time()
    stamp = time.strftime("%Y%m%d-%H%M%S", time.gmtime(ts))
    bundle_dir = os.path.join(
        outdir, f"bundle-{stamp}-{os.getpid()}-{_slug(reason)}"
    )
    os.makedirs(bundle_dir, exist_ok=True)
    files: List[str] = []
    errors: Dict[str, str] = {}

    def write(name: str, produce: Callable[[], str]) -> None:
        try:
            content = produce()
        except Exception as exc:  # noqa: BLE001 - record, keep dumping
            errors[name] = repr(exc)
            return
        if content is None:
            return
        with open(os.path.join(bundle_dir, name), "w") as f:
            f.write(content)
        files.append(name)

    if registry is not None:
        write("metrics.prom", registry.render)
    if events is not None:
        write("events.jsonl", lambda: events.to_jsonl(events_n))
    if tracer is not None:
        def _trace() -> str:
            from ray_lightning_tpu.obs.trace import to_chrome_trace

            traces = tracer.recent_traces(trace_n)
            return json.dumps(
                to_chrome_trace({r: e for r, e in traces.items() if e})
            )
        write("trace.json", _trace)
    if journal is not None:
        # The workload journal (obs.journal) makes the bundle
        # REPLAYABLE: `rlt replay <bundle>/journal.jsonl` re-drives the
        # recorded request stream bit-exactly.
        write("journal.jsonl", journal.to_jsonl)
    if health is not None:
        write("health.json", lambda: json.dumps(
            health.to_dict() if hasattr(health, "to_dict") else health,
            default=str, indent=2,
        ))
    if heartbeats is not None:
        write("heartbeats.json",
              lambda: json.dumps(heartbeats, default=str, indent=2))
    if config is not None:
        write("config.json",
              lambda: json.dumps(config, default=str, indent=2))
    write("versions.json", lambda: json.dumps(collect_versions(), indent=2))

    # All-threads stack dump: the hang-forensics centerpiece. Written
    # directly (not via write()) because faulthandler wants a real fd.
    try:
        stacks_path = os.path.join(bundle_dir, "stacks.txt")
        with open(stacks_path, "w") as f:
            faulthandler.dump_traceback(file=f, all_threads=True)
        files.append("stacks.txt")
    except Exception as exc:  # noqa: BLE001
        errors["stacks.txt"] = repr(exc)

    manifest = {
        "reason": reason,
        "ts": ts,
        "dir": bundle_dir,
        "files": sorted(files),
        "errors": errors,
    }
    with open(os.path.join(bundle_dir, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=2)
    return manifest


def read_bundle(bundle_dir: str) -> Dict[str, str]:
    """``{filename: text}`` of a bundle — the pull format ``rlt doctor``
    and the ``debug_dump(pull=True)`` RPCs ship over the wire."""
    out: Dict[str, str] = {}
    for name in sorted(os.listdir(bundle_dir)):
        path = os.path.join(bundle_dir, name)
        if os.path.isfile(path):
            with open(path, "r", errors="replace") as f:
                out[name] = f.read()
    return out


class FlightRecorder:
    """Bundle policy: rate-limited automatic dumps, bounded retention.

    The ``*_fn`` sources are called AT DUMP TIME so a bundle always
    carries current state; ``maybe_dump`` is the watchdog's trigger
    (rate-limited), ``dump`` the on-demand RPC's (always fires). Both
    prune the output directory to the newest ``keep`` bundles.
    """

    def __init__(
        self,
        outdir: Optional[str] = None,
        keep: int = 3,
        min_interval_s: float = 30.0,
        registry: Optional[MetricsRegistry] = None,
        events: Optional[EventLog] = None,
        tracer: Optional[Any] = None,
        journal: Optional[Any] = None,
        health_fn: Optional[Callable[[], Any]] = None,
        heartbeats_fn: Optional[Callable[[], Dict[str, Any]]] = None,
        config: Optional[Dict[str, Any]] = None,
    ) -> None:
        self.outdir = outdir or default_blackbox_dir()
        self.keep = max(1, int(keep))
        self.min_interval_s = float(min_interval_s)
        self._registry = registry
        self._events = events
        self._tracer = tracer
        self._journal = journal
        self._health_fn = health_fn
        self._heartbeats_fn = heartbeats_fn
        self._config = config
        self._lock = threading.Lock()
        self._last_dump: Optional[float] = None

    def bundles(self) -> List[str]:
        """Bundle directories under ``outdir``, oldest first."""
        try:
            names = sorted(
                n for n in os.listdir(self.outdir)
                if n.startswith("bundle-")
                and os.path.isdir(os.path.join(self.outdir, n))
            )
        except OSError:
            return []
        return [os.path.join(self.outdir, n) for n in names]

    def dump(self, reason: str = "manual") -> Dict[str, Any]:
        with self._lock:
            self._last_dump = time.monotonic()
        manifest = dump_bundle(
            self.outdir,
            registry=self._registry,
            events=self._events,
            tracer=self._tracer,
            journal=self._journal,
            health=self._health_fn() if self._health_fn else None,
            heartbeats=self._heartbeats_fn() if self._heartbeats_fn else None,
            config=self._config,
            reason=reason,
        )
        self._prune()
        return manifest

    def maybe_dump(self, reason: str = "auto") -> Optional[Dict[str, Any]]:
        """Rate-limited dump: None when the last one was less than
        ``min_interval_s`` ago (a flapping watchdog must not spam)."""
        with self._lock:
            now = time.monotonic()
            if (
                self._last_dump is not None
                and now - self._last_dump < self.min_interval_s
            ):
                return None
        return self.dump(reason)

    def _prune(self) -> None:
        import shutil

        bundles = self.bundles()
        for stale in bundles[: max(0, len(bundles) - self.keep)]:
            try:
                shutil.rmtree(stale)
            except OSError:
                pass


# ---------------------------------------------------------------------------
# Process-default crash recorder (the trainer exception path)
# ---------------------------------------------------------------------------
_DEFAULT: Optional[FlightRecorder] = None
_DEFAULT_LOCK = threading.Lock()


def default_recorder() -> FlightRecorder:
    """Lazy process-default recorder over the process registry + event
    log, writing to ``RLT_BLACKBOX_DIR``; rebuilt if the env-configured
    destination changes."""
    global _DEFAULT
    outdir = default_blackbox_dir()
    with _DEFAULT_LOCK:
        if _DEFAULT is None or _DEFAULT.outdir != outdir:
            _DEFAULT = FlightRecorder(
                outdir=outdir,
                min_interval_s=5.0,
                registry=get_registry(),
                events=get_event_log(),
            )
        return _DEFAULT


def crash_dump(reason: str) -> Optional[Dict[str, Any]]:
    """Best-effort bundle on an exception path: rate-limited, and NEVER
    raises — forensics must not mask the original error."""
    try:
        return default_recorder().maybe_dump(reason)
    except Exception:  # noqa: BLE001
        return None
