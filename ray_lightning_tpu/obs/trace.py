"""Request tracing: typed lifecycle spans + Chrome trace-event export.

Every serve request leaves a trail of timestamped events — submit →
queued → admitted → each prefill chunk → prefix-cache seed → each decode
fold it rode → first token → finish/cancel/expire — appended to a
bounded per-replica ring buffer (:class:`RequestTracer`). Recording is a
tuple append under one lock, no I/O and no string formatting, so the
decode hot loop pays nanoseconds per event (the bench measures the
observer effect as ``obs_overhead``; the smoke test pins it < 5%).

Reconstruction happens at READ time: ``trace(request_id)`` scans the
ring, and :func:`to_chrome_trace` converts traces into Chrome
trace-event JSON — the `{"traceEvents": [...]}` format Perfetto and
chrome://tracing open directly. Lifecycle phases (queued / prefill /
decode) are derived as complete ("X") events from the markers; the raw
markers ride along as instant ("i") events on the same track.

Event names (the ``SPAN_*`` constants) are the trace's type system; the
well-formedness contract per admitted request is::

    submit <= queued <= admitted <= [prefill_chunk...] <= first_token
           <= finish | cancel | expire

with ``prefix_seed`` inside the admission block (between queued and the
first chunk — the engine records it while seeding the slot) on a
prefix-cache hit, and ``decode_fold`` events between first_token and the
terminal event. tests/test_obs.py asserts it across chunked-prefill x
prefix-hit x mid-fold-cancel.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

# -- span names (the typed vocabulary) ---------------------------------
#: Request entered the DRIVER-side client (``submit()`` entry or batch
#: coalescing enqueue) — the earliest client-observed instant, and the
#: anchor the anatomy ledger's ``batch_window`` phase starts from.
SPAN_CLIENT_RECV = "client_recv"
#: Driver finished route planning for the request (attrs: replica) —
#: closes ``batch_window`` and opens ``route_plan`` in the ledger.
SPAN_CLIENT_PLAN = "client_plan"
#: Request left the DRIVER-side client (recorded by ServeClient in its
#: own process-local tracer — the cross-process anchor every stitched
#: trace hangs off: replica/follower spans resolve back to it by
#: request id, and the client→admitted gap becomes the derived
#: ``client_wait`` span in :func:`merge_chrome_trace`).
SPAN_CLIENT_SUBMIT = "client_submit"
SPAN_SUBMIT = "submit"          #: request arrived at the RPC surface
SPAN_QUEUED = "queued"          #: entered the scheduler queue
SPAN_ADMITTED = "admitted"      #: entered an engine slot
SPAN_PREFIX_SEED = "prefix_seed"  #: slot KV seeded from the prefix pool
SPAN_PREFILL = "prefill"        #: monolithic (fused) prefill dispatched
SPAN_PREFILL_CHUNK = "prefill_chunk"  #: one chunk of a chunked prefill
SPAN_FIRST_TOKEN = "first_token"
SPAN_DECODE_FOLD = "decode_fold"  #: one engine fold this request rode
#: draft/verify accounting of one speculative fold this request rode
#: (attrs: tokens emitted, drafted, accepted)
SPAN_SPEC_VERIFY = "spec_verify"
SPAN_FINISH = "finish"
SPAN_CANCEL = "cancel"
SPAN_EXPIRE = "expire"
#: Fleet KV plane: the request parked transfer-pending while its warm
#: pages fetch from a peer (attrs: peer, blocks).
SPAN_KV_FETCH = "kv_fetch"
#: Persistent KV store: the request parked while its chain fetches from
#: the object store (no live peer held it; attrs: blocks).
SPAN_KVSTORE_FETCH = "kvstore_fetch"
#: Persistent KV store: a parked/stored chain imported back into this
#: replica's pool — the request admits warm on its next queue pass.
SPAN_KV_RESTORE = "kv_restore"
#: Session parking: an idle conversation's chain exported to the
#: persistent store and its device pages freed (attrs: blocks, stored,
#: freed).
SPAN_KV_PARK = "kv_park"
#: Fleet KV plane: a parked transfer resolved — warm pages landed (or
#: the fetch failed and the request falls back to cold prefill). Attrs:
#: source ("peer" | "store"), ok, and on failure the reason. Closes the
#: ledger's ``kv_fetch`` phase; the land→admit gap is ``transfer_park``.
SPAN_KV_LAND = "kv_land"
#: Disaggregated prefill, decode side: shipped KV pages imported into
#: this replica's pool (attrs: src, blocks, layerwise). Recorded by the
#: fleet plane's service loop — the only mark of the ship transit
#: landing before the stream's resubmit arrives.
SPAN_KV_SHIP_LAND = "kv_ship_land"
#: Disaggregated prefill: this engine finished the prefill and shipped
#: the KV pages to a decode replica (attrs: target, blocks) — terminal
#: HERE, the stream continues on the target.
SPAN_SHIPPED = "shipped"

TERMINAL_SPANS = (SPAN_FINISH, SPAN_CANCEL, SPAN_EXPIRE, SPAN_SHIPPED)


class RequestTracer:
    """Bounded ring buffer of (request_id, span, t, attrs) events.

    ``capacity`` bounds memory for a long-lived replica: old requests'
    events fall off the back as new ones append. ``enabled=False`` turns
    :meth:`event` into an immediate return (the bench's tracing-off
    mode); flipping it at runtime is safe.
    """

    def __init__(self, capacity: int = 8192, enabled: bool = True) -> None:
        self.capacity = int(capacity)
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=self.capacity)
        #: Total events evicted by ring wrap over this tracer's lifetime.
        self.dropped = 0
        # Request ids that lost at least one event to ring wrap. Pruned
        # against the live ring once per `capacity` evictions, so a rid
        # only stays here while it still has events in the ring — i.e.
        # while its retained trace is genuinely partial.
        self._evicted: set = set()
        #: Wall-clock minus monotonic at construction. Events record on
        #: the cheap monotonic clock; cross-process merges add this
        #: offset so rings recorded in different processes (each with its
        #: own monotonic base) align on one wall-clock timeline.
        self.wall_offset = time.time() - time.monotonic()

    # -- hot path ---------------------------------------------------------
    def event(
        self,
        request_id: str,
        span: str,
        t: Optional[float] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Append one event. ``t`` defaults to ``time.monotonic()`` now;
        ``attrs`` is stored by reference (callers must not mutate it)."""
        if not self.enabled:
            return
        if t is None:
            t = time.monotonic()
        with self._lock:
            if len(self._events) == self.capacity and self.capacity > 0:
                self._evicted.add(self._events[0][0])
                self.dropped += 1
                if self.dropped % self.capacity == 0:
                    live = {r for r, _, _, _ in self._events}
                    self._evicted &= live
            self._events.append((request_id, span, t, attrs))

    # -- read side --------------------------------------------------------
    def _scan(self) -> List[Tuple[str, str, float, Optional[Dict[str, Any]]]]:
        with self._lock:
            return list(self._events)

    def is_truncated(self, request_id: str) -> bool:
        """True when ring wrap evicted some of this request's events
        while others remain — the retained trace is partial and any
        duration derived from its first event under-counts."""
        with self._lock:
            return request_id in self._evicted

    def trace(self, request_id: str) -> List[Dict[str, Any]]:
        """All of one request's events, oldest first, as dicts. When the
        ring wrapped over part of this request's history, the first
        retained event carries ``truncated: True`` — consumers must not
        treat its timestamp as the request's start."""
        out = []
        for rid, span, t, attrs in self._scan():
            if rid != request_id:
                continue
            ev: Dict[str, Any] = {"span": span, "t": t}
            if attrs:
                ev.update(attrs)
            out.append(ev)
        if out and self.is_truncated(request_id):
            out[0] = dict(out[0], truncated=True)
        return out

    def recent_traces(self, n: int = 8) -> Dict[str, List[Dict[str, Any]]]:
        """The last ``n`` distinct request ids (by latest event) with
        their full event lists."""
        events = self._scan()
        order: List[str] = []
        for rid, _, _, _ in reversed(events):
            if rid not in order:
                order.append(rid)
            if len(order) >= n:
                break
        keep = set(order)
        traces: Dict[str, List[Dict[str, Any]]] = {rid: [] for rid in order}
        for rid, span, t, attrs in events:
            if rid in keep:
                ev: Dict[str, Any] = {"span": span, "t": t}
                if attrs:
                    ev.update(attrs)
                traces[rid].append(ev)
        return traces

    def request_ids(self) -> List[str]:
        seen: List[str] = []
        for rid, _, _, _ in self._scan():
            if rid not in seen:
                seen.append(rid)
        return seen

    def dump(self, n: int = 16) -> Dict[str, Any]:
        """The wire form of this process's ring for cross-process trace
        stitching: the ``n`` most recent traces plus the wall-clock
        offset :func:`merge_chrome_trace` needs to align them with rings
        from other processes. ``truncated`` lists the dumped request ids
        whose retained traces are partial (ring wrap ate early events) —
        the anatomy layer turns that into ``unaccounted`` provenance
        instead of mis-attributing the missing time. The key is omitted
        entirely when nothing was truncated, keeping the healthy-path
        wire form unchanged."""
        traces = self.recent_traces(n)
        with self._lock:
            truncated = sorted(r for r in traces if r in self._evicted)
        out: Dict[str, Any] = {
            "wall_offset": self.wall_offset,
            "traces": traces,
        }
        if truncated:
            out["truncated"] = truncated
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)


# -- Chrome trace-event export -----------------------------------------
_PHASES = (
    # (name, start marker(s), end marker(s))
    ("queued", (SPAN_SUBMIT, SPAN_QUEUED), (SPAN_ADMITTED,)),
    ("prefill", (SPAN_ADMITTED,), (SPAN_FIRST_TOKEN,) + TERMINAL_SPANS),
    ("decode", (SPAN_FIRST_TOKEN,), TERMINAL_SPANS),
)


def _first_t(evs: List[Dict[str, Any]], spans: Tuple[str, ...]) -> Optional[float]:
    for ev in evs:
        if ev["span"] in spans:
            return ev["t"]
    return None


def _emit_tracks(
    events: List[Dict[str, Any]],
    pid: int,
    traces: Dict[str, List[Dict[str, Any]]],
    us,
) -> None:
    """Append one process's request tracks (thread metadata, derived
    lifecycle phases, raw markers) onto ``events``. Shared by the
    single-process and merged exports so both render identically."""
    for tid, (rid, evs) in enumerate(sorted(traces.items()), start=1):
        events.append(
            {
                "ph": "M",
                "name": "thread_name",
                "pid": pid,
                "tid": tid,
                "args": {"name": f"request {rid}"},
            }
        )
        evs = sorted(evs, key=lambda e: e["t"])
        for phase, starts, ends in _PHASES:
            ts = _first_t(evs, starts)
            te = _first_t(evs, ends)
            if ts is None or te is None or te < ts:
                continue
            events.append(
                {
                    "ph": "X",
                    "name": phase,
                    "cat": "lifecycle",
                    "pid": pid,
                    "tid": tid,
                    "ts": us(ts),
                    "dur": max(round((te - ts) * 1e6, 1), 0.1),
                    "args": {"request_id": rid},
                }
            )
        for ev in evs:
            args = {k: v for k, v in ev.items() if k not in ("span", "t")}
            args["request_id"] = rid
            events.append(
                {
                    "ph": "i",
                    "name": ev["span"],
                    "cat": "marker",
                    "s": "t",
                    "pid": pid,
                    "tid": tid,
                    "ts": us(ev["t"]),
                    "args": args,
                }
            )


def to_chrome_trace(
    traces: Dict[str, List[Dict[str, Any]]],
    process_name: str = "rlt-serve",
    pid: int = 0,
) -> Dict[str, Any]:
    """Convert ``{request_id: [event, ...]}`` into Chrome trace-event
    JSON (dict form; ``json.dump`` it to get a file Perfetto opens).

    Each request gets its own thread track (tid). Derived lifecycle
    phases become complete ("X") events; every raw marker becomes an
    instant ("i") event carrying its attrs as args. Timestamps are
    microseconds relative to the earliest event in the export.
    """
    all_t = [ev["t"] for evs in traces.values() for ev in evs]
    t0 = min(all_t) if all_t else 0.0

    def us(t: float) -> float:
        return round((t - t0) * 1e6, 1)

    events: List[Dict[str, Any]] = [
        {
            "ph": "M",
            "name": "process_name",
            "pid": pid,
            "tid": 0,
            "args": {"name": process_name},
        }
    ]
    _emit_tracks(events, pid, traces, us)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def merge_chrome_trace(
    processes: List[Dict[str, Any]],
) -> Dict[str, Any]:
    """Stitch several processes' trace rings into ONE Chrome trace.

    ``processes`` is a list of ``{"name", "traces", "wall_offset"}``
    dicts — the :meth:`RequestTracer.dump` wire form plus a display
    name (``client`` / ``replica0`` / ``follower1`` ...). Each process
    becomes its own pid track (process_name metadata), each request its
    own thread track within it, and every event's monotonic timestamp
    is shifted by its process's ``wall_offset`` so spans recorded on
    different monotonic bases line up on one wall-clock timeline.

    Cross-process derivation: a request with a :data:`SPAN_CLIENT_SUBMIT`
    in one process and a :data:`SPAN_ADMITTED` (or first token) in
    another gets a ``client_wait`` complete span on the client's track —
    the client-observed queue time (RPC hop + scheduler queue) that no
    single process's ring can see.
    """
    norm: List[Tuple[int, str, Dict[str, List[Dict[str, Any]]]]] = []
    for pid, proc in enumerate(processes):
        off = float(proc.get("wall_offset") or 0.0)
        traces = {
            rid: [dict(ev, t=float(ev["t"]) + off) for ev in evs]
            for rid, evs in (proc.get("traces") or {}).items()
            if evs
        }
        norm.append((pid, str(proc.get("name") or f"process{pid}"), traces))

    all_t = [
        ev["t"] for _, _, traces in norm
        for evs in traces.values() for ev in evs
    ]
    t0 = min(all_t) if all_t else 0.0

    def us(t: float) -> float:
        return round((t - t0) * 1e6, 1)

    events: List[Dict[str, Any]] = []
    for pid, name, traces in norm:
        events.append(
            {
                "ph": "M",
                "name": "process_name",
                "pid": pid,
                "tid": 0,
                "args": {"name": name},
            }
        )
        _emit_tracks(events, pid, traces, us)

    # The cross-process span: client submit -> remote admission (falling
    # back to the first token for engines driven without a scheduler).
    landed: Dict[str, float] = {}
    for _, _, traces in norm:
        for rid, evs in traces.items():
            t_adm = _first_t(
                sorted(evs, key=lambda e: e["t"]),
                (SPAN_ADMITTED, SPAN_FIRST_TOKEN),
            )
            if t_adm is not None and (
                rid not in landed or t_adm < landed[rid]
            ):
                landed[rid] = t_adm
    for pid, _, traces in norm:
        for tid, (rid, evs) in enumerate(sorted(traces.items()), start=1):
            t_sub = _first_t(evs, (SPAN_CLIENT_SUBMIT,))
            t_adm = landed.get(rid)
            if t_sub is None or t_adm is None or t_adm < t_sub:
                continue
            events.append(
                {
                    "ph": "X",
                    "name": "client_wait",
                    "cat": "lifecycle",
                    "pid": pid,
                    "tid": tid,
                    "ts": us(t_sub),
                    "dur": max(round((t_adm - t_sub) * 1e6, 1), 0.1),
                    "args": {"request_id": rid},
                }
            )
    return {"traceEvents": events, "displayTimeUnit": "ms"}
