"""Worker actor class, result wire-format, and the driver result loop.

Parity targets from the reference:
- ``RayExecutor`` actor (launchers/utils.py:27-52): generic "run this
  closure" worker with env-var and node-introspection helpers.
- ``_RayOutput`` (launchers/utils.py:55-69): the record rank 0 returns.
- ``process_results`` / ``_handle_queue`` (util.py:49-70): the driver's
  wait-loop that polls training futures while draining the Tune callback
  queue.
"""
from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, List, NamedTuple, Optional

from ray_lightning_tpu import fabric


class TrainWorker:
    """Generic worker actor: env plumbing, node introspection, closure exec."""

    def set_env_var(self, key: str, value: str) -> None:
        os.environ[key] = str(value)

    def set_env_vars(self, keys: List[str], values: List[str]) -> None:
        for key, value in zip(keys, values):
            self.set_env_var(key, value)

    def get_node_ip(self) -> str:
        return os.environ.get("RLT_NODE_IP", "127.0.0.1")

    def get_node_id(self) -> str:
        return os.environ.get("RLT_NODE_ID", "node-0")

    def find_free_port(self) -> int:
        from ray_lightning_tpu.utils.ports import find_free_port

        return find_free_port()

    def get_local_device_count(self) -> int:
        import jax

        return len(jax.local_devices())

    def execute(self, fn: Callable, *args: Any, **kwargs: Any) -> Any:
        """Run an arbitrary closure — the actor's universal entrypoint."""
        return fn(*args, **kwargs)

    def profile(
        self, duration_s: float = 1.0, outdir: Optional[str] = None
    ) -> Dict[str, Any]:
        """On-demand jax.profiler capture of this worker's device work
        (obs.profiling); returns the artifact paths, never raises."""
        from ray_lightning_tpu.obs.profiling import capture_profile

        return capture_profile(duration_s, outdir)

    def debug_dump(
        self, reason: str = "rpc", pull: bool = False
    ) -> Dict[str, Any]:
        """Flight-recorder bundle of THIS worker process (obs.blackbox):
        registry, event-log tail, all-thread stacks — the forensic RPC
        for a training worker that looks stalled. ``pull`` inlines the
        bundle files so the driver needs no shared filesystem."""
        from ray_lightning_tpu.obs import blackbox

        manifest = blackbox.default_recorder().dump(reason=reason)
        if pull:
            manifest["files_content"] = blackbox.read_bundle(
                manifest["dir"]
            )
        return manifest


_train_worker_cls = TrainWorker


def get_executable_cls() -> type:
    """Test hook: the actor class the launcher spawns (reference
    launchers/utils.py:20-24 uses the same seam for mock actors)."""
    return _train_worker_cls


def set_executable_cls(cls: Optional[type]) -> None:
    global _train_worker_cls
    _train_worker_cls = cls or TrainWorker


class WorkerOutput(NamedTuple):
    """What worker rank 0 ships back to the driver (the ``_RayOutput``
    analog). Weights travel as a state stream — bytes, not file paths — so
    recovery works across nodes without a shared filesystem
    (ray_launcher.py:332-336 rationale)."""

    best_model_path: Optional[str]
    state_stream: Optional[bytes]
    trainer_state: Dict[str, Any]
    results: Any
    callback_metrics: Dict[str, Any]
    logged_metrics: Dict[str, Any]
    callback_states: Dict[str, Any]


def _handle_queue(queue: Any) -> None:
    """Execute all pending (rank, closure) items from the worker queue."""
    if queue is None:
        return
    while not queue.empty():
        try:
            (_actor_rank, item) = queue.get_nowait()
        except Exception:  # noqa: BLE001 - drained concurrently
            return
        if isinstance(item, Callable):
            item()


def process_results(training_result_futures: List[Any], queue: Any = None) -> List[Any]:
    """Wait for all workers while servicing the worker->driver queue.

    This is the driver's main loop during a fit: poll the futures with a
    zero-timeout wait and run queued closures (e.g. ``tune.report``) between
    polls, exactly the reference's event loop shape (util.py:57-70).
    """
    not_ready = list(training_result_futures)
    while not_ready:
        if queue is not None:
            _handle_queue(queue)
        _ready, not_ready = fabric.wait(not_ready, num_returns=len(not_ready), timeout=0)
        time.sleep(0.02)
    if queue is not None:
        _handle_queue(queue)
    return fabric.get(training_result_futures)
