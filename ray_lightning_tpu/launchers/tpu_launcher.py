"""TPULauncher: create worker actors, rendezvous, run, recover results.

The heart of the system — the parity target is ``RayLauncher``
(/root/reference/ray_lightning/launchers/ray_launcher.py:27-379), re-shaped
for TPU: per-*host* actors instead of per-GPU processes, a JAX coordination
service address instead of MASTER_ADDR/PORT env rendezvous, and no
CUDA_VISIBLE_DEVICES pooling (PJRT owns each host's chips; SURVEY.md §7
mapping table).

Launch sequence (cf. SURVEY.md §3.1):
  1. setup_workers: spawn actors with per-worker resources + env, run
     init_hook on each (ray_launcher.py:79-83 analog).
  2. coordinator = worker-0 node IP + a free port on that node
     (ray_launcher.py:85-87 analog) — process 0 hosts the JAX coordination
     service.
  3. env broadcast (seed, coordinator) to all actors (:159-175 analog).
  4. global->(local, node) rank map from actor node IPs (:130-157 analog).
  5. ship (module, spec, strategy) once via the object store, run the loop
     entry in every actor, drive process_results.
  6. collect rank-0 WorkerOutput, restore into the driver's trainer
     (:312-379 analog), teardown actors.
"""
from __future__ import annotations

from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_lightning_tpu import fabric
from ray_lightning_tpu.launchers.utils import (
    WorkerOutput,
    get_executable_cls,
    process_results,
)
from ray_lightning_tpu.parallel.env import DistEnv
from ray_lightning_tpu.utils.seed import GLOBAL_SEED_ENV
import os


def _worker_entry(
    spec_ref: Any,
    host_rank: int,
    dist_env: DistEnv,
    stage: str,
    ckpt_stream: Optional[bytes],
    queue: Any,
) -> Optional[WorkerOutput]:
    """Runs inside each actor: rebuild the loop and execute the stage.

    The analog of ``_wrapping_function`` (ray_launcher.py:252-310), minus the
    pickled-live-trainer tricks: everything arrives via one object-store ref.
    """
    from ray_lightning_tpu.trainer.loop import TrainingLoop

    module, spec, strategy, datamodule = fabric.get(spec_ref)
    strategy.set_remote(True)
    strategy.setup_worker(dist_env)

    tune_session = None
    if queue is not None:
        from ray_lightning_tpu.tune import session as tune_session_mod

        tune_session_mod.init_session(rank=host_rank, queue=queue)
        tune_session = tune_session_mod.get_session()

    loop = TrainingLoop(
        spec, module, strategy, dist_env, tune_session=tune_session, datamodule=datamodule
    )
    if stage == "fit":
        return loop.run_fit(ckpt_stream)
    if stage in ("validate", "test"):
        return loop.run_evaluate(stage, ckpt_stream)
    if stage == "predict":
        return loop.run_predict(ckpt_stream)
    raise ValueError(f"unknown stage {stage}")


class TPULauncher:
    def __init__(self, strategy: Any, trainer: Any) -> None:
        self._strategy = strategy
        self._trainer = trainer
        self._workers: List[Any] = []
        self.tune_queue: Any = None

    # ------------------------------------------------------------------
    def launch(
        self,
        stage: str,
        module: Any,
        datamodule: Any = None,
        ckpt_stream: Optional[bytes] = None,
    ) -> Optional[WorkerOutput]:
        if not fabric.is_initialized():
            fabric.init()
        plans, use_tpu = self._strategy.plan_workers()
        try:
            self.setup_workers(plans)
            dist_envs = self._build_dist_envs(plans, use_tpu)
            output = self.run_function_on_workers(
                stage, module, datamodule, ckpt_stream, dist_envs
            )
        finally:
            self.teardown_workers()
        return output

    # ------------------------------------------------------------------
    def setup_workers(self, plans: List[Any]) -> None:
        from ray_lightning_tpu.tune.session import is_tune_session

        worker_cls = get_executable_cls()
        for plan in plans:
            actor = (
                fabric.remote(worker_cls)
                .options(
                    num_cpus=plan.num_cpus,
                    resources=plan.resources,
                    env=plan.env,
                )
                .remote()
            )
            self._workers.append(actor)
        if self._strategy.init_hook:
            fabric.get(
                [w.execute.remote(self._strategy.init_hook) for w in self._workers]
            )
        # Seed broadcast (PL_GLOBAL_SEED analog, ray_launcher.py:169-172).
        seed = os.environ.get(GLOBAL_SEED_ENV)
        if seed is not None:
            fabric.get(
                [
                    w.set_env_var.remote(GLOBAL_SEED_ENV, seed)
                    for w in self._workers
                ]
            )
        if is_tune_session():
            self.tune_queue = fabric.Queue()

    def _build_dist_envs(self, plans: List[Any], use_tpu: bool) -> List[DistEnv]:
        num_hosts = len(plans)
        chips_per_host = self._strategy.num_workers // num_hosts
        coordinator = None
        if num_hosts > 1:
            # Coordination service runs inside host_rank 0; its address must
            # be that actor's node, not the driver (multi-node correctness).
            ip = fabric.get(self._workers[0].get_node_ip.remote())
            port = fabric.get(self._workers[0].find_free_port.remote())
            coordinator = f"{ip}:{port}"
        global_to_local = self.get_local_ranks()
        envs = []
        for rank, plan in enumerate(plans):
            envs.append(
                DistEnv(
                    world_size=self._strategy.num_workers,
                    num_hosts=num_hosts,
                    host_rank=rank,
                    node_rank=global_to_local[rank][1],
                    local_chips=chips_per_host,
                    coordinator_address=coordinator,
                    first_chip_rank=rank * chips_per_host,
                    global_to_local=global_to_local,
                )
            )
        return envs

    def get_local_ranks(self) -> Dict[int, Tuple[int, int]]:
        """host_rank -> (local_rank, node_rank) from actor node IPs — same
        algorithm as the reference (ray_launcher.py:130-157)."""
        node_ips = fabric.get([w.get_node_ip.remote() for w in self._workers])
        rank_map: Dict[int, Tuple[int, int]] = {}
        node_order: List[str] = []
        per_node_counter: Dict[str, int] = defaultdict(int)
        for global_rank, ip in enumerate(node_ips):
            if ip not in node_order:
                node_order.append(ip)
            node_rank = node_order.index(ip)
            rank_map[global_rank] = (per_node_counter[ip], node_rank)
            per_node_counter[ip] += 1
        return rank_map

    # ------------------------------------------------------------------
    def run_function_on_workers(
        self,
        stage: str,
        module: Any,
        datamodule: Any,
        ckpt_stream: Optional[bytes],
        dist_envs: List[DistEnv],
    ) -> Optional[WorkerOutput]:
        # Single object-store upload shared by all workers (the reference's
        # ray.put(model) + trainer.model=None double-pickle avoidance,
        # ray_launcher.py:232-247, falls out of the explicit-spec design).
        spec = self._trainer._make_spec()
        # Strip the driver-trainer backref so the object-store payload holds
        # only the module (the reference nulls trainer.model for the same
        # double-pickle reason, ray_launcher.py:232-247).
        module.trainer = None
        spec_ref = fabric.put((module, spec, self._strategy, datamodule))
        try:
            futures = [
                w.execute.remote(
                    _worker_entry,
                    spec_ref,
                    rank,
                    dist_envs[rank],
                    stage,
                    ckpt_stream,
                    self.tune_queue,
                )
                for rank, w in enumerate(self._workers)
            ]
            results = process_results(futures, self.tune_queue)
        finally:
            module.trainer = self._trainer
            from ray_lightning_tpu.fabric.core import free

            free([spec_ref])
        return results[0]

    # ------------------------------------------------------------------
    def teardown_workers(self) -> None:
        if self.tune_queue is not None:
            try:
                self.tune_queue.shutdown()
            except Exception:  # noqa: BLE001
                pass
            self.tune_queue = None
        for worker in self._workers:
            try:
                fabric.kill(worker)
            except Exception:  # noqa: BLE001
                pass
        self._workers = []
