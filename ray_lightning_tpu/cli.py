"""Command-line interface: build Trainer + strategy + model from flags/YAML.

Parity target: the reference keeps its strategies LightningCLI/jsonargparse-
constructible — plain typed ctor kwargs instantiated from CLI flags
(/root/reference/ray_lightning/tests/test_lightning_cli.py:11-27,
SURVEY.md §5 config/flag system). jsonargparse is not in this environment,
so the CLI is self-contained: argparse + constructor introspection, with
Lightning's ``{class_path, init_args}`` YAML convention and dotted CLI
overrides.

Usage:
    python -m ray_lightning_tpu.cli fit \
        --model ray_lightning_tpu.models.MNISTClassifier --model.lr 3e-4 \
        --strategy RayTPUStrategy --strategy.num_workers 4 \
        --trainer.max_epochs 2 [--config run.yaml]

YAML config (merged under CLI overrides):
    model:
      class_path: ray_lightning_tpu.models.GPTLM
      init_args: {batch_size: 8}
    strategy:
      class_path: ray_lightning_tpu.strategies.GSPMDStrategy
      init_args: {num_workers: 8, mesh_shape: {data: 4, model: 2}}
    trainer: {max_epochs: 3}
"""
from __future__ import annotations

import argparse
import importlib
import inspect
import sys
from typing import Any, Dict, List, Optional, Tuple

import yaml

_SUBCOMMANDS = (
    "fit", "validate", "test", "predict", "generate", "convert-hf",
    "tokenize", "serve", "doctor", "top", "replay", "why", "plot",
    "alerts",
)


def import_class(path: str) -> type:
    """Resolve ``pkg.mod.Class`` (or a bare name from the strategies /
    models namespaces) to a class object."""
    if "." in path:
        module_name, _, cls_name = path.rpartition(".")
        return getattr(importlib.import_module(module_name), cls_name)
    for ns in ("ray_lightning_tpu.strategies", "ray_lightning_tpu.models"):
        mod = importlib.import_module(ns)
        if hasattr(mod, path):
            return getattr(mod, path)
    raise ValueError(f"cannot resolve class {path!r}")


def _target_type(annotation: Any, default: Any) -> Optional[type]:
    """Best-effort scalar type from a ctor annotation (which is usually a
    *string* — the package uses ``from __future__ import annotations``) or
    the default value."""
    if isinstance(annotation, type):
        return annotation
    if isinstance(annotation, str):
        for name, typ in (("bool", bool), ("int", int), ("float", float),
                          ("str", str)):
            if name in annotation:
                return typ
    if annotation is inspect.Parameter.empty and default is not None:
        if isinstance(default, (bool, int, float, str)):
            return type(default)
    return None


def _coerce(value: str, annotation: Any, default: Any) -> Any:
    """Parse a CLI string with YAML, then bend it toward the ctor's type
    (YAML alone keeps e.g. '3e-4' a string — its float resolver wants a
    dot)."""
    parsed = yaml.safe_load(value)
    target = _target_type(annotation, default)
    if target is bool:
        return parsed if isinstance(parsed, bool) else str(parsed).lower() in (
            "1", "true", "yes",
        )
    if target in (int, float) and isinstance(parsed, (int, float, str)):
        try:
            return target(parsed)
        except (TypeError, ValueError):
            return parsed
    return parsed


def instantiate_class(spec: Any, default_class: Optional[str] = None) -> Any:
    """Instantiate Lightning-style ``{class_path, init_args}`` (or a bare
    class-path string)."""
    if isinstance(spec, str):
        spec = {"class_path": spec, "init_args": {}}
    class_path = spec.get("class_path") or default_class
    if class_path is None:
        raise ValueError(f"missing class_path in {spec!r}")
    cls = import_class(class_path)
    kwargs = dict(spec.get("init_args") or {})
    _validate_ctor_kwargs(cls, kwargs)
    return cls(**kwargs)


def _validate_ctor_kwargs(cls: type, kwargs: Dict[str, Any]) -> None:
    sig = inspect.signature(cls.__init__)
    accepts_var_kw = any(
        p.kind is inspect.Parameter.VAR_KEYWORD for p in sig.parameters.values()
    )
    if accepts_var_kw:
        return
    valid = set(sig.parameters) - {"self"}
    unknown = set(kwargs) - valid
    if unknown:
        raise ValueError(
            f"{cls.__name__} does not accept {sorted(unknown)}; "
            f"valid args: {sorted(valid)}"
        )


def _apply_dotted(
    config: Dict[str, Any], dotted: List[Tuple[str, str]]
) -> Dict[str, Any]:
    """Merge ``--section.key value`` overrides into the config tree, coercing
    through the target constructor's signature where known.

    Two passes so coercion is order-independent: class paths (from YAML or
    any ``--model X`` flag, in either position) are all known before any
    field value is typed.
    """
    # Pass 1: class paths + normalize bare-string YAML nodes to dict form.
    field_overrides: List[Tuple[str, str, str]] = []
    for key, raw in dotted:
        section, _, field = key.partition(".")
        if section in ("src", "out", "family"):  # convert-hf scalar options
            config[section] = raw
            continue
        if section == "overrides":  # convert-hf GPTConfig overrides
            config.setdefault("overrides", {})[field] = yaml.safe_load(raw)
            continue
        if section not in (
            "model", "strategy", "trainer", "data", "generate", "tokenize",
            "serve", "doctor", "top", "replay", "why", "plot", "alerts",
        ):
            raise ValueError(f"unknown config section {section!r} in --{key}")
        node = config.get(section)
        if isinstance(node, str):  # YAML bare class-path form
            config[section] = {"class_path": node, "init_args": {}}
        elif node is None:
            config[section] = {}
        if not field:  # bare --model X == class path
            config[section]["class_path"] = raw
        else:
            field_overrides.append((section, field, raw))
    # Pass 2: typed field values.
    for section, field, raw in field_overrides:
        node = config[section]
        if section in (
            "trainer", "generate", "tokenize", "serve", "doctor", "top",
            "replay", "why", "plot", "alerts",
        ):  # plain dicts
            node[field] = yaml.safe_load(raw)
            continue
        init_args = node.setdefault("init_args", {})
        cls_path = node.get("class_path")
        annotation: Any = inspect.Parameter.empty
        default: Any = None
        if cls_path:
            try:
                sig = inspect.signature(import_class(cls_path).__init__)
                if field in sig.parameters:
                    annotation = sig.parameters[field].annotation
                    default = sig.parameters[field].default
            except Exception:  # noqa: BLE001 - fall back to yaml typing
                pass
        init_args[field] = _coerce(raw, annotation, default)
    return config


def parse_args(argv: Optional[List[str]] = None) -> Tuple[str, Dict[str, Any]]:
    argv = list(sys.argv[1:] if argv is None else argv)
    parser = argparse.ArgumentParser(
        prog="ray_lightning_tpu", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("subcommand", choices=_SUBCOMMANDS)
    parser.add_argument("--config", action="append", default=[])
    parser.add_argument(
        "--address",
        default=None,
        help="fabric head address (host:port) for client mode — start one "
        "with `python -m ray_lightning_tpu.fabric.server`",
    )
    known, rest = parser.parse_known_args(argv)

    config: Dict[str, Any] = {}
    for path in known.config:
        with open(path) as f:
            loaded = yaml.safe_load(f) or {}
        for section, value in loaded.items():
            if isinstance(value, dict) and isinstance(config.get(section), dict):
                merged = dict(config[section])
                merged.update(value)
                config[section] = merged
            else:
                config[section] = value

    # CLI flag wins over any fabric: section from YAML (same precedence as
    # the dotted overrides, which also apply after the YAML merge).
    if known.address:
        fabric_cfg = dict(config.get("fabric") or {})
        fabric_cfg["address"] = known.address
        config["fabric"] = fabric_cfg

    dotted: List[Tuple[str, str]] = []
    i = 0
    while i < len(rest):
        arg = rest[i]
        if not arg.startswith("--"):
            # ``rlt doctor <addr>`` / ``rlt top <addr>`` /
            # ``rlt replay <journal>`` / ``rlt why <addr|journal> <id>``:
            # bare positionals fill the subcommand's keys in order (the
            # explicit dotted flag always wins over a positional).
            pos_keys = {
                "doctor": ("addr",), "top": ("addr",),
                "replay": ("journal",), "why": ("target", "id"),
                "plot": ("addr", "series"), "alerts": ("addr",),
            }.get(known.subcommand) or ()
            taken = config.get(known.subcommand) or {}
            pos_key = next((k for k in pos_keys if k not in taken), None)
            if pos_key is not None:
                config.setdefault(known.subcommand, {})[pos_key] = arg
                i += 1
                continue
            raise ValueError(f"unexpected argument {arg!r}")
        if arg == "--follow" and known.subcommand == "alerts":
            # Ergonomic alias: `rlt alerts <addr> --follow` ==
            # `--alerts.follow true` (the only bare flag the dotted
            # grammar admits — it takes no value).
            dotted.append(("alerts.follow", "true"))
            i += 1
            continue
        key = arg[2:]
        if "=" in key:
            key, _, value = key.partition("=")
        else:
            i += 1
            if i >= len(rest):
                raise ValueError(f"missing value for --{key}")
            value = rest[i]
        dotted.append((key, value))
        i += 1
    return known.subcommand, _apply_dotted(config, dotted)


def build(config: Dict[str, Any]) -> Tuple[Any, Any, Optional[Any]]:
    """(trainer, model, datamodule) from a parsed config tree."""
    from ray_lightning_tpu.trainer import Trainer

    if "model" not in config:
        raise ValueError("a --model (or model: section) is required")
    model = instantiate_class(config["model"])
    datamodule = (
        instantiate_class(config["data"]) if config.get("data") else None
    )
    strategy = None
    if config.get("strategy"):
        strategy = instantiate_class(config["strategy"])
    trainer_kwargs = dict(config.get("trainer") or {})
    _validate_ctor_kwargs(Trainer, trainer_kwargs)
    trainer = Trainer(strategy=strategy, **trainer_kwargs)
    return trainer, model, datamodule


def run_generate(config: Dict[str, Any]) -> Any:
    """``generate``: restore params from a checkpoint and decode.

    Config section (``--generate.<key>`` or ``generate:`` in YAML):
      ckpt_path (required, state-stream checkpoint), prompt (token ids —
      "1,2,3" or a YAML list), max_new_tokens, temperature, top_k, top_p,
      seed. Prints one comma-separated id line per sequence and returns
      the (B, P+N) array. Sharded checkpoint dirs need a live mesh — use
      ``validate``/``test`` for those; generation is a single-program path.
    """
    import numpy as np

    gen = dict(config.pop("generate", None) or {})
    model = instantiate_class(config["model"])
    if not hasattr(model, "generate"):
        raise ValueError(
            f"{type(model).__name__} has no generate(); the generate "
            "subcommand needs an autoregressive model (e.g. GPTLM)"
        )
    ckpt_path = gen.pop("ckpt_path", None)
    if ckpt_path is None:
        raise ValueError("generate requires --generate.ckpt_path")
    from ray_lightning_tpu.trainer.checkpoint_io import is_sharded_checkpoint
    from ray_lightning_tpu.utils.state_stream import load_state_stream

    if is_sharded_checkpoint(ckpt_path):
        raise ValueError(
            "generate restores state-stream checkpoints only; restore "
            "sharded dirs through validate/test first"
        )
    from ray_lightning_tpu.trainer.trainer import Trainer

    model.load_state_dict(load_state_stream(Trainer._read_ckpt(ckpt_path)))
    prompt = gen.pop("prompt", None)
    if prompt is None:
        raise ValueError("generate requires --generate.prompt (token ids)")
    if isinstance(prompt, str):
        prompt = [int(t) for t in prompt.replace(",", " ").split()]
    arr = np.atleast_2d(np.asarray(prompt, np.int32))
    # Pop every known option BEFORE decoding so a typo'd flag fails
    # instantly instead of after a long decode.
    seed = int(gen.pop("seed", 0))
    max_new_tokens = int(gen.pop("max_new_tokens", 32))
    temperature = float(gen.pop("temperature", 0.0))
    top_k = gen.pop("top_k", None)
    top_p = gen.pop("top_p", None)
    if gen:
        raise ValueError(f"unknown generate options: {sorted(gen)}")
    import jax

    out = model.generate(
        arr,
        max_new_tokens=max_new_tokens,
        temperature=temperature,
        rng=jax.random.PRNGKey(seed),
        top_k=top_k,
        top_p=top_p,
    )
    out = np.asarray(out)
    for row in out:
        print(",".join(str(int(t)) for t in row))
    return out


def run_convert_hf(config: Dict[str, Any]) -> str:
    """``convert-hf``: local Hugging Face GPT-2/Llama checkpoint -> a native
    params checkpoint usable as ``fit/validate/generate`` ckpt_path.

    Options (``--src``/``--out`` or a ``convert_hf:`` YAML section):
      src (required, HF checkpoint directory), out (required, .ckpt file),
      plus GPTConfig overrides under ``overrides:`` (e.g.
      ``--overrides.attn_impl reference``).
    """
    section = dict(config.pop("convert_hf", None) or {})
    src = config.pop("src", None) or section.pop("src", None)
    out = config.pop("out", None) or section.pop("out", None)
    family = (
        config.pop("family", None) or section.pop("family", None) or "gpt2"
    )
    overrides = dict(
        (config.pop("overrides", None) or section.pop("overrides", None) or {})
    )
    leftovers = {k: v for k, v in {**config, **section}.items()}
    if leftovers:
        raise ValueError(f"unknown convert-hf options: {sorted(leftovers)}")
    if not src or not out:
        raise ValueError("convert-hf requires --src <hf_dir> and --out <file.ckpt>")
    import dataclasses

    import jax
    import numpy as np

    from ray_lightning_tpu.models import load_hf_gpt2, load_hf_llama
    from ray_lightning_tpu.utils import to_state_stream
    from ray_lightning_tpu.utils.state_stream import state_stream_to_file

    if family not in ("gpt2", "llama"):
        raise ValueError(
            f"unknown convert-hf family {family!r}; use 'gpt2' or 'llama'"
        )
    loader = load_hf_llama if family == "llama" else load_hf_gpt2
    params, cfg = loader(src, **overrides)
    state_stream_to_file(
        to_state_stream(
            {"params": params, "gpt_config": dataclasses.asdict(cfg)}
        ),
        out,
    )
    n_params = sum(
        int(np.prod(np.shape(x)))
        for x in jax.tree_util.tree_leaves(params)
    )
    print(
        f"wrote {out}: {n_params:,} params, "
        f"n_layer={cfg.n_layer} d_model={cfg.d_model} vocab={cfg.vocab_size}"
    )
    return out


#: Every option ``rlt serve`` accepts (``--serve.<key>`` / YAML
#: ``serve:``). Validated UP FRONT so a typo'd flag fails instantly with
#: the valid vocabulary, instead of being silently swallowed or erroring
#: after replicas spawned. ``slo.<metric>`` rules are open-ended.
_SERVE_KEYS = frozenset((
    "ckpt_path", "config", "int8", "prompts",
    "max_new_tokens", "temperature", "top_k", "top_p", "seed",
    "eos_token", "replicas", "num_slots", "max_seq", "mesh",
    "hosts_per_replica",
    "prefill_buckets", "max_prefills_per_step", "decode_fold",
    "fold_ladder", "piggyback_chunks",
    "pipeline", "prefill_chunk", "prefix_cache", "prefix_block",
    "prefix_host_mb", "prefix_disk_dir", "prefix_disk_mb",
    "kv_page", "kv_pages",
    "max_prefill_chunks_per_step", "priority_age_s",
    "spec", "spec_depth", "spec_draft_ckpt", "spec_draft_config",
    "spec_draft_int8", "spec_window",
    "metrics_port", "tracing", "trace_out", "profile_s",
    "watchdog", "watchdog_interval_s", "stall_s", "slo",
    "blackbox_dir", "blackbox_keep",
    "fleet", "fleet_interval_s", "fleet_history",
    "journal", "journal_capacity",
    "supervisor", "restart_limit", "restart_backoff_s", "rpc_timeout_s",
    "preempt_grace_s", "preempt_sigterm", "preempt_metadata",
    "router", "router_refresh_s", "router_affinity", "router_shed",
    "shed_queue_factor", "retry_budget", "hedge_after_s",
    "submit_batch_ms", "directory_shards",
    "autoscale_min", "autoscale_max", "autoscale_interval_s",
    "prefill_replicas", "kvfleet", "kvfleet_timeout_s",
    "kvfleet_inflight_mb", "kvfleet_bandwidth_mbps",
    "kvfleet_layerwise",
    "kvstore_dir", "kvstore_mb", "kvstore_writethrough",
    "alerts", "alerts_interval_s", "alerts_rules", "alerts_webhook",
    "canary", "canary_interval_s", "canary_baseline",
))


def _serve_obs_server(
    client: Any,
    metrics_port: int,
    fleet: bool = True,
    fleet_interval_s: float = 2.0,
    fleet_history: int = 128,
    supervisor: Any = None,
    router: Any = None,
    alerts: bool = True,
    alerts_interval_s: Optional[float] = None,
    alerts_rules: Any = None,
    alerts_webhook: Optional[str] = None,
    canary: bool = False,
    canary_interval_s: float = 10.0,
    canary_baseline: Optional[str] = None,
) -> Tuple[Any, Optional[Any], Optional[Any]]:
    """Build (started) the driver-side obs HTTP server ``rlt serve``
    runs next to a replica gang, plus its FleetPoller (None when
    ``fleet`` is off). Routes:

    - ``/metrics``: every replica's registry (replica-labelled) + the
      driver's own (fabric heartbeat gauges, ``rlt_fleet_*``);
    - ``/stats``: per-replica stats snapshots;
    - ``/healthz``: the FLEET readiness probe an external load balancer
      points at: 503 only when NO replica can serve (every replica
      unhealthy/unreachable — a single sick replica is the
      supervisor's problem, not the LB's); the JSON body lists every
      replica's verdict plus the driver's own (fabric heartbeat)
      report, and the top-level verdict degrades while any replica is
      out;
    - ``/fleet``: the latest FleetSnapshot + history ring (``rlt top``'s
      feed), plus the supervisor's per-replica state table when a
      :class:`serve.supervisor.FleetSupervisor` is wired;
    - ``/events``: the merged structured event rings as JSONL
      (``?level=``/``?subsystem=``/``?n=`` filter server-side);
    - ``/traces``: the stitched cross-process Chrome trace;
    - ``/journal``: the workload journal(s) as JSONL — save it and
      ``rlt replay`` it (multi-replica output is replica-tagged);
    - ``/why?id=<request_id>``: one request's cross-process anatomy
      phase ledger (``rlt why``'s feed) — every tracer ring + the
      driver journal + the event rings stitched under one id;
    - ``/debug/bundle``: a replica flight-recorder bundle augmented
      driver-side with ``fleet.json`` + ``trace_stitched.json`` so a
      pulled post-mortem shows the whole fleet, not one process;
    - ``/query?series=&since=&step=``: one retained watchtower TSDB
      series (``rlt plot``'s feed);
    - ``/alerts``: the alert engine's rules/states/firing payload plus
      the canary summary (``rlt alerts``'s feed).

    The watchtower (PR 20) rides the fleet plane: when ``fleet`` and
    ``alerts`` are both on, a :class:`obs.watchtower.Watchtower`
    samples every FleetPoller snapshot into the ring TSDB, evaluates
    the alert rules on its own cadence, and (with ``canary``) runs the
    fixed-seed probe lane. Returns ``(server, fleet_poller,
    watchtower)`` — each None when its plane is off.

    Factored out of run_serve so the wire path is testable against any
    client-shaped object without spawning the CLI.
    """
    import json as _json

    from ray_lightning_tpu import obs
    from ray_lightning_tpu.fabric import core as fabric_core
    from ray_lightning_tpu.obs import health as obs_health
    from ray_lightning_tpu.obs import watchtower as obs_wt
    from ray_lightning_tpu.obs.fleet import FleetPoller
    from ray_lightning_tpu.obs.tsdb import RingTSDB

    driver_reg = obs.get_registry()
    driver_wd = obs_health.Watchdog(registry=driver_reg)
    driver_wd.add_check(obs_health.heartbeat_check(fabric_core.heartbeats))

    fleet_poller = None
    if fleet:
        fleet_poller = FleetPoller(
            pull_fn=lambda: (
                client.stats(), client.health(), fabric_core.heartbeats()
            ),
            interval_s=float(fleet_interval_s),
            history=int(fleet_history),
            registry=driver_reg,
            events=obs.get_event_log(),
            supervisor_fn=(
                supervisor.rows if supervisor is not None else None
            ),
            router_fn=(router.rows if router is not None else None),
        ).start()

    watchtower = None
    if fleet_poller is not None and (alerts or canary):
        if isinstance(alerts_rules, str):
            with open(alerts_rules) as f:
                alerts_rules = yaml.safe_load(f)
        rules = (
            obs_wt.parse_alert_rules(alerts_rules)
            if alerts_rules is not None else obs_wt.default_rules()
        )
        if not alerts:
            rules = []  # canary-only: just the lane's own rules
        sinks: List[Any] = [obs_wt.LogSink()]
        if alerts_webhook:
            sinks.append(obs_wt.WebhookSink(alerts_webhook))
        tsdb = RingTSDB(registry=driver_reg)
        lane = None
        if canary:
            baseline = canary_baseline
            if isinstance(baseline, str):
                with open(baseline) as f:
                    baseline = yaml.safe_load(f)
            lane = obs_wt.CanaryLane(
                client, tsdb,
                interval_s=float(canary_interval_s),
                baseline=baseline,
                events=obs.get_event_log(),
                registry=driver_reg,
            )
        watchtower = obs_wt.Watchtower(
            tsdb=tsdb,
            rules=rules,
            fleet_latest_fn=fleet_poller.latest,
            metrics_text_fn=client.metrics_text,
            canary=lane,
            sinks=sinks,
            events=obs.get_event_log(),
            registry=driver_reg,
            interval_s=float(
                alerts_interval_s if alerts_interval_s is not None
                else fleet_interval_s
            ),
        ).start()
        # Late-bound: the poller was built before the watchtower (its
        # snapshots are the watchtower's feed), so the /fleet payload's
        # alerts block is wired after the fact.
        fleet_poller._alerts_fn = watchtower.fleet_block

    def _collect() -> str:
        obs.heartbeats_to_registry(fabric_core.heartbeats(), driver_reg)
        return client.metrics_text() + driver_reg.render()

    def _collect_health():
        # FLEET readiness, not per-process health: an external LB gets
        # ONE probe endpoint and should keep routing while ANY replica
        # can serve — a single dead/unhealthy replica is the
        # supervisor's job (drain, restart, fail over), and pulling the
        # whole fleet for it would turn one replica crash into an
        # outage. 503 only when every replica is out; the body always
        # lists per-replica verdicts so operators see exactly who is
        # sick, plus the driver's own (fabric heartbeat) report.
        report = driver_wd.evaluate()
        payload = report.to_dict()
        replicas = client.health()
        payload["replicas"] = replicas
        # Retired replicas are deliberate scale-downs, not failures:
        # they stay visible in the body but never count against the
        # fleet's readiness.
        live = [r for r in replicas if not r.get("retired")]
        up = sum(1 for r in live if r.get("healthy", True))
        payload["replicas_total"] = len(live)
        payload["replicas_healthy"] = up
        if supervisor is not None:
            payload["supervisor"] = supervisor.rows()
        if router is not None:
            payload["router"] = router.rows()
        healthy = up > 0 if live else report.healthy
        payload["healthy"] = healthy
        if not healthy:
            payload["verdict"] = "unhealthy"
        elif (live and up < len(live)) or not report.healthy:
            payload["verdict"] = "degraded"
        return healthy, payload

    def _collect_events() -> str:
        rows = client.recent_events(512)
        rows += [
            dict(ev, replica="driver")
            for ev in obs.get_event_log().tail(128)
        ]
        rows.sort(key=lambda e: e.get("ts", 0))
        return "\n".join(
            _json.dumps(r, default=str) for r in rows
        ) + ("\n" if rows else "")

    def _collect_bundle() -> Dict[str, Any]:
        manifest = client.debug_dump(reason="http", pull=True)
        files = manifest.setdefault("files_content", {})
        extra = []
        # Fleet context rides INTO the bundle driver-side: the replica
        # wrote its own process's forensics; the driver is the only one
        # holding the fleet snapshot and the cross-process trace.
        if fleet_poller is not None:
            try:
                files["fleet.json"] = _json.dumps(
                    fleet_poller.to_dict(), default=str
                )
                extra.append("fleet.json")
            except Exception as exc:  # noqa: BLE001 - record, keep bundle
                manifest.setdefault("errors", {})["fleet.json"] = repr(exc)
        try:
            files["trace_stitched.json"] = _json.dumps(
                client.export_stitched_trace(n=16)
            )
            extra.append("trace_stitched.json")
        except Exception as exc:  # noqa: BLE001
            manifest.setdefault("errors", {})[
                "trace_stitched.json"
            ] = repr(exc)
        if extra:
            manifest["files"] = sorted(
                set(manifest.get("files", [])) | set(extra)
            )
        return manifest

    server = obs.MetricsHTTPServer(
        collect_text=_collect,
        collect_json=lambda: {"serve_stats": client.stats()},
        collect_health=_collect_health,
        collect_bundle=_collect_bundle,
        collect_fleet=(
            fleet_poller.to_dict if fleet_poller is not None else None
        ),
        collect_events=_collect_events,
        collect_traces=lambda: client.export_stitched_trace(n=16),
        collect_journal=client.journal_jsonl,
        collect_why=lambda rid: obs.anatomy_from_client(client, rid),
        collect_query=(
            watchtower.query if watchtower is not None else None
        ),
        collect_alerts=(
            watchtower.alerts_payload if watchtower is not None else None
        ),
        port=int(metrics_port),
    ).start()
    return server, fleet_poller, watchtower


def run_serve(config: Dict[str, Any]) -> Dict[str, Any]:
    """``serve``: spawn replica actors on the fabric and serve prompts.

    Config section (``--serve.<key>`` or ``serve:`` in YAML):
      ckpt_path (required): state-stream checkpoint (convert-hf native
        form with an embedded gpt_config, or a trainer checkpoint) or a
        sharded orbax dir (then ``config`` is required).
      config: GPTConfig field dict (overrides/completes the stored one).
      int8: quantize weights at load (weight-only int8 decode).
      replicas, num_slots, max_seq, max_prefills_per_step: topology knobs.
      mesh: "MODELxDATA" serving mesh (e.g. 4x1) — tensor-parallel
        decode: attention heads, the KV cache, and the prefix pool shard
        over MODEL devices (head counts must be divisible; greedy output
        stays bit-identical to 1x1); MODEL*DATA must equal the replica
        process's device count. Per-device footprint lands in stats
        "memory" and rlt_serve_hbm_bytes{component=}.
      hosts_per_replica: gang-launch one replica PROCESS GROUP per mesh
        on multi-host topologies (leader + followers rendezvoused via
        jax.distributed; single-host default 1).
      decode_fold: decode iterations per compiled dispatch (K tokens per
        slot per engine step; amortizes dispatch/sync, admissions land at
        fold boundaries). pipeline: double-buffer fold dispatch (default
        on).
      fold_ladder: pre-lowered fold depths, e.g. "1,2,8" (comma list or
        YAML list; every rung >= 1, must include decode_fold). Each
        dispatch picks the deepest rung the current queue pressure
        allows — short folds while admissions wait, deep folds on a
        quiet queue — with zero steady-state compiles (the whole
        ladder compiles at construction). Dispatch counts land in
        stats fold_k and rlt_serve_fold_depth.
      prefill_chunk: chunked prefill (tokens per chunk, 0 = monolithic):
        long prompts prefill in chunks interleaved between decode folds.
        max_prefill_chunks_per_step: chunk-vs-fold interleave budget.
      piggyback_chunks: fuse prefill into the decode dispatch (Sarathi
        -style chunked piggybacking): up to C chunked-prefill rows ride
        INSIDE each decode fold instead of issuing separate
        prefill_step dispatches (0 = off; 1 <= C <= num_slots; needs
        prefill_chunk > 0). Resident decodes stop stalling behind
        admissions; outputs stay bit-exact. Traffic lands in
        rlt_serve_piggyback_*_total and stats piggyback.
      prefix_cache: "off" (default), "on" (64 blocks), or a block count
        — device-resident prefix KV reuse for shared prompt prefixes
        (implies chunked prefill). prefix_block: tokens per pool block.
      prefix_host_mb: host-RAM spill tier below the device prefix pool
        (MiB; 0 = off): LRU-evicted pool blocks spill D2H instead of
        dying, and a host hit promotes the block back through one
        compiled H2D copy — cache capacity grows from spare HBM to
        machine RAM with greedy outputs unchanged. prefix_disk_dir /
        prefix_disk_mb: an optional disk tier below the host tier
        (.npy block files under the directory, default budget 1024
        MiB) absorbing host-tier evictions. Tier traffic lands in
        rlt_serve_prefix_*_total{tier=} and stats prefix.tiers.
      kv_pages / kv_page: paged KV (block-table attention) — kv_pages
        arms it and sets the page budget, kv_page the tokens per page
        (default 16; must divide max_seq). KV capacity becomes the
        token budget kv_pages x kv_page instead of slots x max_seq, a
        prefix hit aliases cached pages copy-free (refcounted; the
        prefix cache and slot KV share ONE allocator, so
        prefix_cache must stay off), spill tiers and preemption
        handoff operate on the same pages, and admission parks when
        pages run out instead of deadlocking. Greedy output stays
        bit-identical to the dense engine; pool state lands in
        rlt_serve_kv_pages{state=} and stats kv_pages. Leave unset
        for the dense cache.
      priority_age_s: queued requests age toward priority 0 at this rate
        (seconds per priority level); unset = strict priority order.
      spec: speculative decoding — "off" (default), "ngram" (in-graph
        prompt-lookup drafter, zero extra weights), or "model" (small
        draft model); bare off/on parse as YAML booleans and normalize
        to "off"/"ngram". spec_depth: draft tokens proposed per verify
        forward (accepted prefix advances up to depth+1 tokens per
        forward). spec_draft_ckpt / spec_draft_config /
        spec_draft_int8: the draft model's checkpoint (spec=model),
        config overrides, and weight-only int8. spec_window: history
        window the draft model conditions on. Greedy output stays
        bit-identical to spec off; accept rates land in
        stats.spec_stats and the spec_accept_rate metric.
      metrics_port: serve a Prometheus /metrics endpoint (plus /stats
        JSON, /healthz, /debug/bundle, /fleet, /events, /traces,
        /alerts, /query) on
        this driver-side port for the duration of the run, aggregating
        every replica's registry (0 picks a free port; the chosen URL
        prints to stderr). Point `rlt top <host:port>` at it for a live
        fleet dashboard.
      fleet: drive the driver-side fleet aggregator behind /fleet
        (default on; needs metrics_port to be reachable).
        fleet_interval_s: poll cadence (default 2s); fleet_history:
        snapshots retained in the history ring (default 128).
      alerts: drive the watchtower (default on; rides the fleet
        plane) — fleet snapshots are sampled into bounded
        multi-resolution telemetry rings (obs.tsdb) and declarative
        alert rules (threshold / absence / multi-window burn-rate over
        the SLO-breach ratio) evaluate each tick with a
        pending->firing->resolved lifecycle behind /alerts and
        /query (rlt alerts / rlt plot). alerts_interval_s: evaluation
        cadence (default = fleet_interval_s); alerts_rules: rule
        overrides (a YAML/JSON file path or inline list — see
        docs/observability.md for the grammar); alerts_webhook: an
        http(s) URL notifications are shaped for (webhook-shaped stub
        sink — payloads recorded, no socket opened in this build).
      canary: run the canary probe lane (default off) — a tiny
        fixed-seed probe submitted every canary_interval_s (default
        10s) under the reserved _canary tenant at floor priority;
        TTFT / decode rate / exactness land in dedicated canary.*
        series and alert on deviation from the recorded baseline
        envelope (canary_baseline: JSON file written by bench.py).
        Canary traffic is excluded from organic accounting (cost
        ledger, goodput, autoscaler pressure, tenant rows).
      supervisor: drive the driver-side FleetSupervisor (default on) —
        the detect->decide->recover loop: unhealthy replicas drain
        (no new submissions, in-flight work finishes), dead replicas
        restart through the fabric from the same resolved config, and
        their incomplete requests fail over onto survivors by
        replaying the client journal's submit records (bit-identical
        token streams for greedy/seeded requests; already-streamed
        prefixes deduplicate client-side). restart_limit: consecutive
        failed restarts before a replica is parked as failed (default
        3); restart_backoff_s: base of the capped exponential restart
        backoff (default 1s). Restart/failover traffic lands in
        rlt_fleet_replica_restarts_total, rlt_serve_failover_*, and
        replica_lost/failover/replica_restarted events.
      rpc_timeout_s: per-RPC timeout for every client->replica call
        (default none — block); transient failures retry with capped
        exponential backoff + jitter before the replica is declared
        lost.
      router: the front-door routing policy (default on) — submit
        consults serve.router.Router instead of round-robin:
        supervisor states (draining/preempting/dead) and health
        verdicts demote or exclude replicas, shared-prefix traffic
        lands on the replica holding the warm blocks/pages
        (router_affinity, default on — digests match the engines'
        prefix_block/kv_page), and admission control sheds work at the
        door (router_shed, default on): a deadline the fleet's
        windowed decode rate cannot meet, or lowest-priority work on a
        saturated fleet (every routable queue >= shed_queue_factor x
        its slots, default 4.0), is rejected with a typed outcome and
        a retry-after hint instead of queueing to collapse.
        router_refresh_s: replica-view staleness bound (default 1s).
      retry_budget: aggregate client retry cap — transient-RPC retries
        across ALL calls are limited to this fraction of recent
        submits (default 0.5; false disables), so a sick fleet gets
        backpressure instead of a retry storm; exhaustion counts in
        rlt_serve_retry_budget_exhausted_total.
      hedge_after_s: hedged streaming reads — a stream with no new
        token for this long (while its replica still answers) is
        re-driven on a peer under the same id/seed, bit-exact with the
        delivered prefix deduplicated (default off; covers gray
        failures liveness probes cannot see).
      autoscale_min / autoscale_max / autoscale_interval_s: queue-
        driven replica autoscaling within [min, max] (autoscale_max
        arms it; min defaults to the initial replica count): sustained
        queue depth, shedding, or SLO breaches spawn replicas through
        the retained spawn recipes (role-aware — a disaggregated
        fleet's prefill and decode pools scale independently); a
        sustained-idle fleet retires them gracefully (drained +
        leftovers migrated — no request lost at retire).
      prefill_replicas: dedicate the FIRST N of `replicas` to chunked
        prefill only (disaggregated prefill/decode; needs a prefix
        cache or paged KV and at least one decode replica left over):
        the router lands new prompts on the prefill pool, each
        finished prefill's KV pages ship to a router-chosen decode
        replica over fabric queues, and the request decodes there
        warm — greedy output bit-identical to a fully local run.
        Long prompts stop stealing fold time from resident decodes.
      kvfleet: cross-replica KV sharing (default: auto — on for a
        multi-replica fleet with a prefix cache/paged KV). When the
        router must steer a request away from the replica holding its
        prefix chain, the target fetches the pages from that peer
        (digest-keyed, shard-aware) instead of re-prefilling cold —
        N caches become one fleet cache. kvfleet_timeout_s bounds a
        fetch (timeout/staleness degrade to cold prefill, never a
        lost request); kvfleet_inflight_mb bounds in-flight transfer
        bytes; kvfleet_bandwidth_mbps caps transfer throughput
        (0 = uncapped). Traffic lands in
        rlt_serve_kvfleet_*_total{role=} and the fleet rows.
        kvfleet_layerwise: stream a disaggregated prefill's shipped
        pages to the decode target PER LAYER as each ships, instead
        of one whole-prompt blob at completion — the decode replica
        imports layer l while layer l+1 is in flight, cutting
        ship-to-first-decode latency. A target dying mid-stream
        aborts the staged partial (cold prefill, nothing lost).
      kvstore_dir: fleet-shared persistent KV store (tier of last
        resort, content-addressed by the engines' chained page
        digests): evictions falling off the bottom of a replica's
        local tiers write through here instead of dying, a chain no
        live peer holds fetches from here through the same
        park->import->admit-warm path, a restarted fleet pre-seeds
        its routing directory from the store manifest (yesterday's
        system prompts hit on the first request), and park_session
        exports an idle conversation here and frees its pages —
        restored bit-exactly on the next turn, on any replica.
        kvstore_mb bounds the store (LRU-by-last-access GC on
        measured bytes; 0 = unbounded); kvstore_writethrough
        additionally writes EVERY completed prefill through (pages
        survive autoscale-retire, at extra write amplification).
        Corrupt/vanished entries degrade to cold prefill, never a
        crash. Traffic lands in rlt_serve_kvstore_*_total and the
        fleet rows. NOTE: one store dir per single-host fleet —
        multi-host gang processes would each hold only their own
        shard subset.
      tracing: record request traces on the replicas (default on);
        trace_out: after serving, write the replicas' recent traces as
        Chrome trace-event JSON to this path (opens in Perfetto).
      profile_s: capture an on-demand jax.profiler trace of replica 0
        for this many seconds while the submitted prompts decode; the
        artifact directory prints to stderr.
      watchdog: per-replica health watchdog (default on) — engine
        stall / admission wedge / compile-storm detection driving the
        health() RPC, rlt_health gauges, and automatic flight-recorder
        bundles. stall_s: seconds of no progress before a stall verdict
        (default 10); watchdog_interval_s: evaluation cadence.
      slo.<metric> <limit>: declarative SLO upper bounds evaluated
        against the replica stats snapshot (e.g. --serve.slo.ttft_p95_s
        0.5, --serve.slo.inter_token_p95_s 0.05, --serve.slo.error_rate
        0.01); breaches flip /healthz to 503 and count in
        rlt_slo_breaches_total{rule=...}.
      blackbox_dir / blackbox_keep: where automatic forensic bundles
        land (default RLT_BLACKBOX_DIR or the tempdir) and how many to
        retain. Inspect with `rlt doctor <host:port>` against
        metrics_port.
      journal: workload capture for deterministic replay (default on —
        a bounded in-memory ring of every submit/cancel + per-request
        emitted tokens). Pass a DIRECTORY to additionally stream the
        journal as rotated JSONL there; `false` disables capture.
        journal_capacity: ring size (default 4096 entries). Export via
        the /journal route, journal.jsonl in doctor bundles, or the
        journal_dump RPC; re-drive with `rlt replay <journal>`.
      prompts: path to a prompts file ("-" = stdin), one request per
        line as comma/space-separated token ids.
      max_new_tokens, temperature, top_k, top_p, seed, eos_token:
        sampling defaults applied to every request.

    All prompts are submitted up front (they overlap inside the engine —
    that is the point), streamed to completion, and printed as
    ``<request_id><TAB><prompt+generated ids csv>`` lines. One final JSON
    line carries the per-replica stats-endpoint snapshots.
    """
    import json as _json

    from ray_lightning_tpu import fabric
    from ray_lightning_tpu.serve import start_replicas

    serve_cfg = dict(config.pop("serve", None) or {})
    # Reject mistyped --serve.* keys FIRST, naming the valid vocabulary
    # — before any checkpoint loads or replicas spawn.
    unknown = sorted(
        k for k in serve_cfg
        if k not in _SERVE_KEYS and not k.startswith("slo.")
    )
    if unknown:
        raise ValueError(
            f"unknown serve option(s) {unknown}; valid --serve.* keys: "
            f"{sorted(_SERVE_KEYS)} (plus slo.<metric> rules)"
        )
    # Mesh spec: validated up front like the key vocabulary — a
    # malformed --serve.mesh must fail before a checkpoint loads or a
    # replica spawns, naming the valid format. Normalized to the
    # canonical "MODELxDATA" string (YAML coerces a bare "8" to int).
    from ray_lightning_tpu.parallel.mesh import parse_mesh_spec

    mesh_raw = serve_cfg.pop("mesh", None)
    mesh_spec = None
    if mesh_raw is not None:
        mesh_spec = "{}x{}".format(*parse_mesh_spec(mesh_raw))
    hosts_per_replica = int(serve_cfg.pop("hosts_per_replica", 1))
    if hosts_per_replica < 1:
        raise ValueError("--serve.hosts_per_replica must be >= 1")
    ckpt_path = serve_cfg.pop("ckpt_path", None)
    if ckpt_path is None:
        raise ValueError("serve requires --serve.ckpt_path")
    prompts_src = serve_cfg.pop("prompts", None)
    if prompts_src is None:
        raise ValueError(
            "serve requires --serve.prompts (file of token-id lines, or -)"
        )
    sampling = {
        "max_new_tokens": int(serve_cfg.pop("max_new_tokens", 32)),
        "temperature": float(serve_cfg.pop("temperature", 0.0)),
        "top_k": serve_cfg.pop("top_k", None),
        "top_p": serve_cfg.pop("top_p", None),
        "eos_token": serve_cfg.pop("eos_token", None),
    }
    seed = int(serve_cfg.pop("seed", 0))
    replicas = int(serve_cfg.pop("replicas", 1))
    replica_kwargs = {
        "ckpt_path": ckpt_path,
        "model_config": serve_cfg.pop("config", None),
        "int8": bool(serve_cfg.pop("int8", False)),
        "num_slots": int(serve_cfg.pop("num_slots", 4)),
        "max_seq": serve_cfg.pop("max_seq", None),
        "max_prefills_per_step": int(
            serve_cfg.pop("max_prefills_per_step", 1)
        ),
        "decode_fold": int(serve_cfg.pop("decode_fold", 1)),
        "pipeline": bool(serve_cfg.pop("pipeline", True)),
        "prefill_chunk": int(serve_cfg.pop("prefill_chunk", 0)),
        "prefix_block": int(serve_cfg.pop("prefix_block", 16)),
        "max_prefill_chunks_per_step": int(
            serve_cfg.pop("max_prefill_chunks_per_step", 1)
        ),
    }
    # Fused-dispatch knobs, validated up front with named ranges (the
    # engine re-validates, but a fleet launch should die on the driver
    # with the flag name, not in replica 3's traceback).
    ladder = serve_cfg.pop("fold_ladder", None)
    if ladder is not None:
        if isinstance(ladder, str):
            ladder = [r for r in ladder.replace(",", " ").split() if r]
        elif isinstance(ladder, (int, float)):
            ladder = [ladder]
        rungs = sorted({int(r) for r in ladder})
        bad = [r for r in rungs if r < 1]
        if bad:
            raise ValueError(
                f"--serve.fold_ladder rungs {bad} out of range: every "
                "rung must be >= 1 (decode iterations per dispatch)"
            )
        if replica_kwargs["decode_fold"] not in rungs:
            raise ValueError(
                f"--serve.fold_ladder {rungs} must include decode_fold="
                f"{replica_kwargs['decode_fold']} (the rung a "
                "full-runway dispatch uses)"
            )
        replica_kwargs["fold_ladder"] = rungs
    pbc = int(serve_cfg.pop("piggyback_chunks", 0))
    if not 0 <= pbc <= replica_kwargs["num_slots"]:
        raise ValueError(
            f"--serve.piggyback_chunks {pbc} out of range: need 0 <= C "
            f"<= num_slots={replica_kwargs['num_slots']} (each "
            "piggyback row targets one slot; 0 = off)"
        )
    if pbc and replica_kwargs["prefill_chunk"] <= 0:
        raise ValueError(
            "--serve.piggyback_chunks needs --serve.prefill_chunk > 0 "
            "(piggyback rows are chunked-prefill rows riding the "
            "decode fold)"
        )
    if pbc:
        replica_kwargs["piggyback_chunks"] = pbc
    if mesh_spec is not None:
        replica_kwargs["mesh"] = mesh_spec
    age = serve_cfg.pop("priority_age_s", None)
    if age is not None:
        replica_kwargs["priority_age_s"] = float(age)
    # Speculative decoding: --serve.spec {off|ngram|model} with
    # --serve.spec_depth draft tokens per verify; spec=model drafts with
    # the (optionally int8) checkpoint at --serve.spec_draft_ckpt.
    # Dotted values parse as YAML, where bare off/on are 1.1 booleans —
    # map them back to the words the flag documents (on = the
    # zero-weight n-gram drafter).
    spec_raw = serve_cfg.pop("spec", "off")
    if spec_raw is False:
        spec_raw = "off"
    elif spec_raw is True:
        spec_raw = "ngram"
    replica_kwargs["spec"] = str(spec_raw)
    replica_kwargs["spec_depth"] = int(serve_cfg.pop("spec_depth", 4))
    replica_kwargs["spec_window"] = int(serve_cfg.pop("spec_window", 32))
    replica_kwargs["spec_draft_int8"] = bool(
        serve_cfg.pop("spec_draft_int8", False)
    )
    draft_ckpt = serve_cfg.pop("spec_draft_ckpt", None)
    if draft_ckpt is not None:
        replica_kwargs["spec_draft_ckpt"] = str(draft_ckpt)
    draft_cfg = serve_cfg.pop("spec_draft_config", None)
    if draft_cfg is not None:
        replica_kwargs["spec_draft_config"] = dict(draft_cfg)
    replica_kwargs["tracing"] = bool(serve_cfg.pop("tracing", True))
    replica_kwargs["watchdog"] = bool(serve_cfg.pop("watchdog", True))
    # Workload journal: the ring is on by default; --serve.journal DIR
    # additionally spills JSONL there (rotated), --serve.journal false
    # turns capture off entirely. YAML parses bare off/on as booleans.
    jr = serve_cfg.pop("journal", True)
    if jr is False or jr in ("off",):
        replica_kwargs["journal"] = False
    elif jr is not True and jr not in ("on",):
        replica_kwargs["journal_dir"] = str(jr)
    jc = serve_cfg.pop("journal_capacity", None)
    if jc is not None:
        replica_kwargs["journal_capacity"] = int(jc)
    for knob, cast in (
        ("watchdog_interval_s", float),
        ("stall_s", float),
        ("blackbox_dir", str),
        ("blackbox_keep", int),
        # Preemption signal plane: grace window for the drain,
        # SIGTERM-as-notice (on by default), and the GCE-shaped
        # maintenance-event metadata poller (off by default — only
        # meaningful on metadata-served hosts).
        ("preempt_grace_s", float),
        ("preempt_sigterm", bool),
        ("preempt_metadata", bool),
    ):
        val = serve_cfg.pop(knob, None)
        if val is not None:
            replica_kwargs[knob] = cast(val)
    # SLO rules: YAML ``serve: {slo: {metric: limit}}`` and/or dotted
    # ``--serve.slo.<metric> <limit>`` flags (all upper bounds).
    slo_cfg = dict(serve_cfg.pop("slo", None) or {})
    for key in [k for k in serve_cfg if k.startswith("slo.")]:
        slo_cfg[key[len("slo."):]] = serve_cfg.pop(key)
    if slo_cfg:
        replica_kwargs["slo"] = {
            str(m): float(v) for m, v in slo_cfg.items()
        }
    metrics_port = serve_cfg.pop("metrics_port", None)
    trace_out = serve_cfg.pop("trace_out", None)
    profile_s = serve_cfg.pop("profile_s", None)
    # Fleet aggregation (rides the metrics endpoint): the driver-side
    # puller behind /fleet, rlt top, and the fleet.json bundle file.
    fleet_enabled = bool(serve_cfg.pop("fleet", True))
    fleet_interval_s = float(serve_cfg.pop("fleet_interval_s", 2.0))
    fleet_history = int(serve_cfg.pop("fleet_history", 128))
    # Watchtower (rides the fleet plane): retained telemetry rings +
    # the burn-rate alert engine behind /alerts, /query, and rlt
    # alerts/plot; the canary lane submits fixed-seed probes under the
    # reserved _canary tenant (excluded from organic accounting).
    alerts_enabled = bool(serve_cfg.pop("alerts", True))
    alerts_interval_s = serve_cfg.pop("alerts_interval_s", None)
    if alerts_interval_s is not None:
        alerts_interval_s = float(alerts_interval_s)
    alerts_rules = serve_cfg.pop("alerts_rules", None)
    alerts_webhook = serve_cfg.pop("alerts_webhook", None)
    canary_enabled = bool(serve_cfg.pop("canary", False))
    canary_interval_s = float(serve_cfg.pop("canary_interval_s", 10.0))
    canary_baseline = serve_cfg.pop("canary_baseline", None)
    # Fault tolerance: the driver-side supervisor (drain/restart/fail
    # over) and the client's per-RPC timeout knob.
    supervisor_enabled = bool(serve_cfg.pop("supervisor", True))
    restart_limit = int(serve_cfg.pop("restart_limit", 3))
    restart_backoff_s = float(serve_cfg.pop("restart_backoff_s", 1.0))
    rpc_timeout_s = serve_cfg.pop("rpc_timeout_s", None)
    if rpc_timeout_s is not None:
        rpc_timeout_s = float(rpc_timeout_s)
    # Front-door router (default on): health/state-aware + prefix-
    # affinity routing with admission control; the autoscaler arms when
    # autoscale_max is set. retry_budget caps the client's aggregate
    # transient-RPC retries as a fraction of recent submits (false
    # disables the cap); hedge_after_s arms hedged streaming reads.
    router_enabled = bool(serve_cfg.pop("router", True))
    router_refresh_s = float(serve_cfg.pop("router_refresh_s", 1.0))
    router_affinity = bool(serve_cfg.pop("router_affinity", True))
    router_shed = bool(serve_cfg.pop("router_shed", True))
    shed_queue_factor = float(serve_cfg.pop("shed_queue_factor", 4.0))
    retry_budget = serve_cfg.pop("retry_budget", 0.5)
    retry_budget = (
        None if retry_budget in (False, None) else float(retry_budget)
    )
    hedge_after_s = serve_cfg.pop("hedge_after_s", None)
    if hedge_after_s is not None:
        hedge_after_s = float(hedge_after_s)
    # Control-plane throughput knobs (validated up front with named
    # ranges — a fleet launch dies on the driver with the flag name):
    # submit_batch_ms arms the client's micro-batching window (one
    # vectorized plan + one submit_many RPC per target per window),
    # directory_shards lock-stripes the fleet KV directory.
    submit_batch_ms = float(serve_cfg.pop("submit_batch_ms", 0.0))
    if not 0.0 <= submit_batch_ms <= 1000.0:
        raise ValueError(
            f"--serve.submit_batch_ms {submit_batch_ms} out of range: "
            "need 0 <= ms <= 1000 (micro-batching window; 0 = off, the "
            "serial submit path)"
        )
    directory_shards = int(serve_cfg.pop("directory_shards", 1))
    if not 1 <= directory_shards <= 256:
        raise ValueError(
            f"--serve.directory_shards {directory_shards} out of "
            "range: need 1 <= N <= 256 (lock stripes over the fleet KV "
            "directory; 1 = the single-shard structure)"
        )
    autoscale_min = serve_cfg.pop("autoscale_min", None)
    autoscale_max = serve_cfg.pop("autoscale_max", None)
    autoscale_interval_s = float(
        serve_cfg.pop("autoscale_interval_s", 2.0)
    )
    if autoscale_max is not None and int(autoscale_max) < replicas:
        raise ValueError(
            f"--serve.autoscale_max {autoscale_max} is below the "
            f"initial replica count {replicas}"
        )
    # Fleet KV plane: disaggregated prefill/decode pools + the
    # cross-replica transfer knobs (validated below once the prefix
    # cache / paged-KV config is resolved).
    prefill_replicas = int(serve_cfg.pop("prefill_replicas", 0))
    if not 0 <= prefill_replicas < replicas:
        raise ValueError(
            f"--serve.prefill_replicas {prefill_replicas} must leave "
            f"at least one decode replica (0 <= N < replicas="
            f"{replicas})"
        )
    kvfleet = serve_cfg.pop("kvfleet", None)
    if kvfleet is not None:
        kvfleet = bool(kvfleet)
    kvfleet_timeout_s = float(serve_cfg.pop("kvfleet_timeout_s", 5.0))
    kvfleet_inflight_mb = float(
        serve_cfg.pop("kvfleet_inflight_mb", 64.0)
    )
    kvfleet_bandwidth_mbps = float(
        serve_cfg.pop("kvfleet_bandwidth_mbps", 0.0)
    )
    kvfleet_layerwise = bool(serve_cfg.pop("kvfleet_layerwise", False))
    if kvfleet_layerwise and not (kvfleet or prefill_replicas):
        raise ValueError(
            "--serve.kvfleet_layerwise streams shipped KV pages per "
            "layer over the fleet plane: enable --serve.kvfleet or "
            "set --serve.prefill_replicas first"
        )
    if kvfleet_layerwise:
        replica_kwargs["kvfleet_layerwise"] = True
    # Persistent KV store (fleet-shared tier of last resort):
    # --serve.kvstore_dir mounts it, --serve.kvstore_mb bounds it (LRU
    # GC; 0 = unbounded), --serve.kvstore_writethrough makes prefill
    # replicas write every completed prefill through so pages survive
    # autoscale-retire.
    kvstore_dir = serve_cfg.pop("kvstore_dir", None)
    kvstore_mb = float(serve_cfg.pop("kvstore_mb", 0.0))
    if kvstore_mb < 0:
        raise ValueError(
            f"--serve.kvstore_mb {kvstore_mb} must be >= 0 (MiB budget; "
            "0 = unbounded)"
        )
    kvstore_writethrough = bool(
        serve_cfg.pop("kvstore_writethrough", False)
    )
    if kvstore_writethrough and kvstore_dir is None:
        raise ValueError(
            "--serve.kvstore_writethrough needs --serve.kvstore_dir "
            "(the store to write through to)"
        )
    if kvstore_dir is not None:
        replica_kwargs["kvstore_dir"] = str(kvstore_dir)
        replica_kwargs["kvstore_mb"] = kvstore_mb
        replica_kwargs["kvstore_writethrough"] = kvstore_writethrough
    pc = serve_cfg.pop("prefix_cache", "off")
    if isinstance(pc, str):
        pc_norm = pc.strip().lower()
        if pc_norm in ("off", "false", "0", ""):
            blocks = 0
        elif pc_norm in ("on", "true"):
            blocks = 64
        else:
            blocks = int(pc_norm)
    else:
        blocks = (64 if pc else 0) if isinstance(pc, bool) else int(pc)
    replica_kwargs["prefix_blocks"] = blocks
    # Spill tiers below the device pool (host RAM, then disk). Budgets
    # are MiB floats; the engine rejects tiers without a device pool.
    replica_kwargs["prefix_host_mb"] = float(
        serve_cfg.pop("prefix_host_mb", 0.0)
    )
    pdd = serve_cfg.pop("prefix_disk_dir", None)
    if pdd is not None:
        replica_kwargs["prefix_disk_dir"] = str(pdd)
    replica_kwargs["prefix_disk_mb"] = float(
        serve_cfg.pop("prefix_disk_mb", 0.0)
    )
    # Paged KV: --serve.kv_pages arms block-table attention (capacity =
    # kv_pages * kv_page tokens instead of slots * max_seq);
    # --serve.kv_page sets the page size (default 16). Validated up
    # front: the page budget must be real, the page size must be a
    # token count, and the DENSE prefix cache cannot ride along — the
    # paged allocator IS the prefix cache (copy-free aliasing), so a
    # combined config would silently double-provision; reject it loudly
    # instead.
    kv_pages = serve_cfg.pop("kv_pages", None)
    kv_page = serve_cfg.pop("kv_page", None)
    if kv_pages is not None:
        kv_pages = int(kv_pages)
        if kv_pages < 2:
            raise ValueError(
                f"--serve.kv_pages {kv_pages} is not a usable page "
                "budget: need >= 2 (one scratch page + at least one "
                "real page; the engine additionally requires the "
                "budget to hold one max_seq-length request)"
            )
        replica_kwargs["kv_pages"] = kv_pages
    if kv_page is not None:
        kv_page = int(kv_page)
        if kv_page < 1:
            raise ValueError(
                f"--serve.kv_page {kv_page} must be >= 1 (tokens per "
                "KV page; it must also divide the engine's max_seq)"
            )
        if kv_pages is None:
            raise ValueError(
                "--serve.kv_page needs --serve.kv_pages (the paged-KV "
                "page budget); dense mode takes neither"
            )
        replica_kwargs["kv_page"] = kv_page
    if kv_pages and replica_kwargs.get("prefix_blocks"):
        raise ValueError(
            "--serve.kv_pages (paged KV) unifies the prefix pool into "
            "the page allocator — prefix sharing is built in and "
            "copy-free; drop --serve.prefix_cache/--serve.prefix_block "
            "(tune the page size with --serve.kv_page instead)"
        )
    pb = serve_cfg.pop("prefill_buckets", None)
    if pb is not None:
        replica_kwargs["prefill_buckets"] = [int(b) for b in pb]
    if prefill_replicas and not (blocks or kv_pages):
        raise ValueError(
            "--serve.prefill_replicas (disaggregated prefill) ships KV "
            "pages through the prefix pool: set --serve.prefix_cache "
            "(dense) or --serve.kv_pages (paged)"
        )
    roles = None
    if prefill_replicas:
        roles = (
            ["prefill"] * prefill_replicas
            + ["decode"] * (replicas - prefill_replicas)
        )
    # Resolved router policy: built once — it constructs the Router
    # below AND rides into every replica's journal header (provenance a
    # replayed capture carries). Affinity digests must use the engines'
    # block/page size, and only pay when a prefix cache exists at all.
    router_cfg = None
    if router_enabled:
        aff_block = int(replica_kwargs.get("prefix_block", 16))
        if replica_kwargs.get("kv_pages"):
            aff_block = int(replica_kwargs.get("kv_page", 16) or 16)
        router_cfg = {
            "refresh_s": router_refresh_s,
            "affinity": bool(
                router_affinity
                and (blocks > 0 or replica_kwargs.get("kv_pages"))
            ),
            "prefix_block": aff_block,
            "shed": router_shed,
            "shed_queue_factor": shed_queue_factor,
            "retry_budget_ratio": retry_budget,
            "hedge_after_s": hedge_after_s,
            "autoscale_min": autoscale_min,
            "autoscale_max": autoscale_max,
            "autoscale_interval_s": autoscale_interval_s,
            "submit_batch_ms": submit_batch_ms,
            "directory_shards": directory_shards,
        }
        replica_kwargs["router_config"] = router_cfg
    if serve_cfg:
        # _SERVE_KEYS said these were valid but nothing consumed them:
        # the vocabulary and the pops drifted apart — a bug here, not a
        # user typo (those were rejected up front).
        raise RuntimeError(
            f"serve options {sorted(serve_cfg)} are listed in _SERVE_KEYS "
            "but unhandled"
        )

    if prompts_src == "-":
        lines = [ln.strip() for ln in sys.stdin]
    else:
        with open(prompts_src) as f:
            lines = [ln.strip() for ln in f]
    prompts = [
        [int(t) for t in ln.replace(",", " ").split()] for ln in lines if ln
    ]
    if not prompts:
        raise ValueError(f"no prompts in {prompts_src!r}")

    if not fabric.is_initialized():
        fabric.init()
    # Replicas on a chipless fabric decode on CPU; pin the platform so the
    # actor does not stall probing for devices it will not get. A mesh
    # spec on CPU additionally forces that many VIRTUAL host devices in
    # the replica process (the same trick the strategies' CPU worker
    # planning uses) — a "4x2" mesh needs 8 devices wherever it runs.
    env = (
        {"JAX_PLATFORMS": "cpu"}
        if fabric.cluster_resources().get("TPU", 0) < 1
        else {}
    )
    if env and mesh_spec is not None:
        model, data = parse_mesh_spec(mesh_spec)
        if model * data > 1:
            env["XLA_FLAGS"] = (
                f"--xla_force_host_platform_device_count={model * data}"
            )
    client = start_replicas(
        replicas,
        env=env,
        hosts_per_replica=hosts_per_replica,
        rpc_timeout_s=rpc_timeout_s,
        retry_budget_ratio=retry_budget,
        hedge_after_s=hedge_after_s,
        submit_batch_ms=submit_batch_ms,
        roles=roles,
        kvfleet=kvfleet,
        kvfleet_timeout_s=kvfleet_timeout_s,
        kvfleet_inflight_mb=kvfleet_inflight_mb,
        kvfleet_bandwidth_mbps=kvfleet_bandwidth_mbps,
        **replica_kwargs,
    )
    metrics_server = None
    fleet_poller = None
    watchtower = None
    supervisor = None
    router = None
    autoscaler = None
    if supervisor_enabled:
        # Close the detect->decide->recover loop for the run's duration:
        # unhealthy replicas drain, dead ones restart (same resolved
        # config) within the backoff budget, and their incomplete
        # requests fail over onto survivors bit-exactly.
        from ray_lightning_tpu.serve.supervisor import FleetSupervisor

        supervisor = FleetSupervisor(
            client,
            restart_limit=restart_limit,
            restart_backoff_s=restart_backoff_s,
        ).start()
    if router_cfg is not None:
        # The front door: submit consults this policy instead of the
        # bare round-robin — supervisor states and health verdicts
        # demote/exclude, shared prefixes land on the warm replica, and
        # an overloaded fleet sheds at the door instead of collapsing
        # its queues.
        from ray_lightning_tpu.serve.router import (
            Router,
            RouterAutoscaler,
        )

        router = Router(
            client=client,
            state_fn=(
                supervisor.rows if supervisor is not None else None
            ),
            refresh_s=router_refresh_s,
            affinity=router_cfg["affinity"],
            prefix_block=router_cfg["prefix_block"],
            shed=router_shed,
            shed_queue_factor=shed_queue_factor,
            directory_shards=directory_shards,
        )
        client.router = router
        # Warm-start: a fresh fleet inherits the persistent store's
        # manifest as store-held directory routes, so yesterday's
        # prefixes hit (via a store fetch) on the FIRST request.
        client.seed_store_directory(router)
        if autoscale_max is not None:
            autoscaler = RouterAutoscaler(
                client,
                router=router,
                min_replicas=int(autoscale_min or replicas),
                max_replicas=int(autoscale_max),
                interval_s=autoscale_interval_s,
            ).start()
    try:
        if metrics_port is not None:
            # Driver-side Prometheus endpoint for the run's duration:
            # each scrape pulls every replica's registry live (plus the
            # driver's own, which carries fabric heartbeat gauges), and
            # /healthz aggregates fabric heartbeat verdicts + every
            # replica's health() RPC — 200 only while nothing is
            # unhealthy, so an external LB can act on it. /fleet,
            # /events, and /traces serve the fleet plane (rlt top,
            # post-mortems, the stitched cross-process trace).
            metrics_server, fleet_poller, watchtower = _serve_obs_server(
                client,
                int(metrics_port),
                fleet=fleet_enabled,
                fleet_interval_s=fleet_interval_s,
                fleet_history=fleet_history,
                supervisor=supervisor,
                router=router,
                alerts=alerts_enabled,
                alerts_interval_s=alerts_interval_s,
                alerts_rules=alerts_rules,
                alerts_webhook=alerts_webhook,
                canary=canary_enabled,
                canary_interval_s=canary_interval_s,
                canary_baseline=canary_baseline,
            )
            if supervisor is not None and fleet_poller is not None:
                # Share PR 8's pull: the supervisor reads heartbeat ages
                # from the poller's latest snapshot instead of its own
                # fabric read.
                supervisor.poller = fleet_poller
            if router is not None and fleet_poller is not None:
                # Same for the router: its replica views ride the
                # poller's snapshot instead of issuing their own pulls.
                router.poller = fleet_poller
            print(
                f"serve metrics endpoint: {metrics_server.url}",
                file=sys.stderr,
                flush=True,
            )
        handles = [
            client.submit(p, seed=seed + i, **sampling)
            for i, p in enumerate(prompts)
        ]
        if profile_s is not None:
            # Capture while the submitted prompts decode on the loop
            # thread (the RPC itself only sleeps replica-side).
            prof = client.profile(float(profile_s))
            print(
                "serve profile: "
                + (prof.get("dir", "") if prof.get("ok") else str(prof)),
                file=sys.stderr,
                flush=True,
            )
        outputs = []
        for p, h in zip(prompts, handles):
            toks = list(client.stream_handle(h))
            outputs.append(
                {"request_id": h.request_id, "tokens": p + toks}
            )
            print(
                h.request_id
                + "\t"
                + ",".join(str(t) for t in p + toks)
            )
        if trace_out:
            trace_json = client.export_trace(n=len(prompts))
            with open(trace_out, "w") as f:
                _json.dump(trace_json, f)
            print(f"serve trace written: {trace_out}", file=sys.stderr,
                  flush=True)
        stats = client.stats()
        print(_json.dumps({"serve_stats": stats}))
        return {"outputs": outputs, "stats": stats}
    finally:
        if autoscaler is not None:
            autoscaler.stop()  # before shutdown: no scaling mid-teardown
        if supervisor is not None:
            supervisor.stop()  # before shutdown: no restarts mid-teardown
        if watchtower is not None:
            watchtower.stop()  # before the poller: its snapshot feed
        if fleet_poller is not None:
            fleet_poller.stop()
        if metrics_server is not None:
            metrics_server.close()
        client.shutdown()


def run_doctor(config: Dict[str, Any]) -> Dict[str, Any]:
    """``doctor``: interrogate a live serve obs endpoint.

    Usage: ``rlt doctor <host:port> [--doctor.bundle DIR]`` where
    ``<host:port>`` is the ``--serve.metrics_port`` endpoint (or any
    :class:`obs.MetricsHTTPServer` with a health collector). Prints the
    health report — overall verdict, per-component verdicts with
    reasons, per-replica sections — and, with ``--doctor.bundle``,
    pulls a flight-recorder bundle over ``/debug/bundle`` into DIR.
    Returns ``{"status": <http code>, "report": ..., "bundle": ...}``;
    status 200 means healthy, 503 carries the reason.
    """
    import json as _json
    import urllib.error
    import urllib.request

    cfg = dict(config.pop("doctor", None) or {})
    addr = cfg.pop("addr", None) or cfg.pop("url", None)
    bundle_dir = cfg.pop("bundle", None)
    timeout = float(cfg.pop("timeout_s", 30.0))
    if cfg:
        raise ValueError(f"unknown doctor options: {sorted(cfg)}")
    if not addr:
        raise ValueError(
            "doctor requires the serve obs endpoint: rlt doctor <host:port>"
        )
    base = str(addr) if "://" in str(addr) else f"http://{addr}"
    base = base.rstrip("/")

    def fetch(path: str):
        try:
            resp = urllib.request.urlopen(base + path, timeout=timeout)
            return resp.status, resp.read()
        except urllib.error.HTTPError as exc:
            # 503 is an ANSWER (unhealthy + JSON reason), not a failure.
            return exc.code, exc.read()

    status, body = fetch("/healthz")
    try:
        report = _json.loads(body)
    except ValueError:
        report = {
            "raw": body.decode(errors="replace").strip(),
            "healthy": status == 200,
        }

    def show(rep: Dict[str, Any], indent: str = "") -> None:
        verdict = rep.get("verdict", "healthy" if status == 200 else "?")
        print(f"{indent}overall: {verdict}")
        for name, comp in sorted((rep.get("components") or {}).items()):
            reasons = "; ".join(comp.get("reasons") or [])
            line = f"{indent}  {name:<28} {comp.get('verdict', '?')}"
            print(line + (f"   {reasons}" if reasons else ""))

    print(f"doctor {base} -> HTTP {status}")
    show(report)
    for i, rep in enumerate(report.get("replicas") or []):
        print(f"replica {i}:")
        show(rep, indent="  ")

    out: Dict[str, Any] = {"status": status, "report": report}
    if bundle_dir:
        b_status, b_body = fetch("/debug/bundle")
        if b_status != 200:
            raise RuntimeError(
                f"bundle pull failed: HTTP {b_status} "
                f"{b_body[:200].decode(errors='replace')}"
            )
        manifest = _json.loads(b_body)
        files = manifest.get("files_content") or {}
        import os as _os

        dest = _os.path.join(
            str(bundle_dir),
            _os.path.basename(manifest.get("dir", "bundle")),
        )
        _os.makedirs(dest, exist_ok=True)
        for name, content in files.items():
            with open(_os.path.join(dest, name), "w") as f:
                f.write(content)
        print(f"bundle pulled: {dest} ({len(files)} files)")
        out["bundle"] = dest
    return out


def run_replay(config: Dict[str, Any]) -> Dict[str, Any]:
    """``replay``: re-drive a captured workload journal bit-exactly.

    Usage: ``rlt replay <journal> [--replay.*]`` where ``<journal>`` is
    a journal JSONL file (a doctor bundle's ``journal.jsonl``, a saved
    ``/journal`` body, or a ``--serve.journal`` spill file/directory).
    The engine + scheduler rebuild from the journal's recorded
    config/checkpoint header and the recorded request stream is
    re-driven; per-request token output must match the recorded
    outcomes bit-exactly, with a first-divergence report (request id,
    token index, expected vs got) on mismatch. Exit status: 0 exact,
    1 diverged (the scriptable regression probe).

    Options (``--replay.<key>``):
      ckpt: checkpoint path override (benchmark a DIFFERENT engine
        build against the captured trace; default: the recorded path).
      config: model-config dict override (with ckpt overrides).
      timing: "virtual" (default — as fast as the engine goes, recorded
        cancels fire deterministically at their recorded token counts)
        or "wall" (recorded inter-arrivals honored; emits a perf
        comparison — tokens/s, TTFT p50/p95, goodput — against the
        recorded run's ledger, so the trace doubles as a benchmark).
      replica: which replica's stream to replay from a replica-tagged
        multi-replica journal (default: lowest tag).
      router: re-drive the capture through the ROUTER instead of the
        single-engine path — every replica stream merges, every submit
        routes through a Router.plan rebuilt from the header's recorded
        policy knobs, and the verdict additionally asserts zero lost
        (shedding is forced off: a replay must place every request).
      speed: wall-pace multiplier for --replay.router (1.0 = recorded
        pace, 10.0 = ten times faster; truncations stay deterministic
        so exactness holds at any speed). Router mode only.
      max_steps: scheduler-step budget (default 200000).
      out: also write the verdict JSON to this path.
    """
    import json as _json

    from ray_lightning_tpu.obs.journal import (
        load_journal,
        load_journal_streams,
        replay_journal,
        replay_journal_router,
    )

    cfg = dict(config.pop("replay", None) or {})
    journal_path = cfg.pop("journal", None)
    ckpt = cfg.pop("ckpt", None)
    model_cfg = cfg.pop("config", None)
    timing = str(cfg.pop("timing", "virtual"))
    replica = cfg.pop("replica", None)
    use_router = bool(cfg.pop("router", False))
    speed = float(cfg.pop("speed", 1.0))
    max_steps = int(cfg.pop("max_steps", 200_000))
    out_path = cfg.pop("out", None)
    if cfg:
        raise ValueError(f"unknown replay options: {sorted(cfg)}")
    if not journal_path:
        raise ValueError(
            "replay requires a journal path: rlt replay <journal.jsonl>"
        )
    if speed <= 0:
        raise ValueError(
            f"--replay.speed {speed} out of range: need > 0 "
            "(wall-pace multiplier; 1.0 = recorded pace)"
        )
    if speed != 1.0 and not use_router:
        raise ValueError(
            "--replay.speed only applies to --replay.router (the "
            "single-engine path paces with --replay.timing)"
        )
    if use_router:
        result = replay_journal_router(
            load_journal_streams(str(journal_path)),
            ckpt_path=None if ckpt is None else str(ckpt),
            model_config=(
                None if model_cfg is None else dict(model_cfg)
            ),
            speed=speed,
            max_steps=max_steps,
        )
        verdict = "EXACT" if result["exact"] else "DIVERGED"
        print(
            f"router replay {journal_path} -> {verdict}: "
            f"{result['compared']}/{result['requests']} requests "
            f"compared over {result['streams']} stream(s), "
            f"{result['planned']} planned, {result['lost']} lost, "
            f"{result['tokens_compared']} tokens, "
            f"speed={result['speed']}x",
            file=sys.stderr,
            flush=True,
        )
    else:
        journal = load_journal(
            str(journal_path),
            replica=None if replica is None else int(replica),
        )
        result = replay_journal(
            journal,
            ckpt_path=None if ckpt is None else str(ckpt),
            model_config=None if model_cfg is None else dict(model_cfg),
            timing=timing,
            max_steps=max_steps,
        )
        verdict = "EXACT" if result["exact"] else "DIVERGED"
        print(
            f"replay {journal_path} -> {verdict}: "
            f"{result['compared']}/{result['requests']} requests "
            f"compared, {result['tokens_compared']} tokens, "
            f"{result['open']} open at capture, "
            f"timing={result['timing']}",
            file=sys.stderr,
            flush=True,
        )
    div = result.get("divergence")
    if div is not None:
        print(
            f"first divergence: request {div['request_id']} token "
            f"{div['token_index']}: expected {div['expected']} got "
            f"{div['got']}",
            file=sys.stderr,
            flush=True,
        )
    perf = result.get("perf")
    if perf is not None:
        rec, rep = perf["recorded"], perf["replayed"]
        print(
            "perf recorded vs replayed: "
            f"tok/s {rec['tokens_per_sec']} -> {rep['tokens_per_sec']}  "
            f"ttft_p50 {rec['ttft_p50_s']} -> {rep['ttft_p50_s']}  "
            f"ttft_p95 {rec['ttft_p95_s']} -> {rep['ttft_p95_s']}  "
            f"goodput {rec['goodput_tokens_per_device_s']} -> "
            f"{rep['goodput_tokens_per_device_s']}",
            file=sys.stderr,
            flush=True,
        )
        # Phase-level diff (wall mode, when the capture carried the
        # anatomy ledgers): recorded vs replayed p95 per phase —
        # pinpoints WHICH phase the incident lost its time to.
        ph = perf.get("phases") or {}
        rec_p, rep_p = ph.get("recorded") or {}, ph.get("replayed") or {}
        if rec_p or rep_p:
            from ray_lightning_tpu.obs.anatomy import PHASES

            def _p95(block: Dict[str, Any], phase: str) -> str:
                row = block.get(phase)
                return f"{row['p95_s']:g}" if row else "-"

            cells = [
                f"{phase} {_p95(rec_p, phase)}->{_p95(rep_p, phase)}"
                for phase in PHASES
                if phase in rec_p or phase in rep_p
            ]
            if cells:
                print(
                    "phase p95 recorded vs replayed: "
                    + "  ".join(cells),
                    file=sys.stderr,
                    flush=True,
                )
    if out_path:
        with open(str(out_path), "w") as f:
            _json.dump(result, f, indent=2, default=str)
    print(_json.dumps(
        {k: v for k, v in result.items() if k != "rows"}, default=str
    ))
    return result


def _fmt_cell(v: Any, width: int, digits: int = 3) -> str:
    if v is None:
        s = "-"
    elif isinstance(v, float):
        s = f"{v:.{digits}f}"
    else:
        s = str(v)
    return s.rjust(width)


def render_fleet(payload: Dict[str, Any]) -> str:
    """One terminal frame of the fleet dashboard from a ``/fleet``
    payload (latest snapshot + history ring): a header line, one row
    per replica, and the fleet roll-up. Plain text — the same string
    pipes cleanly and paints a tty frame."""
    import datetime as _dt

    latest = payload.get("latest") or {}
    rows = latest.get("replicas") or []
    fleet = latest.get("fleet") or {}
    ts = latest.get("ts")
    when = (
        _dt.datetime.fromtimestamp(ts).strftime("%H:%M:%S")
        if ts else "-"
    )
    history = payload.get("history") or []
    out = [
        f"rlt top — {len(rows)} replica(s) @ {when}  "
        f"(polls={payload.get('polls', 0)} "
        f"errors={payload.get('errors', 0)} "
        f"history={len(history)})",
        (
            f"{'replica':>7} {'health':>9} {'role':>7} {'queue':>5} "
            f"{'slots':>7} "
            f"{'tok/s':>9} {'ttft_p50':>9} {'ttft_p95':>9} "
            f"{'accept':>7} {'hit':>6} {'hit d/h/k':>14} "
            f"{'pages f/r/a':>12} {'fetch/ship':>11} {'store h/m/w':>12} "
            f"{'pb d/r':>9} {'goodput':>9} {'weight':>7} {'phase':>13}"
        ),
    ]
    # Router weights keyed by replica (absent without a router).
    router_block = payload.get("router") or {}
    weights = {
        w.get("replica"): w.get("weight")
        for w in router_block.get("replicas") or []
    }
    for r in rows:
        # Tiered prefix cache: fraction of block probes each tier served
        # (device/host/disk) — "-" when the replica runs no tiers.
        th = r.get("prefix_tier_hit_rate") or {}
        tier_cell = (
            "{:.2f}/{:.2f}/{:.2f}".format(
                th.get("device", 0.0), th.get("host", 0.0),
                th.get("disk", 0.0),
            )
            if th
            else None
        )
        # Paged KV pool: free/resident/aliased pages — "-" on dense
        # replicas.
        kvp = r.get("kv_pages") or {}
        page_cell = (
            "{}/{}/{}".format(
                kvp.get("free", 0), kvp.get("resident", 0),
                kvp.get("aliased", 0),
            )
            if kvp
            else None
        )
        # Fleet KV plane: cross-replica fetches / ships — "-" on
        # fleets without the plane.
        kvf = r.get("kvfleet") or {}
        kvf_cell = (
            "{}/{}".format(kvf.get("fetches", 0), kvf.get("ships", 0))
            if kvf
            else None
        )
        # Persistent object-store tier: hits/misses/writes — "-" when
        # the replica runs without a store.
        kvs = r.get("kvstore") or {}
        kvs_cell = (
            "{}/{}/{}".format(
                kvs.get("hits", 0), kvs.get("misses", 0),
                kvs.get("writes", 0),
            )
            if kvs
            else None
        )
        # Fused dispatches: piggyback dispatches / chunk rows that rode
        # decode folds — "-" when piggybacking is off.
        pb = r.get("piggyback") or {}
        pb_cell = (
            "{}/{}".format(
                pb.get("dispatches", 0), pb.get("chunk_rows", 0)
            )
            if pb
            else None
        )
        # Anatomy hot spot: the replica's single largest p95 phase —
        # "-" when the phase ledger is off or idle.
        rph = r.get("phases") or {}
        phase_cell = (
            f"{rph['hot_phase']}"
            if rph.get("hot_phase")
            else None
        )
        out.append(
            f"{_fmt_cell(r.get('replica'), 7)} "
            f"{_fmt_cell(r.get('health'), 9)} "
            f"{_fmt_cell(r.get('role', 'mixed'), 7)} "
            f"{_fmt_cell(r.get('queue_depth'), 5)} "
            + _fmt_cell(
                f"{r.get('active_slots', 0)}/{r.get('num_slots', 0)}", 7
            )
            + f" {_fmt_cell(r.get('tokens_per_sec'), 9, 1)} "
            f"{_fmt_cell(r.get('ttft_p50_s'), 9, 4)} "
            f"{_fmt_cell(r.get('ttft_p95_s'), 9, 4)} "
            f"{_fmt_cell(r.get('spec_accept_rate'), 7, 2)} "
            f"{_fmt_cell(r.get('prefix_hit_rate'), 6, 2)} "
            f"{_fmt_cell(tier_cell, 14)} "
            f"{_fmt_cell(page_cell, 12)} "
            f"{_fmt_cell(kvf_cell, 11)} "
            f"{_fmt_cell(kvs_cell, 12)} "
            f"{_fmt_cell(pb_cell, 9)} "
            f"{_fmt_cell(r.get('goodput_tokens_per_device_s'), 9, 1)} "
            f"{_fmt_cell(weights.get(r.get('replica')), 7, 2)} "
            f"{_fmt_cell(phase_cell, 13)}"
        )
    if fleet:
        out.append(
            f"fleet: healthy={fleet.get('healthy', 0)}"
            f"/{fleet.get('replicas', 0)} "
            f"queue={fleet.get('queue_depth', 0)} "
            f"tok/s={fleet.get('tokens_per_sec', 0.0)} "
            f"goodput={fleet.get('goodput_tokens_per_device_s', 0.0)} "
            f"ttft_p95_worst={fleet.get('ttft_p95_s_worst')}"
        )
        # Anatomy decomposition: the fleet's hot phase (largest p95)
        # plus the per-phase p95 spread — only rendered once the phase
        # ledger has a window.
        fph = fleet.get("phases") or {}
        if fph.get("hot_phase"):
            spread = "  ".join(
                f"{p}={row['p95_s']:g}"
                for p, row in sorted(
                    (fph.get("by_phase") or {}).items(),
                    key=lambda kv: -kv[1]["p95_s"],
                )[:6]
            )
            out.append(
                f"phases: hot={fph['hot_phase']} "
                f"p95={fph['hot_phase_p95_s']:g}s  {spread}"
            )
        # Active SLO-breach attribution — the "where is the breach
        # coming from" line; absent while nothing is breaching.
        if fleet.get("breach_attribution"):
            out.append(f"why: {fleet['breach_attribution']}")
        # Fleet KV plane roll-up: only rendered once the plane moved
        # anything (a homogeneous isolated fleet stays clean).
        if fleet.get("kvfleet_fetches") or fleet.get("kvfleet_ships"):
            out.append(
                f"kvfleet: fetches={fleet.get('kvfleet_fetches', 0)} "
                f"timeouts={fleet.get('kvfleet_fetch_timeouts', 0)} "
                f"ships={fleet.get('kvfleet_ships', 0)}"
            )
        # Persistent store roll-up: only rendered once the store saw
        # traffic (a storeless fleet stays clean).
        if (fleet.get("kvstore_hits") or fleet.get("kvstore_misses")
                or fleet.get("kvstore_writes")):
            out.append(
                f"kvstore: hits={fleet.get('kvstore_hits', 0)} "
                f"misses={fleet.get('kvstore_misses', 0)} "
                f"writes={fleet.get('kvstore_writes', 0)} "
                f"write_errors={fleet.get('kvstore_write_errors', 0)} "
                f"evictions={fleet.get('kvstore_evictions', 0)}"
            )
    # Alert plane (when the watchtower is wired): firing count + names
    # worst-first — "all quiet" renders too, so the line's absence
    # means the watchtower is OFF, never that nothing is firing.
    alerts_block = payload.get("alerts")
    if alerts_block is not None:
        names = alerts_block.get("names") or []
        out.append(
            f"alerts: firing={alerts_block.get('firing', 0)}"
            + (" " + " ".join(names) if names else " (all quiet)")
        )
    # Recovery plane (when a FleetSupervisor is wired): one cell per
    # replica — state, lifetime restarts, pending attempts.
    sup = payload.get("supervisor") or []
    if sup:
        cells = []
        for s in sup:
            cell = f"r{s.get('replica')}={s.get('state')}"
            extras = []
            if s.get("restarts"):
                extras.append(f"restarts={s['restarts']}")
            if s.get("attempts"):
                extras.append(f"attempts={s['attempts']}")
            if extras:
                cell += "(" + ",".join(extras) + ")"
            cells.append(cell)
        out.append("supervisor: " + " ".join(cells))
    # Routing plane (when a Router is wired): decision totals + any
    # replicas currently excluded from the routable set.
    if router_block:
        parts = [
            f"routed={router_block.get('routed', 0)}",
            f"shed={router_block.get('shed', 0)}",
            f"affinity_entries={router_block.get('affinity_entries', 0)}",
        ]
        # Plan throughput: requests planned per µs of planning wall (the
        # control-plane speedometer) + the mean vectorized batch size.
        plan = router_block.get("plan") or {}
        if plan.get("requests"):
            parts.append(f"plan b/µs={plan.get('per_us', 0.0)}")
            parts.append(f"plan_batch={plan.get('mean_batch', 1.0)}")
        shards = (router_block.get("directory") or {}).get("shards")
        if shards and int(shards) > 1:
            parts.append(f"dir_shards={shards}")
        out_of_rotation = [
            f"r{w.get('replica')}"
            for w in router_block.get("replicas") or []
            if not w.get("routable", True)
        ]
        if out_of_rotation:
            parts.append("excluded=" + ",".join(out_of_rotation))
        out.append("router: " + " ".join(parts))
    return "\n".join(out)


def run_top(config: Dict[str, Any]) -> Dict[str, Any]:
    """``top``: live terminal dashboard over a serve fleet endpoint.

    Usage: ``rlt top <host:port>`` where ``<host:port>`` is the
    ``--serve.metrics_port`` endpoint (its ``/fleet`` route feeds the
    dashboard). On a tty it repaints every ``--top.interval_s`` (default
    2s) until Ctrl-C; piped (or with ``--top.plain true``) it prints
    one plain-text frame and exits, so ``rlt top addr | grep unhealthy``
    works in scripts. ``--top.iterations N`` bounds the refresh loop.
    ``--top.once`` forces exactly one frame regardless of tty, and
    ``--top.json`` prints the raw ``/fleet`` payload (the latest
    FleetSnapshot + history ring) as ONE JSON line instead of the
    rendered frame — the machine-readable form for scripts/CI
    (``rlt top addr --top.once --top.json | jq .latest.fleet``).
    Returns ``{"snapshot": <last /fleet payload>}``.
    """
    import json as _json
    import time as _time
    import urllib.request

    cfg = dict(config.pop("top", None) or {})
    addr = cfg.pop("addr", None) or cfg.pop("url", None)
    interval_s = float(cfg.pop("interval_s", 2.0))
    iterations = cfg.pop("iterations", None)
    plain = bool(cfg.pop("plain", False))
    once = bool(cfg.pop("once", False))
    json_out = bool(cfg.pop("json", False))
    timeout = float(cfg.pop("timeout_s", 10.0))
    if cfg:
        raise ValueError(f"unknown top options: {sorted(cfg)}")
    if not addr:
        raise ValueError(
            "top requires the serve obs endpoint: rlt top <host:port>"
        )
    base = str(addr) if "://" in str(addr) else f"http://{addr}"
    base = base.rstrip("/")
    plain = plain or json_out or not sys.stdout.isatty()
    if once:
        iterations = 1
    if iterations is None:
        iterations = 1 if plain else 0  # 0 = refresh until Ctrl-C
    iterations = int(iterations)
    count = 0
    last: Optional[Dict[str, Any]] = None
    try:
        while True:
            body = urllib.request.urlopen(
                base + "/fleet", timeout=timeout
            ).read()
            last = _json.loads(body)
            if json_out:
                # ONE machine-readable line per poll: the raw /fleet
                # payload (latest FleetSnapshot + history), no framing.
                print(_json.dumps(last, default=str))
                count += 1
                if iterations and count >= iterations:
                    break
                _time.sleep(interval_s)
                continue
            frame = render_fleet(last)
            if plain:
                print(frame)
            else:
                # Clear + home, one repaint per poll — a dumb-terminal
                # dashboard, no curses dependency.
                sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
                sys.stdout.flush()
            count += 1
            if iterations and count >= iterations:
                break
            _time.sleep(interval_s)
    except KeyboardInterrupt:
        pass
    return {"snapshot": last}


def run_why(config: Dict[str, Any]) -> Dict[str, Any]:
    """``why``: where one request's latency went — its phase ledger.

    Usage: ``rlt why <target> <request_id>`` where ``<target>`` is
    either a live serve obs endpoint (``host:port`` — the ledger is
    assembled from every process's tracer ring via ``/why?id=``, full
    cross-process timeline) or a captured journal JSONL path (offline
    autopsy — the outcome record's compact scheduler-local phases, no
    live fleet needed). Renders the timeline: per-phase durations, the
    replica/process each phase ran on, the outcome chain, and the
    coverage line (phases + unaccounted == observed, exactly).
    ``--why.json true`` prints the raw ledger as one JSON line instead.
    Exit status: 0 when the request was found, 1 when no ring/journal
    knows the id. Returns the ledger dict.
    """
    import json as _json
    import os as _os
    import urllib.error
    import urllib.request
    from urllib.parse import quote

    from ray_lightning_tpu.obs.anatomy import (
        ledger_from_phase_map,
        render_anatomy,
    )

    cfg = dict(config.pop("why", None) or {})
    target = (
        cfg.pop("target", None) or cfg.pop("addr", None)
        or cfg.pop("journal", None)
    )
    rid = cfg.pop("id", None) or cfg.pop("request_id", None)
    json_out = bool(cfg.pop("json", False))
    timeout = float(cfg.pop("timeout_s", 10.0))
    if cfg:
        raise ValueError(f"unknown why options: {sorted(cfg)}")
    if not target or rid is None:
        raise ValueError(
            "why requires a target and a request id: "
            "rlt why <host:port|journal.jsonl> <request_id>"
        )
    rid = str(rid)
    if _os.path.exists(str(target)):
        # Offline journal autopsy: the newest outcome record's compact
        # phase ledger (scheduler-local phases; no live fleet).
        from ray_lightning_tpu.obs.journal import load_journal

        entries = load_journal(str(target)).get("entries") or []
        outcome = next(
            (
                e for e in reversed(entries)
                if e.get("kind") == "outcome"
                and str(e.get("request_id")) == rid
            ),
            None,
        )
        if outcome is None:
            ledger: Dict[str, Any] = {"request_id": rid, "found": False}
        else:
            ledger = ledger_from_phase_map(
                rid, outcome.get("phases") or {},
                outcome=str(outcome.get("outcome", "unknown")),
            )
    else:
        base = (
            str(target) if "://" in str(target)
            else f"http://{target}"
        )
        url = base.rstrip("/") + "/why?id=" + quote(rid)
        try:
            body = urllib.request.urlopen(url, timeout=timeout).read()
        except urllib.error.HTTPError as exc:
            if exc.code != 404:
                raise
            body = exc.read()  # found:false rides the 404 body
        except urllib.error.URLError as exc:
            raise ValueError(
                f"why target {target!r} is neither a readable journal "
                f"file nor a reachable obs endpoint: {exc.reason}"
            ) from exc
        ledger = _json.loads(body)
    if json_out:
        print(_json.dumps(ledger, default=str))
    else:
        print(render_anatomy(ledger))
    return ledger


#: Unicode block ramp for the `rlt plot` sparkline (8 heights).
_SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def render_sparkline(
    points: List[Any], width: int = 60
) -> str:
    """One-line terminal sparkline over ``[(ts, value), ...]`` points.

    The window is resampled to ``width`` columns (last-value-wins per
    column, gaps rendered as spaces) and values are mapped onto the
    eight-block ramp between the window's min and max. A flat series
    renders as a run of the lowest block — still visibly "present".
    """
    vals = [float(v) for _, v in points]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = hi - lo
    n = len(vals)
    cols: List[str] = []
    if n <= width:
        take = vals
    else:
        # Downsample: each column shows the max of its slice (spikes
        # must survive resampling — that's what the plot is FOR).
        take = [
            max(vals[int(i * n / width): max(int(i * n / width) + 1,
                                             int((i + 1) * n / width))])
            for i in range(width)
        ]
    for v in take:
        idx = 0 if span <= 0 else int((v - lo) / span * 7.999)
        cols.append(_SPARK_BLOCKS[idx])
    return "".join(cols)


def run_plot(config: Dict[str, Any]) -> Dict[str, Any]:
    """``plot``: terminal sparkline of one retained watchtower series.

    Usage: ``rlt plot <host:port> <series>`` against a serve obs
    endpoint running the watchtower (``/query`` route). Renders the
    series name, the covered window, min/mean/max/last, and a unicode
    sparkline. Options (``--plot.*``): ``since_s`` (window, default the
    finest rung that has data), ``step_s`` (bucket width — picks the
    matching TSDB rung), ``width`` (sparkline columns, default 60),
    ``json`` (raw ``/query`` payload as one JSON line). Exit status:
    0 when the series exists, 1 for an unknown series (the 404 body's
    ``available`` sample is printed so you can fix the name).
    """
    import json as _json
    import urllib.error
    import urllib.request
    from urllib.parse import quote

    cfg = dict(config.pop("plot", None) or {})
    target = cfg.pop("addr", None) or cfg.pop("target", None)
    series = cfg.pop("series", None)
    since_s = cfg.pop("since_s", None)
    step_s = cfg.pop("step_s", None)
    width = int(cfg.pop("width", 60))
    json_out = bool(cfg.pop("json", False))
    timeout = float(cfg.pop("timeout_s", 10.0))
    if cfg:
        raise ValueError(f"unknown plot options: {sorted(cfg)}")
    if not target or not series:
        raise ValueError(
            "plot requires a target and a series name: "
            "rlt plot <host:port> <series>"
        )
    base = str(target) if "://" in str(target) else f"http://{target}"
    url = base.rstrip("/") + "/query?series=" + quote(str(series))
    if since_s is not None:
        url += f"&since={float(since_s)}"
    if step_s is not None:
        url += f"&step={float(step_s)}"
    try:
        body = urllib.request.urlopen(url, timeout=timeout).read()
    except urllib.error.HTTPError as exc:
        if exc.code != 404:
            raise
        body = exc.read()  # found:false + available sample ride the 404
    except urllib.error.URLError as exc:
        raise ValueError(
            f"plot target {target!r} is not a reachable obs endpoint "
            f"(needs --serve.metrics_port + watchtower): {exc.reason}"
        ) from exc
    result = _json.loads(body)
    if json_out:
        print(_json.dumps(result, default=str))
        return result
    if not result.get("found"):
        available = result.get("available") or []
        print(f"series {series!r} unknown")
        if available:
            print("available: " + " ".join(available))
        return result
    points = result.get("points") or []
    vals = [float(v) for _, v in points]
    header = f"{series}  step={result.get('step_s')}s  n={len(points)}"
    if vals:
        header += (
            f"  min={min(vals):.4g} mean={sum(vals) / len(vals):.4g}"
            f" max={max(vals):.4g} last={vals[-1]:.4g}"
        )
    print(header)
    print(render_sparkline(points, width=width) or "(no samples)")
    return result


def render_alerts(payload: Dict[str, Any]) -> str:
    """Human rendering of the ``/alerts`` payload: one row per rule
    (state, severity, value vs threshold, firing duration), firing
    rules first, then the canary line when the lane is running."""
    alerts = payload.get("alerts") or {}
    states: Dict[str, Any] = alerts.get("states") or {}
    rules = {r["name"]: r for r in alerts.get("rules") or []}
    out: List[str] = []
    firing = alerts.get("firing") or []
    firing_names = [
        f.get("rule", "?") if isinstance(f, dict) else str(f)
        for f in firing
    ]
    out.append(
        f"alerts: firing={len(firing)}"
        + ((" " + " ".join(firing_names)) if firing_names else
           " (all quiet)")
    )
    order = sorted(
        states,
        key=lambda nm: (states[nm].get("state") != "firing", nm),
    )
    for nm in order:
        st = states[nm]
        rule = rules.get(nm, {})
        line = (
            f"  {st.get('state', '?'):>7}  {nm}"
            f" [{rule.get('severity', '?')}/{rule.get('kind', '?')}]"
        )
        if st.get("value") is not None:
            line += f" value={st['value']:.4g}"
        if st.get("detail"):
            line += f" ({st['detail']})"
        out.append(line)
    canary = payload.get("canary")
    if canary:
        last = canary.get("last") or {}
        out.append(
            "canary: probes={} exact={} ttft_s={} decode_tok_s={}".format(
                canary.get("probes", 0),
                last.get("exact", "n/a"),
                last.get("ttft_s", "n/a"),
                last.get("decode_tokens_per_s", "n/a"),
            )
        )
    return "\n".join(out)


def run_alerts(config: Dict[str, Any]) -> Dict[str, Any]:
    """``alerts``: the watchtower's alert state — and a live tail.

    Usage: ``rlt alerts <host:port>`` against a serve obs endpoint
    running the watchtower. One-shot mode renders every rule's state
    (firing first), values/details, and the canary lane summary.
    ``--follow`` (or ``--alerts.follow true``) switches to a live tail
    of ``alert_firing``/``alert_resolved``/``canary_*`` events via the
    ``/events?since=<seq>`` cursor — each poll fetches only events
    newer than the last seen sequence (deduped per replica ring, since
    sequences are per-ring monotonic, not global). Options:
    ``interval_s`` (follow poll period, default 2), ``iterations``
    (stop after N polls; 0 = forever), ``json`` (raw payload / JSONL
    passthrough). Exit status: 0 quiet, 1 when any rule is firing.
    """
    import json as _json
    import time as _time
    import urllib.error
    import urllib.request

    cfg = dict(config.pop("alerts", None) or {})
    target = cfg.pop("addr", None) or cfg.pop("target", None)
    follow = bool(cfg.pop("follow", False))
    interval_s = float(cfg.pop("interval_s", 2.0))
    iterations = int(cfg.pop("iterations", 0))
    json_out = bool(cfg.pop("json", False))
    timeout = float(cfg.pop("timeout_s", 10.0))
    if cfg:
        raise ValueError(f"unknown alerts options: {sorted(cfg)}")
    if not target:
        raise ValueError(
            "alerts requires a target: rlt alerts <host:port> [--follow]"
        )
    base = str(target) if "://" in str(target) else f"http://{target}"

    def _fetch_payload() -> Dict[str, Any]:
        url = base.rstrip("/") + "/alerts"
        try:
            body = urllib.request.urlopen(url, timeout=timeout).read()
        except urllib.error.URLError as exc:
            raise ValueError(
                f"alerts target {target!r} is not a reachable obs "
                f"endpoint (needs --serve.metrics_port + watchtower): "
                f"{getattr(exc, 'reason', exc)}"
            ) from exc
        return _json.loads(body)

    payload = _fetch_payload()
    if not follow:
        if json_out:
            print(_json.dumps(payload, default=str))
        else:
            print(render_alerts(payload))
        return payload

    # Live tail: poll /events with the ?since= cursor. Sequences are
    # per-RING monotonic (each replica's EventLog counts its own), so
    # the cursor is kept per (replica, ) origin via a seen-set keyed on
    # (replica, seq) with the max seq per origin driving ?since= — one
    # shared cursor at the MIN of the per-origin maxima would refetch,
    # so dedup client-side and advance since only when safe (single
    # origin: plain max).
    seen: set = set()
    cursor = 0
    count = 0
    try:
        while True:
            url = base.rstrip("/") + (
                "/events?subsystem=watchtower&since=" + str(cursor)
            )
            try:
                body = urllib.request.urlopen(url, timeout=timeout).read()
            except urllib.error.URLError:
                body = b""
            new_max = cursor
            for ln in body.decode().splitlines():
                if not ln.strip():
                    continue
                try:
                    ev = _json.loads(ln)
                except ValueError:
                    continue
                key = (ev.get("replica"), ev.get("seq"))
                if key in seen:
                    continue
                seen.add(key)
                if isinstance(ev.get("seq"), int):
                    new_max = max(new_max, ev["seq"])
                if json_out:
                    print(_json.dumps(ev, default=str))
                else:
                    print(
                        "{} {:>5} {} {}".format(
                            _time.strftime(
                                "%H:%M:%S",
                                _time.localtime(float(ev.get("ts", 0))),
                            ),
                            ev.get("level", "?"),
                            ev.get("name", "?"),
                            " ".join(
                                f"{k}={v}" for k, v in sorted(ev.items())
                                if k not in (
                                    "ts", "level", "subsystem", "name",
                                    "seq",
                                )
                            ),
                        )
                    )
                sys.stdout.flush()
            cursor = new_max
            count += 1
            if iterations and count >= iterations:
                break
            _time.sleep(interval_s)
    except KeyboardInterrupt:
        pass
    payload = _fetch_payload()
    return payload


def run_tokenize(config: Dict[str, Any]) -> Dict[str, Any]:
    """``tokenize``: train (or load) a ByteBPETokenizer and optionally
    encode the corpus into a pretraining shard.

    Config section (YAML ``tokenize:`` or ``--tokenize.*`` flags):
      input: text file path or list of paths (each non-empty LINE is one
        document — merges never span documents)
      vocab_size: target vocab (default 512)
      out: tokenizer JSON path (default tokenizer.json)
      tokenizer: existing tokenizer JSON to reuse instead of training
      encode_to: token-bin shard path; when set, the corpus is encoded
        and written for TokenBinDataset
    Prints one JSON summary line on stdout.
    """
    import json as _json

    from ray_lightning_tpu.tokenizer import ByteBPETokenizer

    cfg = dict(config.get("tokenize") or {})
    inputs = cfg.get("input")
    if isinstance(inputs, str):
        inputs = [inputs]
    if not inputs:
        raise ValueError("tokenize needs tokenize.input (text file path[s])")
    docs: List[str] = []
    for path in inputs:
        with open(path, "r", encoding="utf-8") as f:
            docs.extend(line for line in (ln.strip("\n") for ln in f) if line)
    if not docs:
        raise ValueError(f"no non-empty lines in {inputs}")

    existing = cfg.get("tokenizer")
    if existing:
        tok = ByteBPETokenizer.load(str(existing))
    else:
        tok = ByteBPETokenizer.train(docs, vocab_size=int(cfg.get("vocab_size", 512)))
    out_path = str(cfg.get("out", "tokenizer.json"))
    if not existing:
        tok.save(out_path)

    summary: Dict[str, Any] = {
        "vocab_size": tok.vocab_size,
        "documents": len(docs),
        "tokenizer": str(existing) if existing else out_path,
    }
    encode_to = cfg.get("encode_to")
    if encode_to:
        from ray_lightning_tpu.trainer.data import write_token_bin

        ids = tok.encode_corpus(docs)
        shard = write_token_bin(str(encode_to), ids)
        summary["shard"] = shard
        summary["n_tokens"] = int(ids.size)
        summary["bytes_per_token"] = round(
            sum(len(d.encode()) for d in docs) / max(1, ids.size), 3
        )
    print(_json.dumps(summary))
    return summary


def main(argv: Optional[List[str]] = None) -> Any:
    subcommand, config = parse_args(argv)
    fabric_cfg = config.pop("fabric", None) or {}
    if fabric_cfg:
        from ray_lightning_tpu import fabric

        fabric.init(**fabric_cfg)
    if subcommand == "tokenize":
        return run_tokenize(config)
    if subcommand == "convert-hf":
        return run_convert_hf(config)
    if subcommand == "generate":
        return run_generate(config)
    if subcommand == "serve":
        return run_serve(config)
    if subcommand == "doctor":
        return run_doctor(config)
    if subcommand == "top":
        return run_top(config)
    if subcommand == "replay":
        return run_replay(config)
    if subcommand == "why":
        return run_why(config)
    if subcommand == "plot":
        return run_plot(config)
    if subcommand == "alerts":
        return run_alerts(config)
    trainer, model, datamodule = build(config)
    fn = getattr(trainer, subcommand)
    if datamodule is not None:
        return fn(model, datamodule=datamodule)
    return fn(model)


def cli_entry(argv: Optional[List[str]] = None) -> Any:
    """Actual command-line entrypoint (console script / ``python -m``).

    Re-applies ``JAX_PLATFORMS`` over any sitecustomize-forced plugin
    platform config — on the command line the env var IS the user's
    intent. Programmatic callers use :func:`main`, which never clobbers
    an application's own ``jax.config`` pins.
    """
    from ray_lightning_tpu.utils.platform import apply_jax_platform_env

    apply_jax_platform_env()
    out = main(argv)
    args = sys.argv[1:] if argv is None else argv
    if args and args[0] == "doctor":
        # The EXIT STATUS is doctor's contract (scriptable health
        # probe): 0 healthy, 1 unhealthy.
        return 0 if out.get("status") == 200 else 1
    if args and args[0] == "replay":
        # Replay's contract mirrors doctor: 0 bit-exact, 1 diverged —
        # `rlt replay journal.jsonl && deploy` is the regression gate.
        return 0 if out.get("exact") else 1
    if args and args[0] == "why":
        # 0 when some ring/journal knew the request, 1 when nothing did.
        return 0 if out.get("found") else 1
    if args and args[0] == "plot":
        # 0 when the series exists in the TSDB, 1 for an unknown name.
        return 0 if out.get("found") else 1
    if args and args[0] == "alerts":
        # 0 all quiet, 1 when any rule is firing — `rlt alerts $ADDR
        # && deploy` gates a rollout on the watchtower's verdict.
        firing = (out.get("alerts") or {}).get("firing") or []
        return 1 if firing else 0
    # The console wrapper sys.exit()s our return value; any other
    # command's result dict is already on stdout, and a truthy
    # sys.exit(dict) would dump it to stderr and exit 1 — a successful
    # `rlt serve` must exit 0.
    return 0


if __name__ == "__main__":
    # Mirror the console-script wrapper (which sys.exit()s the return
    # value): `python -m ray_lightning_tpu.cli doctor|replay ...` must
    # carry the same exit-status contract as `rlt doctor|replay`.
    sys.exit(cli_entry())
