"""Byte-level BPE tokenizer — the native data-layer front end.

The reference ecosystem gets tokenization from Hugging Face's Rust
tokenizers; this build carries its own byte-level BPE with the hot loops
in C++ (csrc/rltnative.cpp, bound GIL-free via ctypes — the same native
data path that does batch assembly) and a pure-Python fallback that is
bit-identical by a shared determinism contract: each training round
merges the most frequent adjacent pair, ties broken by the smallest
(left, right) pair; encoding applies merges greedily in rank order
(GPT-2 style).

Byte-level means no out-of-vocabulary inputs, ever: ids 0..255 are raw
bytes, 256+r is merge rank r. Pairs with ``TokenBinDataset`` /
``write_token_bin`` for the corpus -> shard -> GPT/BERT pretraining
pipeline (uint16 shards hold vocabs up to 65,536).
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, Iterable, List, Sequence, Tuple, Union

import numpy as np

Text = Union[str, bytes]


def _to_bytes(text: Text) -> bytes:
    return text.encode("utf-8") if isinstance(text, str) else bytes(text)


def _train_python(
    corpus: np.ndarray, n_merges: int, sep: int = -1
) -> np.ndarray:
    """Reference trainer (fallback + the contract the C++ must match)."""
    ids = corpus.astype(np.int32).tolist()
    merges: List[Tuple[int, int]] = []
    for r in range(n_merges):
        counts: Dict[Tuple[int, int], int] = {}
        for pair in zip(ids, ids[1:]):
            if pair[0] == sep or pair[1] == sep:
                continue
            counts[pair] = counts.get(pair, 0) + 1
        best = None
        for pair, c in counts.items():
            if c < 2:
                continue
            if best is None or c > best[1] or (c == best[1] and pair < best[0]):
                best = (pair, c)
        if best is None:
            break
        (left, right), _ = best
        merges.append((left, right))
        new_id = 256 + r
        out: List[int] = []
        i = 0
        while i < len(ids):
            if i + 1 < len(ids) and ids[i] == left and ids[i + 1] == right:
                out.append(new_id)
                i += 2
            else:
                out.append(ids[i])
                i += 1
        ids = out
    return np.asarray(merges, dtype=np.int32).reshape(-1, 2)


def _encode_python(text: np.ndarray, merges: np.ndarray) -> np.ndarray:
    rank = {(int(l), int(r)): i for i, (l, r) in enumerate(merges)}
    ids = text.astype(np.int32).tolist()
    n_merges = len(merges)
    while len(ids) >= 2:
        best = n_merges
        for pair in zip(ids, ids[1:]):
            got = rank.get((int(pair[0]), int(pair[1])), n_merges)
            if got < best:
                best = got
        if best == n_merges:
            break
        left, right = (int(x) for x in merges[best])
        new_id = 256 + best
        out: List[int] = []
        i = 0
        while i < len(ids):
            if i + 1 < len(ids) and ids[i] == left and ids[i + 1] == right:
                out.append(new_id)
                i += 2
            else:
                out.append(ids[i])
                i += 1
        ids = out
    return np.asarray(ids, dtype=np.int32)


class ByteBPETokenizer:
    """Trained byte-level BPE: ``encode``/``decode`` + JSON persistence.

    ``vocab_size`` = 256 + number of merges. ``train`` learns merges from
    raw text (native C++ trainer when available); both directions have
    no unknown-token failure mode — any byte sequence round-trips.
    """

    def __init__(self, merges: Any = ()) -> None:
        self.merges = np.asarray(merges, dtype=np.int32).reshape(-1, 2)
        # Expand each token id to its byte sequence once (decode table),
        # validating ranges as we go: rank r may only reference earlier
        # ids (negative ids would silently mis-index the table, and a
        # merge touching byte 0 would break encode_corpus's
        # separator-strip invariant — the trainer never emits either,
        # but hand-edited/corrupt JSON must not load quietly).
        table: List[bytes] = [bytes([b]) for b in range(256)]
        for r, (left, right) in enumerate(self.merges):
            for tid in (int(left), int(right)):
                if not 0 <= tid < 256 + r:
                    raise ValueError(
                        f"merge {r} references id {tid}, outside "
                        f"[0, {256 + r})"
                    )
                if tid == 0:
                    raise ValueError(
                        f"merge {r} touches byte 0 (the document "
                        "separator); not a trainer-produced vocab"
                    )
            table.append(table[int(left)] + table[int(right)])
        self._bytes_table = table

    # -- construction ---------------------------------------------------
    @classmethod
    def train(
        cls, texts: Union[Text, Iterable[Text]], vocab_size: int = 512
    ) -> "ByteBPETokenizer":
        """Learn ``vocab_size - 256`` merges from text(s).

        Documents are joined with a 0x00 separator, and the trainer
        excludes every pair touching it — merges can never span a
        document boundary (binary corpora embedding real NULs simply
        learn no merges across them).
        """
        if vocab_size < 256:
            raise ValueError(f"vocab_size must be >= 256, got {vocab_size}")
        if isinstance(texts, (str, bytes)):
            texts = [texts]
        corpus = np.frombuffer(
            b"\x00".join(_to_bytes(t) for t in texts), dtype=np.uint8
        )
        n_merges = vocab_size - 256
        merges = _dispatch_train(corpus, n_merges, sep=0)
        return cls(merges)

    @classmethod
    def load(cls, path: str) -> "ByteBPETokenizer":
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
        if data.get("type") != "byte_bpe":
            raise ValueError(f"{path} is not a byte_bpe tokenizer file")
        return cls(data["merges"])

    def save(self, path: str) -> str:
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(
                {"type": "byte_bpe", "merges": self.merges.tolist()}, f
            )
        os.replace(tmp, path)
        return path

    # -- use ------------------------------------------------------------
    @property
    def vocab_size(self) -> int:
        return 256 + len(self.merges)

    def encode(self, text: Text) -> np.ndarray:
        data = np.frombuffer(_to_bytes(text), dtype=np.uint8)
        if not len(data):
            return np.empty(0, dtype=np.int32)
        return _dispatch_encode(data, self.merges)

    def encode_corpus(self, texts: Iterable[Text]) -> np.ndarray:
        """Concatenated ids over documents — the ``write_token_bin``
        input for pretraining shards.

        One encode call over the 0x00-joined corpus instead of one per
        document: the trainer never learns a merge touching the
        separator, so no merge can match across a boundary and stripping
        the separator ids reproduces the per-document encoding exactly —
        while the merge-rank table is built once, not per document.
        (Documents that themselves contain NUL bytes fall back to the
        per-document path, where their NULs encode as ordinary id-0
        tokens.)
        """
        docs = [_to_bytes(t) for t in texts]
        if not docs:
            return np.empty(0, dtype=np.int32)
        if any(b"\x00" in d for d in docs):
            return np.concatenate([self.encode(d) for d in docs])
        joined = np.frombuffer(b"\x00".join(docs), dtype=np.uint8)
        ids = _dispatch_encode(joined, self.merges)
        return ids[ids != 0]

    def decode(self, ids: Sequence[int]) -> str:
        return self.decode_bytes(ids).decode("utf-8", errors="replace")

    def decode_bytes(self, ids: Sequence[int]) -> bytes:
        table = self._bytes_table
        n = len(table)
        out = []
        for i in np.asarray(ids, dtype=np.int64).ravel():
            if not 0 <= i < n:
                raise ValueError(f"token id {int(i)} out of range [0, {n})")
            out.append(table[int(i)])
        return b"".join(out)


def _dispatch_train(
    corpus: np.ndarray, n_merges: int, sep: int = -1
) -> np.ndarray:
    from ray_lightning_tpu.utils import native

    if native.native_available():
        return native.bpe_train(corpus, n_merges, sep=sep)
    return _train_python(corpus, n_merges, sep=sep)


def _dispatch_encode(data: np.ndarray, merges: np.ndarray) -> np.ndarray:
    from ray_lightning_tpu.utils import native

    if native.native_available():
        return native.bpe_encode(data, merges)
    return _encode_python(data, merges)
