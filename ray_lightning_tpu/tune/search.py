"""Search-space primitives and samplers for the tuner.

The reference delegates search to ray.tune (grid_search/choice/uniform in
examples, e.g. examples/ray_ddp_tune.py); these are from-scratch
equivalents sufficient for the same example/test surface.
"""
from __future__ import annotations

import itertools
import math
import random
from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Sequence


@dataclass(frozen=True)
class GridSearch:
    values: Sequence[Any]


@dataclass(frozen=True)
class Choice:
    values: Sequence[Any]


@dataclass(frozen=True)
class Uniform:
    low: float
    high: float


@dataclass(frozen=True)
class LogUniform:
    low: float
    high: float


def grid_search(values: Sequence[Any]) -> GridSearch:
    return GridSearch(tuple(values))


def choice(values: Sequence[Any]) -> Choice:
    return Choice(tuple(values))


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def generate_configs(
    param_space: Dict[str, Any], num_samples: int = 1, seed: int = 0
) -> List[Dict[str, Any]]:
    """Expand the space: full cross-product of grid axes x num_samples draws
    of stochastic axes (ray.tune semantics)."""
    rng = random.Random(seed)
    grid_keys = [k for k, v in param_space.items() if isinstance(v, GridSearch)]
    grid_values = [list(param_space[k].values) for k in grid_keys]
    combos = list(itertools.product(*grid_values)) if grid_keys else [()]

    def sample_stochastic() -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for key, v in param_space.items():
            if isinstance(v, GridSearch):
                continue
            if isinstance(v, Choice):
                out[key] = rng.choice(list(v.values))
            elif isinstance(v, Uniform):
                out[key] = rng.uniform(v.low, v.high)
            elif isinstance(v, LogUniform):
                out[key] = math.exp(
                    rng.uniform(math.log(v.low), math.log(v.high))
                )
            else:
                out[key] = v  # constant
        return out

    configs: List[Dict[str, Any]] = []
    for _ in range(max(1, num_samples)):
        for combo in combos:
            cfg = sample_stochastic()
            cfg.update(dict(zip(grid_keys, combo)))
            configs.append(cfg)
    return configs
