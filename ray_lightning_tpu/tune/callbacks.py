"""Tune report/checkpoint callbacks.

Parity targets (/root/reference/ray_lightning/tune.py):
- ``TuneReportCallback`` (:58-134): rank-0 only, ships a ``tune.report``
  closure through the worker->driver queue at a chosen hook.
- ``_TuneCheckpointCallback`` (:136-178): dumps the full checkpoint to
  bytes in the worker, writes it driver-side under the trial dir via fsspec.
- ``TuneReportCheckpointCallback`` (:180-236): composition of both.

TPU-shaped details: metrics are already host floats at hook time (the loop
fetches them at epoch boundaries), so shipping them costs no extra device
sync; checkpoint bytes are the state-stream format.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Union

from ray_lightning_tpu.trainer.callbacks import Callback
from ray_lightning_tpu.tune import session as tune_session


def _resolve_metrics(
    metrics: Union[None, str, List[str], Dict[str, str]],
    available: Dict[str, float],
) -> Dict[str, float]:
    if metrics is None:
        return dict(available)
    if isinstance(metrics, str):
        metrics = [metrics]
    if isinstance(metrics, list):
        return {m: available[m] for m in metrics if m in available}
    return {new: available[old] for new, old in metrics.items() if old in available}


class TuneCallback(Callback):
    """Base: fires on a configured hook, rank 0 only."""

    def __init__(self, on: str = "validation_end") -> None:
        valid = ("validation_end", "train_epoch_end", "fit_end")
        if on not in valid:
            raise ValueError(f"on must be one of {valid}")
        self._on = on

    def on_validation_end(self, trainer: Any, module: Any) -> None:
        if self._on == "validation_end":
            self._maybe_handle(trainer, module)

    def on_train_epoch_end(self, trainer: Any, module: Any) -> None:
        if self._on == "train_epoch_end":
            self._maybe_handle(trainer, module)

    def on_fit_end(self, trainer: Any, module: Any) -> None:
        if self._on == "fit_end":
            self._maybe_handle(trainer, module)

    #: Subclasses that snapshot ``trainer.checkpoint_state()`` set this so
    #: the (collective) state gathers run on EVERY rank before the rank
    #: gate — a rank-0-only gather deadlocks under multi-process sharding.
    needs_checkpoint_state = False

    def _maybe_handle(self, trainer: Any, module: Any) -> None:
        if getattr(trainer, "sanity_checking", False):
            # Skip the pre-train sanity check (reference tune.py:113-114).
            return
        gather = self.needs_checkpoint_state and (
            trainer.global_rank == 0
            or getattr(trainer, "gather_is_collective", False)
        )
        self._gathered_state = trainer.checkpoint_state() if gather else None
        try:
            if trainer.global_rank != 0:
                return
            self._handle(trainer, module)
        finally:
            # Don't pin a full host copy of params+opt_state between hooks.
            self._gathered_state = None

    def _handle(self, trainer: Any, module: Any) -> None:
        raise NotImplementedError


class TuneReportCallback(TuneCallback):
    """Ship current metrics to the tuner at the configured hook."""

    def __init__(
        self,
        metrics: Union[None, str, List[str], Dict[str, str]] = None,
        on: str = "validation_end",
    ) -> None:
        super().__init__(on=on)
        self._metrics = metrics

    def _handle(self, trainer: Any, module: Any) -> None:
        report = _resolve_metrics(self._metrics, dict(trainer.callback_metrics))
        if not report:
            return
        # Closure crosses the worker->driver queue and runs in the trial
        # driver (reference tune.py:130-134 pattern), or runs directly for
        # in-process fits.
        _dispatch(lambda: tune_session.report(metrics=report))


def _dispatch(closure: Any) -> None:
    """Run ``closure`` in the trial driver: via the worker queue when inside
    a launched worker, directly when the fit is in-process in the trial."""
    worker_session = tune_session.get_session()
    if worker_session is not None and worker_session.queue is not None:
        worker_session.put_queue(closure)
    elif tune_session.get_trial_session() is not None:
        closure()


def _checkpoint_closure(stream: bytes, step: int, filename: str):
    """Build the trial-driver-side closure that writes checkpoint bytes under
    the trial dir (single source of truth for the checkpoint layout)."""

    def write_checkpoint() -> str:
        from ray_lightning_tpu.utils.state_stream import state_stream_to_file

        trial_dir = tune_session.get_trial_dir() or "."
        ckpt_dir = os.path.join(trial_dir, f"checkpoint_{step:06d}")
        os.makedirs(ckpt_dir, exist_ok=True)
        path = os.path.join(ckpt_dir, filename)
        state_stream_to_file(stream, path)
        return path

    return write_checkpoint


class _TuneCheckpointCallback(TuneCallback):
    """Dump a full checkpoint and deliver it into the trial dir."""

    needs_checkpoint_state = True

    def __init__(self, filename: str = "checkpoint.ckpt", on: str = "validation_end") -> None:
        super().__init__(on=on)
        self._filename = filename

    def _handle(self, trainer: Any, module: Any) -> None:
        from ray_lightning_tpu.utils.state_stream import to_state_stream

        stream = to_state_stream(self._gathered_state)
        _dispatch(_checkpoint_closure(stream, trainer.global_step, self._filename))


class TuneReportCheckpointCallback(TuneCallback):
    """Checkpoint then report, as one atomic hook (reference tune.py:180-236
    notes checkpointing must precede the report)."""

    def __init__(
        self,
        metrics: Union[None, str, List[str], Dict[str, str]] = None,
        filename: str = "checkpoint.ckpt",
        on: str = "validation_end",
    ) -> None:
        super().__init__(on=on)
        self._metrics = metrics
        self._filename = filename

    needs_checkpoint_state = True

    def _handle(self, trainer: Any, module: Any) -> None:
        report = _resolve_metrics(self._metrics, dict(trainer.callback_metrics))
        from ray_lightning_tpu.utils.state_stream import to_state_stream

        stream = to_state_stream(self._gathered_state)
        write_checkpoint = _checkpoint_closure(
            stream, trainer.global_step, self._filename
        )

        def checkpoint_and_report() -> None:
            path = write_checkpoint()
            if report:
                tune_session.report(metrics=report, checkpoint_path=path)

        _dispatch(checkpoint_and_report)
