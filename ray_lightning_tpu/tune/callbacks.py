"""Tune report/checkpoint callbacks.

Parity targets (/root/reference/ray_lightning/tune.py):
- ``TuneReportCallback`` (:58-134): rank-0 only, ships a ``tune.report``
  closure through the worker->driver queue at a chosen hook.
- ``_TuneCheckpointCallback`` (:136-178): dumps the full checkpoint to
  bytes in the worker, writes it driver-side under the trial dir via fsspec.
- ``TuneReportCheckpointCallback`` (:180-236): composition of both.

TPU-shaped details: metrics are already host floats at hook time (the loop
fetches them at epoch boundaries), so shipping them costs no extra device
sync; checkpoint bytes are the state-stream format.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Union

from ray_lightning_tpu.trainer.callbacks import Callback
from ray_lightning_tpu.tune import session as tune_session


def _resolve_metrics(
    metrics: Union[None, str, List[str], Dict[str, str]],
    available: Dict[str, float],
) -> Dict[str, float]:
    if metrics is None:
        return dict(available)
    if isinstance(metrics, str):
        metrics = [metrics]
    if isinstance(metrics, list):
        return {m: available[m] for m in metrics if m in available}
    return {new: available[old] for new, old in metrics.items() if old in available}


#: Reference-contract aliases (tune.py:104 accepts the short PTL-style
#: hook names) -> this trainer's hook vocabulary.
_HOOK_ALIASES = {
    "batch_end": "train_batch_end",
    "epoch_end": "train_epoch_end",
    "train_end": "fit_end",
}

_VALID_HOOKS = (
    "fit_start",
    "train_epoch_start",
    "train_batch_end",
    "train_epoch_end",
    "validation_end",
    "fit_end",
)


class TuneCallback(Callback):
    """Base: fires on the configured hook(s), rank 0 only.

    ``on`` is a trainer event name or a LIST of them (reference contract,
    tune.py:104): any of ``fit_start``, ``train_epoch_start``,
    ``train_batch_end`` (alias ``batch_end``), ``train_epoch_end`` (alias
    ``epoch_end``), ``validation_end``, ``fit_end`` (alias ``train_end``);
    an ``on_`` prefix is tolerated.
    """

    def __init__(self, on: Union[str, List[str]] = "validation_end") -> None:
        hooks = [on] if isinstance(on, str) else list(on)
        if not hooks:
            raise ValueError("on must name at least one trainer event")
        canon = []
        for h in hooks:
            name = h[3:] if isinstance(h, str) and h.startswith("on_") else h
            name = _HOOK_ALIASES.get(name, name)
            if name not in _VALID_HOOKS:
                raise ValueError(
                    f"on={h!r} must be one of {_VALID_HOOKS} (aliases "
                    f"{tuple(_HOOK_ALIASES)})"
                )
            canon.append(name)
        self._on = tuple(canon)

    def _fire(self, hook: str, trainer: Any, module: Any) -> None:
        if hook in self._on:
            self._maybe_handle(trainer, module)

    def on_fit_start(self, trainer: Any, module: Any) -> None:
        self._fire("fit_start", trainer, module)

    def on_train_epoch_start(self, trainer: Any, module: Any) -> None:
        self._fire("train_epoch_start", trainer, module)

    #: Live logs of the batch that just ended (host floats), set only for
    #: the duration of a train_batch_end firing: callback_metrics updates
    #: at epoch boundaries, so per-batch reports resolve against these.
    _batch_logs: Optional[Dict[str, float]] = None

    def on_train_batch_end(
        self, trainer: Any, module: Any, logs: Any = None, *args: Any,
        **kwargs: Any,
    ) -> None:
        self._batch_logs = dict(logs or {})
        try:
            self._fire("train_batch_end", trainer, module)
        finally:
            self._batch_logs = None

    def _available_metrics(self, trainer: Any) -> Dict[str, float]:
        out = dict(trainer.callback_metrics)
        if self._batch_logs:
            out.update(self._batch_logs)
        return out

    def on_validation_end(self, trainer: Any, module: Any) -> None:
        self._fire("validation_end", trainer, module)

    def on_train_epoch_end(self, trainer: Any, module: Any) -> None:
        self._fire("train_epoch_end", trainer, module)

    def on_fit_end(self, trainer: Any, module: Any) -> None:
        self._fire("fit_end", trainer, module)

    #: Subclasses that snapshot ``trainer.checkpoint_state()`` set this so
    #: the (collective) state gathers run on EVERY rank before the rank
    #: gate — a rank-0-only gather deadlocks under multi-process sharding.
    needs_checkpoint_state = False

    def _maybe_handle(self, trainer: Any, module: Any) -> None:
        if getattr(trainer, "sanity_checking", False):
            # Skip the pre-train sanity check (reference tune.py:113-114).
            return
        gather = self.needs_checkpoint_state and (
            trainer.global_rank == 0
            or getattr(trainer, "gather_is_collective", False)
        )
        self._gathered_state = trainer.checkpoint_state() if gather else None
        try:
            if trainer.global_rank != 0:
                return
            self._handle(trainer, module)
        finally:
            # Don't pin a full host copy of params+opt_state between hooks.
            self._gathered_state = None

    def _handle(self, trainer: Any, module: Any) -> None:
        raise NotImplementedError


class TuneReportCallback(TuneCallback):
    """Ship current metrics to the tuner at the configured hook."""

    def __init__(
        self,
        metrics: Union[None, str, List[str], Dict[str, str]] = None,
        on: Union[str, List[str]] = "validation_end",
    ) -> None:
        super().__init__(on=on)
        self._metrics = metrics

    def _handle(self, trainer: Any, module: Any) -> None:
        report = _resolve_metrics(self._metrics, self._available_metrics(trainer))
        if not report:
            return
        # Closure crosses the worker->driver queue and runs in the trial
        # driver (reference tune.py:130-134 pattern), or runs directly for
        # in-process fits.
        _dispatch(lambda: tune_session.report(metrics=report))


def _dispatch(closure: Any) -> None:
    """Run ``closure`` in the trial driver: via the worker queue when inside
    a launched worker, directly when the fit is in-process in the trial."""
    worker_session = tune_session.get_session()
    if worker_session is not None and worker_session.queue is not None:
        worker_session.put_queue(closure)
    elif tune_session.get_trial_session() is not None:
        closure()


def _checkpoint_closure(stream: bytes, step: int, filename: str):
    """Build the trial-driver-side closure that writes checkpoint bytes under
    the trial dir (single source of truth for the checkpoint layout)."""

    def write_checkpoint() -> str:
        from ray_lightning_tpu.utils.state_stream import state_stream_to_file

        trial_dir = tune_session.get_trial_dir() or "."
        ckpt_dir = os.path.join(trial_dir, f"checkpoint_{step:06d}")
        os.makedirs(ckpt_dir, exist_ok=True)
        path = os.path.join(ckpt_dir, filename)
        state_stream_to_file(stream, path)
        return path

    return write_checkpoint


class _TuneCheckpointCallback(TuneCallback):
    """Dump a full checkpoint and deliver it into the trial dir."""

    needs_checkpoint_state = True

    def __init__(
        self,
        filename: str = "checkpoint.ckpt",
        on: Union[str, List[str]] = "validation_end",
    ) -> None:
        super().__init__(on=on)
        self._filename = filename

    def _handle(self, trainer: Any, module: Any) -> None:
        from ray_lightning_tpu.utils.state_stream import to_state_stream

        stream = to_state_stream(self._gathered_state)
        _dispatch(_checkpoint_closure(stream, trainer.global_step, self._filename))


class TuneReportCheckpointCallback(TuneCallback):
    """Checkpoint then report, as one atomic hook (reference tune.py:180-236
    notes checkpointing must precede the report)."""

    def __init__(
        self,
        metrics: Union[None, str, List[str], Dict[str, str]] = None,
        filename: str = "checkpoint.ckpt",
        on: Union[str, List[str]] = "validation_end",
    ) -> None:
        super().__init__(on=on)
        self._metrics = metrics
        self._filename = filename

    needs_checkpoint_state = True

    def _handle(self, trainer: Any, module: Any) -> None:
        report = _resolve_metrics(self._metrics, dict(trainer.callback_metrics))
        from ray_lightning_tpu.utils.state_stream import to_state_stream

        stream = to_state_stream(self._gathered_state)
        write_checkpoint = _checkpoint_closure(
            stream, trainer.global_step, self._filename
        )

        def checkpoint_and_report() -> None:
            path = write_checkpoint()
            if report:
                tune_session.report(metrics=report, checkpoint_path=path)

        _dispatch(checkpoint_and_report)
