"""The trial runner: a from-scratch mini-Tune on the fabric.

The reference nests N-worker distributed fits inside ray.tune trials
(SURVEY.md §3.3); since this framework owns its process fabric, it also owns
the trial layer: each trial is a fabric actor running the user's
``train_fn(config)`` (which typically builds a Trainer + strategy, spawning
its *own* nested worker actors), reporting metrics back to the tuner through
a results queue. An ASHA-style scheduler can terminate underperforming
trials early by killing the trial actor — the same mechanism ray.tune uses
(trial process termination), made safe by the fabric's SIGTERM cleanup of
nested actors.
"""
from __future__ import annotations

import json
import os
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Union

from ray_lightning_tpu import fabric
from ray_lightning_tpu.tune.search import generate_configs


class PlacementGroupFactory:
    """A trial's gang-resource request: head bundle + one bundle per
    training worker, placed together (reference ``PlacementGroupFactory(
    [head] + child_bundles, strategy="PACK")``, tune.py:50-55)."""

    def __init__(
        self, bundles: List[Dict[str, float]], strategy: str = "PACK"
    ) -> None:
        if not bundles:
            raise ValueError("need at least the head bundle")
        self.bundles = [
            {k: float(v) for k, v in b.items() if float(v)} for b in bundles
        ]
        self.strategy = strategy

    @property
    def required_resources(self) -> Dict[str, float]:
        """Aggregate across bundles (legacy flat view)."""
        total: Dict[str, float] = {}
        for b in self.bundles:
            for k, v in b.items():
                total[k] = total.get(k, 0.0) + v
        return total

    def __repr__(self) -> str:
        return (
            f"PlacementGroupFactory({self.bundles}, "
            f"strategy={self.strategy!r})"
        )


def get_tune_resources(
    num_workers: int = 1,
    num_cpus_per_worker: float = 1,
    use_tpu: bool = False,
    chips_per_worker: float = 1,
) -> PlacementGroupFactory:
    """Resource request for ONE trial: 1 CPU for the trial driver + one
    bundle per training worker, gang-placed with PACK (reference
    ``get_tune_resources`` builds the same [{CPU:1}] + N x {CPU, GPU}
    PlacementGroupFactory, tune.py:32-56)."""
    head = {"CPU": 1.0}
    child = {"CPU": float(num_cpus_per_worker)}
    if use_tpu:
        child["TPU"] = float(chips_per_worker)
    return PlacementGroupFactory(
        [head] + [dict(child) for _ in range(num_workers)], strategy="PACK"
    )


@dataclass
class Trial:
    trial_id: str
    config: Dict[str, Any]
    trial_dir: str
    status: str = "pending"  # pending/running/terminated/stopped/errored
    iterations: int = 0
    last_metrics: Dict[str, float] = field(default_factory=dict)
    history: List[Dict[str, Any]] = field(default_factory=list)
    checkpoint_path: Optional[str] = None
    error: Optional[str] = None
    actor: Any = None
    future: Any = None
    pg: Any = None  # fabric PlacementGroup while the trial holds its gang


@dataclass
class Result:
    trial_id: str
    config: Dict[str, Any]
    metrics: Dict[str, float]
    checkpoint_path: Optional[str]
    history: List[Dict[str, Any]]
    error: Optional[str]
    #: terminal trial state: "terminated" (ran to completion), "stopped"
    #: (scheduler-pruned), or "errored" — so callers can count what ASHA
    #: actually pruned without reaching into Tuner internals.
    status: str = "terminated"


class ResultGrid:
    def __init__(self, results: List[Result]) -> None:
        self._results = results

    def __iter__(self):
        return iter(self._results)

    def __len__(self) -> int:
        return len(self._results)

    def get_best_result(self, metric: str, mode: str = "min") -> Result:
        scored = [r for r in self._results if metric in r.metrics]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        key = lambda r: r.metrics[metric]  # noqa: E731
        return min(scored, key=key) if mode == "min" else max(scored, key=key)

    @property
    def errors(self) -> List[Result]:
        return [r for r in self._results if r.error]

    @property
    def num_stopped(self) -> int:
        """Trials the scheduler pruned before completion."""
        return sum(1 for r in self._results if r.status == "stopped")


class ASHAScheduler:
    """Async successive halving: at each rung, keep the top 1/eta of trials
    by the monitored metric and kill the rest."""

    def __init__(
        self,
        metric: str,
        mode: str = "min",
        grace_period: int = 1,
        reduction_factor: int = 2,
        max_t: Optional[int] = None,
    ) -> None:
        assert mode in ("min", "max")
        self.metric = metric
        self.mode = mode
        self.grace_period = max(1, grace_period)
        self.eta = max(2, reduction_factor)
        self.max_t = max_t
        self._rungs: Dict[int, List[float]] = {}

    def _rung_for(self, iteration: int) -> Optional[int]:
        rung = self.grace_period
        while rung <= iteration:
            if rung == iteration:
                return rung
            rung *= self.eta
        return None

    def on_report(self, trial: Trial, iteration: int, metrics: Dict[str, float]) -> str:
        """Returns "continue" or "stop"."""
        if self.max_t is not None and iteration >= self.max_t:
            return "stop"
        value = metrics.get(self.metric)
        if value is None:
            return "continue"
        rung = self._rung_for(iteration)
        if rung is None:
            return "continue"
        peers = self._rungs.setdefault(rung, [])
        peers.append(float(value))
        if len(peers) < self.eta:
            return "continue"
        ordered = sorted(peers, reverse=(self.mode == "max"))
        cutoff = ordered[max(0, len(peers) // self.eta - 1)]
        good = value <= cutoff if self.mode == "min" else value >= cutoff
        return "continue" if good else "stop"


def _trial_entry(train_fn: Callable, config: Dict[str, Any], trial_id: str,
                 trial_dir: str, results_queue: Any) -> Dict[str, Any]:
    """Runs inside the trial actor process."""
    from ray_lightning_tpu.tune import session as tune_session

    os.makedirs(trial_dir, exist_ok=True)
    tune_session.init_trial_session(trial_id, trial_dir, results_queue)
    try:
        train_fn(config)
        return {"status": "terminated"}
    finally:
        # The trial's own nested fabric session (training workers) dies with
        # this process's atexit; nothing else to clean here.
        tune_session.clear_trial_session()


class Tuner:
    """Run trials over a search space with bounded concurrency.

    usage:
        tuner = Tuner(train_fn, param_space={"lr": tune.loguniform(1e-4, 1e-1)},
                      num_samples=4, resources_per_trial=get_tune_resources(2),
                      scheduler=ASHAScheduler("val_loss"))
        results = tuner.fit()
    """

    def __init__(
        self,
        train_fn: Callable[[Dict[str, Any]], None],
        param_space: Dict[str, Any],
        num_samples: int = 1,
        resources_per_trial: Optional[
            Union[Dict[str, float], "PlacementGroupFactory"]
        ] = None,
        scheduler: Optional[ASHAScheduler] = None,
        max_concurrent: Optional[int] = None,
        experiment_dir: Optional[str] = None,
        seed: int = 0,
    ) -> None:
        self.train_fn = train_fn
        self.param_space = param_space
        self.num_samples = num_samples
        if resources_per_trial is None:
            resources_per_trial = PlacementGroupFactory([{"CPU": 1.0}])
        elif isinstance(resources_per_trial, dict):
            # Legacy flat request: a single-bundle gang (same placement
            # behavior the flat path had — one node must fit it all).
            resources_per_trial = PlacementGroupFactory([resources_per_trial])
        self.resources_per_trial = resources_per_trial
        self.scheduler = scheduler
        self.max_concurrent = max_concurrent
        self.experiment_dir = experiment_dir or os.path.join(
            os.environ.get("TMPDIR", "/tmp"), f"rlt_tune_{uuid.uuid4().hex[:6]}"
        )
        self.seed = seed

    # -- scheduling ----------------------------------------------------
    def _can_launch(self, running: List[Trial]) -> bool:
        if self.max_concurrent is not None and len(running) >= self.max_concurrent:
            return False
        need = self.resources_per_trial.required_resources
        # A trial's nested training workers are processes ON the trial
        # driver's host, so the whole gang must fit one node NOW.
        return any(
            all(n["Available"].get(k, 0.0) >= v for k, v in need.items())
            for n in fabric.nodes()
        )

    def _launch(self, trial: Trial, results_queue: Any) -> None:
        from ray_lightning_tpu.launchers.utils import TrainWorker

        factory = self.resources_per_trial
        head = dict(factory.bundles[0])
        # Gang placement (reference tune.py:50-55): reserve head + worker
        # bundles together (on the fabric head when in client mode). PACK
        # lands them on one node when it can; this fabric runs a trial's
        # nested workers as processes on the trial driver's host, so a
        # gang that STRADDLES nodes cannot actually co-locate — treat it
        # as unplaceable now and retry when capacity frees up (fit()
        # pre-checks that packing is possible at all, so this cannot spin
        # forever).
        trial.pg = fabric.placement_group(
            factory.bundles, strategy=factory.strategy
        )
        if len(set(trial.pg.bundle_node_ids)) > 1:
            fabric.remove_placement_group(trial.pg)
            trial.pg = None
            raise fabric.InsufficientResourcesError(
                f"trial {trial.trial_id} gang {factory.bundles} only "
                "fits straddling nodes; waiting for a single node to "
                "free up (nested workers run on the trial driver's host)"
            )
        # Request EXACTLY what bundle 0 reserves: defaulting the driver to
        # 1 CPU when the head bundle declares none could never fit the
        # bundle and would retry forever.
        num_cpus = head.pop("CPU", 0.0)
        options = dict(
            num_cpus=num_cpus,
            resources=head,
            placement_group=trial.pg,
            placement_group_bundle_index=0,
        )
        try:
            trial.actor = (
                fabric.remote(TrainWorker)
                .options(env={"RLT_TUNE_SESSION": "1"}, **options)
                .remote()
            )
        except BaseException:
            self._release_gang(trial)
            raise
        trial.future = trial.actor.execute.remote(
            _trial_entry,
            self.train_fn,
            trial.config,
            trial.trial_id,
            trial.trial_dir,
            results_queue,
        )
        trial.status = "running"

    def _release_gang(self, trial: Trial) -> None:
        if trial.pg is not None:
            try:
                fabric.remove_placement_group(trial.pg)
            except Exception:  # noqa: BLE001
                pass
            trial.pg = None

    def _drain_reports(self, trials: Dict[str, Trial], results_queue: Any) -> None:
        while not results_queue.empty():
            try:
                item = results_queue.get_nowait()
            except Exception:  # noqa: BLE001
                return
            trial = trials.get(item["trial_id"])
            if trial is None:
                continue
            trial.iterations = item["iteration"]
            trial.last_metrics = dict(item["metrics"])
            trial.history.append(
                {"iteration": item["iteration"], **item["metrics"]}
            )
            if item.get("checkpoint_path"):
                trial.checkpoint_path = item["checkpoint_path"]
            if self.scheduler and trial.status == "running":
                decision = self.scheduler.on_report(
                    trial, item["iteration"], item["metrics"]
                )
                if decision == "stop":
                    self._stop_trial(trial)

    def _stop_trial(self, trial: Trial) -> None:
        trial.status = "stopped"
        if trial.actor is not None:
            try:
                fabric.kill(trial.actor)
            except Exception:  # noqa: BLE001
                pass
        self._release_gang(trial)

    # -- main loop -----------------------------------------------------
    def fit(self) -> ResultGrid:
        if not fabric.is_initialized():
            fabric.init()
        # Fail fast if a trial's gang can never be placed, so the scheduler
        # loop can't spin forever with nothing launchable. Nested training
        # workers run on the trial driver's host, so the whole gang must
        # fit one node's CAPACITY — an "unpackable" trial is rejected here
        # with the packing math, not discovered as a hang (VERDICT r4
        # missing #1).
        need = self.resources_per_trial.required_resources
        node_caps = [n["Resources"] for n in fabric.nodes()]
        if not any(
            all(cap.get(k, 0.0) >= v for k, v in need.items())
            for cap in node_caps
        ):
            raise fabric.InsufficientResourcesError(
                f"resources_per_trial {self.resources_per_trial} "
                f"(total {need}) cannot be packed onto any single "
                f"node: capacities {node_caps}. A trial's training "
                "workers are co-located with its driver, so the gang "
                "must fit one node — shrink the trial or add capacity."
            )
        os.makedirs(self.experiment_dir, exist_ok=True)
        configs = generate_configs(self.param_space, self.num_samples, self.seed)
        results_queue = fabric.Queue()
        trials: Dict[str, Trial] = {}
        for i, config in enumerate(configs):
            trial_id = f"trial_{i:04d}"
            trials[trial_id] = Trial(
                trial_id=trial_id,
                config=config,
                trial_dir=os.path.join(self.experiment_dir, trial_id),
            )
        pending = list(trials.values())
        running: List[Trial] = []

        while pending or running:
            while pending and self._can_launch(running):
                trial = pending.pop(0)
                try:
                    self._launch(trial, results_queue)
                    running.append(trial)
                except fabric.InsufficientResourcesError:
                    pending.insert(0, trial)
                    break
            self._drain_reports(trials, results_queue)
            still_running: List[Trial] = []
            for trial in running:
                if trial.status == "stopped":
                    continue
                done, _ = fabric.wait([trial.future], timeout=0)
                if done:
                    try:
                        fabric.get(trial.future)
                        trial.status = "terminated"
                    except Exception as exc:  # noqa: BLE001
                        trial.status = "errored"
                        trial.error = str(exc)
                    if trial.actor is not None:
                        try:
                            fabric.kill(trial.actor)
                        except Exception:  # noqa: BLE001
                            pass
                    self._release_gang(trial)
                else:
                    still_running.append(trial)
            running = still_running
            time.sleep(0.05)
        self._drain_reports(trials, results_queue)

        results = [
            Result(
                trial_id=t.trial_id,
                config=t.config,
                metrics=t.last_metrics,
                checkpoint_path=t.checkpoint_path,
                history=t.history,
                error=t.error,
                status=t.status,
            )
            for t in trials.values()
        ]
        with open(os.path.join(self.experiment_dir, "results.json"), "w") as f:
            json.dump(
                [
                    {
                        "trial_id": r.trial_id,
                        "config": r.config,
                        "metrics": r.metrics,
                        "checkpoint_path": r.checkpoint_path,
                        "error": r.error,
                        "status": r.status,
                    }
                    for r in results
                ],
                f,
                indent=2,
                default=str,
            )
        return ResultGrid(results)


def run(
    train_fn: Callable[[Dict[str, Any]], None],
    config: Dict[str, Any],
    num_samples: int = 1,
    **tuner_kwargs: Any,
) -> ResultGrid:
    """ray.tune.run-shaped convenience wrapper."""
    return Tuner(train_fn, config, num_samples=num_samples, **tuner_kwargs).fit()
