"""Hyperparameter-tuning integration (reference: ray_lightning/tune.py).

Populated incrementally: session channel first (needed by the launcher);
the Tuner/search/report callbacks land with the tune milestone.
"""
from ray_lightning_tpu.tune.session import (
    get_actor_rank,
    get_session,
    init_session,
    is_tune_session,
    put_queue,
)

__all__ = [
    "init_session",
    "get_session",
    "get_actor_rank",
    "put_queue",
    "is_tune_session",
]
