"""Hyperparameter-tuning integration.

Feature parity with the reference's tune module
(/root/reference/ray_lightning/tune.py): ``get_tune_resources``,
``TuneReportCallback``, ``TuneReportCheckpointCallback``, plus — because
this framework owns its process fabric instead of depending on ray.tune — a
from-scratch trial runner (``Tuner``/``run``) with grid/random search and an
ASHA early-stopping scheduler.
"""
from ray_lightning_tpu.tune.callbacks import (
    TuneReportCallback,
    TuneReportCheckpointCallback,
    _TuneCheckpointCallback,
)
from ray_lightning_tpu.tune.search import choice, grid_search, loguniform, uniform
from ray_lightning_tpu.tune.session import (
    get_actor_rank,
    get_session,
    get_trial_dir,
    get_trial_session,
    init_session,
    init_trial_session,
    is_tune_session,
    put_queue,
    report,
)
from ray_lightning_tpu.tune.tuner import (
    ASHAScheduler,
    PlacementGroupFactory,
    Result,
    ResultGrid,
    Tuner,
    get_tune_resources,
    run,
)

__all__ = [
    "Tuner",
    "run",
    "ResultGrid",
    "Result",
    "ASHAScheduler",
    "PlacementGroupFactory",
    "get_tune_resources",
    "TuneReportCallback",
    "TuneReportCheckpointCallback",
    "grid_search",
    "choice",
    "uniform",
    "loguniform",
    "report",
    "init_session",
    "get_session",
    "get_actor_rank",
    "put_queue",
    "is_tune_session",
    "init_trial_session",
    "get_trial_session",
    "get_trial_dir",
]
