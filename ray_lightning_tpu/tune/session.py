"""Per-worker session: the worker -> driver closure channel.

Mirrors the reference's ``session.py`` (/root/reference/ray_lightning/
session.py:6-63): a module-global singleton per worker process holding
(rank, queue); ``put_queue(closure)`` enqueues ``(rank, closure)`` items the
driver executes in ``_handle_queue`` (util.py:49-54). This is how mid-train
callbacks (tune reporting/checkpointing) reach the trial driver without
breaking the compiled step cadence.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Optional


class TrainingSession:
    def __init__(self, rank: int, queue: Any) -> None:
        self.rank = rank
        self.queue = queue

    def put_queue(self, item: Callable[[], Any]) -> None:
        if self.queue is None:
            raise RuntimeError("session has no queue attached")
        self.queue.put((self.rank, item))


_session: Optional[TrainingSession] = None


def init_session(rank: int, queue: Any) -> None:
    global _session
    _session = TrainingSession(rank=rank, queue=queue)


def get_session() -> Optional[TrainingSession]:
    return _session


def clear_session() -> None:
    global _session
    _session = None


def get_actor_rank() -> int:
    sess = get_session()
    return sess.rank if sess is not None else 0


def put_queue(item: Callable[[], Any]) -> None:
    sess = get_session()
    if sess is None:
        raise RuntimeError("put_queue called outside a worker session")
    sess.put_queue(item)


def is_tune_session() -> bool:
    """True when the driver itself runs inside a Tune trial (then workers
    need the queue channel; reference gates on this at
    ray_launcher.py:101-103)."""
    return os.environ.get("RLT_TUNE_SESSION") == "1"


# ---------------------------------------------------------------------------
# Trial session: lives in the *trial driver* process (the actor the tuner
# spawned). ``report()`` forwards metrics to the tuner's results queue —
# the function worker-shipped closures ultimately call, equivalent to
# ``tune.report`` reaching Ray Tune in the reference (tune.py:130-134).
# ---------------------------------------------------------------------------
class TrialSession:
    def __init__(self, trial_id: str, trial_dir: str, results_queue: Any) -> None:
        self.trial_id = trial_id
        self.trial_dir = trial_dir
        self.results_queue = results_queue
        self.iteration = 0

    def report(self, metrics: dict, checkpoint_path: Optional[str] = None) -> None:
        self.iteration += 1
        self.results_queue.put(
            {
                "trial_id": self.trial_id,
                "iteration": self.iteration,
                "metrics": dict(metrics),
                "checkpoint_path": checkpoint_path,
            }
        )


_trial_session: Optional[TrialSession] = None


def init_trial_session(trial_id: str, trial_dir: str, results_queue: Any) -> None:
    global _trial_session
    _trial_session = TrialSession(trial_id, trial_dir, results_queue)
    os.environ["RLT_TUNE_SESSION"] = "1"


def get_trial_session() -> Optional[TrialSession]:
    return _trial_session


def clear_trial_session() -> None:
    global _trial_session
    _trial_session = None
    os.environ.pop("RLT_TUNE_SESSION", None)


def report(metrics: Optional[dict] = None, checkpoint_path: Optional[str] = None, **kw: Any) -> None:
    """Report trial metrics (``tune.report`` analog). Callable from the
    trial driver; worker-side callbacks ship closures that call this."""
    sess = get_trial_session()
    if sess is None:
        raise RuntimeError("tune.report() called outside a trial session")
    merged = dict(metrics or {})
    merged.update(kw)
    sess.report(merged, checkpoint_path=checkpoint_path)


def get_trial_dir() -> Optional[str]:
    sess = get_trial_session()
    return sess.trial_dir if sess is not None else None
