"""Per-worker session: the worker -> driver closure channel.

Mirrors the reference's ``session.py`` (/root/reference/ray_lightning/
session.py:6-63): a module-global singleton per worker process holding
(rank, queue); ``put_queue(closure)`` enqueues ``(rank, closure)`` items the
driver executes in ``_handle_queue`` (util.py:49-54). This is how mid-train
callbacks (tune reporting/checkpointing) reach the trial driver without
breaking the compiled step cadence.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Optional


class TrainingSession:
    def __init__(self, rank: int, queue: Any) -> None:
        self.rank = rank
        self.queue = queue

    def put_queue(self, item: Callable[[], Any]) -> None:
        if self.queue is None:
            raise RuntimeError("session has no queue attached")
        self.queue.put((self.rank, item))


_session: Optional[TrainingSession] = None


def init_session(rank: int, queue: Any) -> None:
    global _session
    _session = TrainingSession(rank=rank, queue=queue)


def get_session() -> Optional[TrainingSession]:
    return _session


def clear_session() -> None:
    global _session
    _session = None


def get_actor_rank() -> int:
    sess = get_session()
    return sess.rank if sess is not None else 0


def put_queue(item: Callable[[], Any]) -> None:
    sess = get_session()
    if sess is None:
        raise RuntimeError("put_queue called outside a worker session")
    sess.put_queue(item)


def is_tune_session() -> bool:
    """True when the driver itself runs inside a Tune trial (then workers
    need the queue channel; reference gates on this at
    ray_launcher.py:101-103)."""
    return os.environ.get("RLT_TUNE_SESSION") == "1"
