"""Placeholder class for optional dependencies.

Same contract as the reference's ``Unavailable``
(/root/reference/ray_lightning/util.py:42-46): importable at module scope,
raises only when actually instantiated/used, so optional integrations degrade
gracefully when their dependency is absent.
"""
from typing import Any


class Unavailable:
    """Stands in for a class whose optional dependency is not installed."""

    _reason = "a required optional dependency is not installed"

    def __init_subclass__(cls, reason: str = "", **kwargs: Any) -> None:
        super().__init_subclass__(**kwargs)
        if reason:
            cls._reason = reason

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        raise RuntimeError(f"{type(self).__name__} is unavailable: {self._reason}.")

    def __getattr__(self, name: str) -> Any:
        raise RuntimeError(f"{type(self).__name__} is unavailable: {self._reason}.")


def make_unavailable(name: str, reason: str) -> type:
    """Create a named Unavailable subclass with a custom error reason."""
    return type(name, (Unavailable,), {"_reason": reason})
