"""Loader for the native data-path library (csrc/rltnative.cpp).

Compiles the C++ source with g++ on first use into a per-user cache keyed by
source hash (so edits rebuild automatically), binds it with ctypes (no
pybind11 in this environment), and degrades to numpy fallbacks when no
compiler is available or RLT_NO_NATIVE=1. ctypes releases the GIL for the
call duration, which is what lets the prefetch thread in
``trainer.data`` overlap batch assembly with device compute.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from pathlib import Path
from typing import Any, Optional

import numpy as np

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_SRC = Path(__file__).resolve().parent.parent / "csrc" / "rltnative.cpp"


def _cache_dir() -> Path:
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    return Path(base) / "ray_lightning_tpu"


def _build() -> Optional[ctypes.CDLL]:
    src = _SRC.read_bytes()
    digest = hashlib.sha256(src).hexdigest()[:16]
    out = _cache_dir() / f"rltnative-{digest}.so"
    if not out.exists():
        out.parent.mkdir(parents=True, exist_ok=True)
        tmp = out.with_suffix(f".build-{os.getpid()}.so")
        cmd = [
            os.environ.get("CXX", "g++"),
            "-O3",
            "-shared",
            "-fPIC",
            "-std=c++17",
            "-pthread",
            str(_SRC),
            "-o",
            str(tmp),
        ]
        proc = subprocess.run(cmd, capture_output=True, timeout=120)
        if proc.returncode != 0:
            raise RuntimeError(
                f"native build failed: {proc.stderr.decode(errors='replace')}"
            )
        os.replace(tmp, out)  # atomic vs concurrent workers building too
    lib = ctypes.CDLL(str(out))
    lib.rlt_abi_version.restype = ctypes.c_int32
    if lib.rlt_abi_version() != 3:
        raise RuntimeError("rltnative ABI mismatch")
    lib.rlt_gather_rows.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_int32,
    ]
    lib.rlt_gather_u8_to_f32.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_float,
        ctypes.c_float,
        ctypes.c_int32,
    ]
    lib.rlt_gather_windows_bytes.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_int32,
    ]
    lib.rlt_gather_windows_u16_i32.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_int32,
    ]
    lib.rlt_bpe_train.restype = ctypes.c_int64
    lib.rlt_bpe_train.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_int32,
        ctypes.c_int32,
        ctypes.c_void_p,
    ]
    lib.rlt_bpe_encode.restype = ctypes.c_int64
    lib.rlt_bpe_encode.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_void_p,
        ctypes.c_int32,
        ctypes.c_void_p,
    ]
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, or None (fallback mode)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("RLT_NO_NATIVE") == "1":
            return None
        try:
            _lib = _build()
        except Exception:  # noqa: BLE001 - any failure means fallback
            _lib = None
    return _lib


def native_available() -> bool:
    return get_lib() is not None


def _n_threads(n_rows: int) -> int:
    cpus = os.cpu_count() or 1
    return max(1, min(4, cpus, n_rows // 512))


def _check_bounds(idx: np.ndarray, n_rows: int) -> None:
    """Match numpy's fancy-indexing contract before handing indices to the
    C memcpy loop (which would OOB-read where numpy raises)."""
    if len(idx) and (idx.min() < -n_rows or idx.max() >= n_rows):
        bad = idx[(idx < -n_rows) | (idx >= n_rows)][0]
        raise IndexError(
            f"index {int(bad)} is out of bounds for axis 0 with size {n_rows}"
        )


def gather_rows(src: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """out[i] = src[idx[i]] for contiguous src; GIL-free when native."""
    lib = get_lib()
    if lib is None or not src.flags.c_contiguous:
        return src[idx]
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    _check_bounds(idx, len(src))
    if len(idx) and idx.min() < 0:  # numpy-style negative indices
        idx = np.where(idx < 0, idx + len(src), idx)
    out = np.empty((len(idx),) + src.shape[1:], dtype=src.dtype)
    row_bytes = src.dtype.itemsize * int(np.prod(src.shape[1:], initial=1))
    lib.rlt_gather_rows(
        src.ctypes.data,
        out.ctypes.data,
        idx.ctypes.data,
        len(idx),
        row_bytes,
        _n_threads(len(idx)),
    )
    return out


def gather_rows_u8_to_f32(
    src: np.ndarray, idx: np.ndarray, scale: float = 1.0 / 255.0, shift: float = 0.0
) -> np.ndarray:
    """Fused gather + uint8->float32 normalize (image batch hot path)."""
    lib = get_lib()
    if lib is None or not src.flags.c_contiguous or src.dtype != np.uint8:
        return src[idx].astype(np.float32) * scale + shift
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    out = np.empty((len(idx),) + src.shape[1:], dtype=np.float32)
    row_elems = int(np.prod(src.shape[1:], initial=1))
    lib.rlt_gather_u8_to_f32(
        src.ctypes.data,
        out.ctypes.data,
        idx.ctypes.data,
        len(idx),
        row_elems,
        scale,
        shift,
        _n_threads(len(idx)),
    )
    return out



def gather_windows(
    src: np.ndarray, starts: np.ndarray, window: int, out_dtype: Any = None
) -> np.ndarray:
    """out[i] = src[starts[i] : starts[i] + window] for 1-D ``src``.

    The memmap token-corpus batch path: windows may overlap (stride <
    seq_len), and ``src`` is typically a cold np.memmap whose page faults
    should happen off the GIL — the native copy threads do exactly that.
    uint16 -> int32 (the GPT shard-to-model-input case) runs fused in one
    pass; other dtype conversions copy then astype.
    """
    if src.ndim != 1:
        raise ValueError(f"gather_windows needs 1-D src, got ndim={src.ndim}")
    starts = np.ascontiguousarray(starts, dtype=np.int64)
    out_dtype = np.dtype(out_dtype) if out_dtype is not None else src.dtype
    if len(starts) and (
        starts.min() < 0 or starts.max() + window > src.shape[0]
    ):
        bad = starts[(starts < 0) | (starts + window > src.shape[0])][0]
        raise IndexError(
            f"window [{int(bad)}, {int(bad) + window}) out of bounds for "
            f"size {src.shape[0]}"
        )
    lib = get_lib()
    if lib is None or not src.flags.c_contiguous:
        return np.stack(
            [src[s : s + window] for s in starts]
        ).astype(out_dtype, copy=False) if len(starts) else np.empty(
            (0, window), out_dtype
        )
    if not len(starts):
        return np.empty((0, window), dtype=out_dtype)
    if src.dtype == np.uint16 and out_dtype == np.int32:
        out = np.empty((len(starts), window), dtype=out_dtype)
        lib.rlt_gather_windows_u16_i32(
            src.ctypes.data,
            out.ctypes.data,
            starts.ctypes.data,
            len(starts),
            window,
            _n_threads(len(starts)),
        )
        return out
    item = src.dtype.itemsize
    raw = np.empty((len(starts), window), dtype=src.dtype)
    # Bound to a name: a bare `(starts * item).ctypes.data` hands C a
    # pointer into a temporary the GC may reclaim mid-call.
    byte_starts = starts * item
    lib.rlt_gather_windows_bytes(
        src.ctypes.data,
        raw.ctypes.data,
        byte_starts.ctypes.data,
        len(starts),
        window * item,
        _n_threads(len(starts)),
    )
    return raw.astype(out_dtype, copy=False)


def bpe_train(corpus: np.ndarray, n_merges: int, sep: int = -1) -> np.ndarray:
    """Learn up to ``n_merges`` BPE merges over a uint8 corpus; returns an
    (n_learned, 2) int32 array of (left, right) pairs in rank order.
    Pairs touching ``sep`` (a document separator byte; -1 = none) are
    never merged. GIL-free when native; tokenizer.py carries the Python
    fallback."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    corpus = np.ascontiguousarray(corpus, dtype=np.uint8)
    merges = np.empty((max(1, n_merges), 2), dtype=np.int32)
    n = lib.rlt_bpe_train(
        corpus.ctypes.data, len(corpus), n_merges, sep, merges.ctypes.data
    )
    return merges[: int(n)].copy()


def bpe_encode(text: np.ndarray, merges: np.ndarray) -> np.ndarray:
    """Encode uint8 bytes -> int32 token ids with rank-ordered merges."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    text = np.ascontiguousarray(text, dtype=np.uint8)
    merges = np.ascontiguousarray(merges, dtype=np.int32)
    out = np.empty(max(1, len(text)), dtype=np.int32)
    n = lib.rlt_bpe_encode(
        text.ctypes.data, len(text), merges.ctypes.data, len(merges),
        out.ctypes.data,
    )
    return out[: int(n)].copy()
