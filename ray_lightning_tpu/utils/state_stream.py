"""Pytree <-> bytes state streams: the checkpoint wire format.

TPU-native equivalent of the reference's torch-serialized state streams
(/root/reference/ray_lightning/util.py:73-92): worker rank 0 converts the
final JAX param/opt pytree to host numpy, serializes it, and ships the bytes
to the driver through the object store; the driver restores it (optionally
re-placing leaves onto devices with a target sharding). Works cross-node by
construction — no shared filesystem needed.

Format: a msgpack map of {flat key path: raw numpy buffer + dtype + shape},
plus a pickled treedef, so the payload is self-describing and zero-copy
friendly (buffers are contiguous and can be memoryview'd straight out of
shared memory).
"""
import io
import pickle
from typing import Any, Optional

import numpy as np

try:  # msgpack is baked into the image; guard anyway for portability.
    import msgpack

    _HAS_MSGPACK = True
except ImportError:  # pragma: no cover
    _HAS_MSGPACK = False

_MAGIC = b"RLTS1"


def _leaf_to_host(leaf: Any) -> Any:
    """Move one pytree leaf to host memory as numpy (jax/np/scalar passthrough)."""
    import jax

    if isinstance(leaf, jax.Array):
        # Fully-addressable arrays come back whole; sharded arrays must be
        # gathered by the caller first (see strategies/sharded.py).
        return np.asarray(jax.device_get(leaf))
    if isinstance(leaf, np.ndarray):
        return leaf
    return leaf


def to_state_stream(pytree: Any) -> bytes:
    """Serialize a JAX pytree of arrays to a self-contained bytes blob."""
    import jax

    host_tree = jax.tree_util.tree_map(_leaf_to_host, pytree)
    leaves, treedef = jax.tree_util.tree_flatten(host_tree)
    if not _HAS_MSGPACK:  # pragma: no cover
        return _MAGIC + b"P" + pickle.dumps((leaves, treedef), protocol=5)

    arrays = []
    others = []
    for i, leaf in enumerate(leaves):
        if isinstance(leaf, np.ndarray):
            buf = np.ascontiguousarray(leaf)
            arrays.append(
                {
                    "i": i,
                    "dtype": buf.dtype.str,
                    "shape": list(buf.shape),
                    "data": buf.tobytes(),
                }
            )
        else:
            others.append((i, leaf))
    payload = {
        "arrays": arrays,
        "others": pickle.dumps(others, protocol=5),
        "treedef": pickle.dumps(treedef, protocol=5),
        "n": len(leaves),
    }
    return _MAGIC + b"M" + msgpack.packb(payload, use_bin_type=True)


def load_state_stream(stream: bytes, sharding: Optional[Any] = None) -> Any:
    """Restore a pytree from ``to_state_stream`` bytes.

    If ``sharding`` is given (a ``jax.sharding.Sharding`` or a pytree of them
    matching the stream's structure), leaves are placed on device accordingly;
    otherwise they stay as host numpy.
    """
    import jax

    if not stream.startswith(_MAGIC):
        raise ValueError("not a ray_lightning_tpu state stream")
    kind, body = stream[5:6], stream[6:]
    if kind == b"P":  # pragma: no cover
        leaves, treedef = pickle.loads(body)
    else:
        payload = msgpack.unpackb(body, raw=False)
        leaves: list = [None] * payload["n"]
        for rec in payload["arrays"]:
            # bytearray copy makes the restored array writable (frombuffer on
            # bytes yields read-only views, which breaks in-place finetuning).
            arr = np.frombuffer(bytearray(rec["data"]), dtype=np.dtype(rec["dtype"]))
            leaves[rec["i"]] = arr.reshape(rec["shape"])
        for i, leaf in pickle.loads(payload["others"]):
            leaves[i] = leaf
        treedef = pickle.loads(payload["treedef"])
    tree = jax.tree_util.tree_unflatten(treedef, leaves)
    if sharding is None:
        return tree
    if isinstance(sharding, jax.sharding.Sharding):
        return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)
    return jax.tree_util.tree_map(lambda x, s: jax.device_put(x, s), tree, sharding)


def state_stream_to_file(stream: bytes, path: str) -> None:
    """Write a state stream to ``path`` via fsspec (remote URIs supported).

    Local writes are atomic (tmp + rename): a process killed mid-save —
    the exact event ``max_restarts`` recovery exists for — must never
    leave a truncated checkpoint as the newest file in the directory.
    """
    if "://" not in path:
        import os

        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with io.open(tmp, "wb") as f:
                f.write(stream)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)  # don't orphan partial temp files
            except OSError:
                pass
            raise
        return
    import fsspec

    with fsspec.open(path, "wb") as f:
        f.write(stream)
