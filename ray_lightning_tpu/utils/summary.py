"""Model summary (PTL's ModelSummary / enable_model_summary analog).

PTL prints a per-module table of layer names, types, and parameter counts
when a fit starts. Params here are plain pytrees, so the summary groups by
pytree path prefix instead of nn.Module hierarchy — with the TPU-relevant
additions: per-group dtype, on-device bytes, and (for placed jax.Arrays)
whether leaves are sharded or replicated across the mesh.
"""
from __future__ import annotations

from typing import Any, Dict, List, Tuple


def _key_name(k: Any) -> str:
    """DictKey -> key, SequenceKey -> idx, GetAttrKey -> name, else str."""
    for attr in ("key", "idx", "name"):
        if hasattr(k, attr):
            return str(getattr(k, attr))
    return str(k)


def _walk(params: Any) -> List[Tuple[Tuple[str, ...], Any]]:
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    return [(tuple(_key_name(k) for k in path), leaf) for path, leaf in flat]


def _placement(leaf: Any) -> str:
    sh = getattr(leaf, "sharding", None)
    if sh is None:
        return "host"
    try:
        return "replicated" if sh.is_fully_replicated else "sharded"
    except Exception:  # noqa: BLE001 - exotic shardings: just say placed
        return "device"


def summarize_params(params: Any, depth: int = 1) -> str:
    """Human-readable parameter table, grouped by path prefix.

    ``depth`` controls grouping granularity (1 = top-level keys). Returns a
    string; callers decide where to print (the loop does it rank-0 only,
    to stderr — stdout is a data channel for CLI/bench pipelines).
    """
    import numpy as np

    rows: Dict[str, Dict[str, Any]] = {}
    total = 0
    total_bytes = 0
    placements = set()
    for path, leaf in _walk(params):
        group = ".".join(path[:depth]) if path else "(root)"
        shape = tuple(getattr(leaf, "shape", ()) or ())
        n = int(np.prod(shape, initial=1))
        dtype = str(getattr(leaf, "dtype", "?"))
        nbytes = n * int(getattr(getattr(leaf, "dtype", None), "itemsize", 4) or 4)
        row = rows.setdefault(
            group, {"params": 0, "bytes": 0, "dtypes": set(), "place": set()}
        )
        row["params"] += n
        row["bytes"] += nbytes
        row["dtypes"].add(dtype)
        row["place"].add(_placement(leaf))
        placements |= row["place"]
        total += n
        total_bytes += nbytes

    def fmt_n(n: int) -> str:
        for unit, div in (("B", 1e9), ("M", 1e6), ("K", 1e3)):
            if n >= div:
                return f"{n / div:.1f} {unit}"
        return str(n)

    # The placement column only appears once something is device-placed —
    # a host-side numpy tree prints the compact classic table.
    show_place = placements - {"host"}
    name_w = max([len(g) for g in rows] + [5])
    head = f"{'name':<{name_w}} | {'params':>8} | {'bytes':>8} | dtype"
    if show_place:
        head += " | placement"
    lines = [head, "-" * len(head)]
    for group, row in rows.items():
        line = (
            f"{group:<{name_w}} | {fmt_n(row['params']):>8} | "
            f"{fmt_n(row['bytes']):>8} | {','.join(sorted(row['dtypes']))}"
        )
        if show_place:
            line += f" | {','.join(sorted(row['place']))}"
        lines.append(line)
    lines.append("-" * len(head))
    lines.append(
        f"{'total':<{name_w}} | {fmt_n(total):>8} | {fmt_n(total_bytes):>8} |"
        f" {len(rows)} groups"
    )
    return "\n".join(lines)
