"""Seed plumbing.

The reference broadcasts ``PL_GLOBAL_SEED`` to every worker and calls
``reset_seed()`` per worker (/root/reference/ray_lightning/ray_ddp.py:167,
launchers/ray_launcher.py:169-172). Here the seed additionally derives the
root ``jax.random.PRNGKey`` for model init, so a fixed seed gives bitwise
reproducible initial parameters across workers.
"""
import os
import random
from typing import Optional

import numpy as np

GLOBAL_SEED_ENV = "RLT_GLOBAL_SEED"


def seed_everything(seed: Optional[int] = None) -> int:
    """Seed python, numpy, and record the seed for worker broadcast."""
    if seed is None:
        env = os.environ.get(GLOBAL_SEED_ENV)
        seed = int(env) if env is not None else random.randint(0, 2**31 - 1)
    seed = int(seed)
    os.environ[GLOBAL_SEED_ENV] = str(seed)
    random.seed(seed)
    np.random.seed(seed % (2**32))
    return seed


def reset_seed() -> Optional[int]:
    """Re-apply the broadcast seed inside a worker, if one was set."""
    env = os.environ.get(GLOBAL_SEED_ENV)
    if env is None:
        return None
    return seed_everything(int(env))
