"""JAX version-compat shims.

The package targets current JAX but must keep working on the 0.4.x line
(the CI nightly/release matrix): APIs that moved between the two are
funneled through here so call sites stay on the modern spelling.
"""
from __future__ import annotations

from typing import Any, Optional, Set

import jax


def shard_map(
    f: Any,
    mesh: Any,
    in_specs: Any,
    out_specs: Any,
    axis_names: Optional[Set[str]] = None,
    **kwargs: Any,
):
    """``jax.shard_map`` (>= 0.5) or ``jax.experimental.shard_map`` (0.4.x).

    ``axis_names`` selects the manual axes; the 0.4.x API expresses the
    same thing inversely via ``auto`` (every OTHER mesh axis stays under
    the partitioner).
    """
    if hasattr(jax, "shard_map"):
        if axis_names is not None:
            kwargs["axis_names"] = axis_names
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    if axis_names is not None:
        kwargs["auto"] = frozenset(mesh.axis_names) - set(axis_names)
        # The 0.4.x replication checker mis-types lax.cond carries under
        # partial-auto manual axes (the pipeline's fill/drain cond); the
        # checker is advisory, and jax's own error message recommends
        # disabling it there.
        kwargs.setdefault("check_rep", False)
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )
