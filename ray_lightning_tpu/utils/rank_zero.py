"""Rank-zero-only logging helpers.

Mirrors the role of PTL's ``rank_zero_only`` that the reference sets per worker
(/root/reference/ray_lightning/ray_ddp.py:169): workers set
``rank_zero_only.rank`` so only global rank 0 emits logs/checkpoints.
"""
import functools
import logging
from typing import Any, Callable, Optional, TypeVar

logger = logging.getLogger("ray_lightning_tpu")

T = TypeVar("T", bound=Callable[..., Any])


def rank_zero_only(fn: T) -> T:
    """Decorator: run ``fn`` only on global rank 0 (returns None elsewhere)."""

    @functools.wraps(fn)
    def wrapped(*args: Any, **kwargs: Any) -> Optional[Any]:
        if getattr(rank_zero_only, "rank", 0) == 0:
            return fn(*args, **kwargs)
        return None

    return wrapped  # type: ignore[return-value]


rank_zero_only.rank = 0  # type: ignore[attr-defined]


@rank_zero_only
def rank_zero_info(msg: str, *args: Any) -> None:
    logger.info(msg, *args)


@rank_zero_only
def rank_zero_warn(msg: str, *args: Any) -> None:
    logger.warning(msg, *args)
