"""Weight-only int8 quantization for inference.

Decode on TPU is HBM-bandwidth-bound: every generated token re-reads the
model's matmul weights (and the (V, D) head), so halving the bytes per
weight roughly halves the per-token floor that bf16 sets. Weight-only
int8 (symmetric, per-output-channel scales over the contraction axes)
keeps activations and accumulation in the compute dtype — XLA fuses the
``int8 -> f32 * scale`` dequant into the consuming matmul's operand read.

``quantize_params_int8`` maps a GPT parameter pytree (models/gpt.py
layout) to the same tree with the large matmul leaves replaced by
``{"q": int8, "s": f32 broadcast-ready scales}`` nodes; norms, biases,
positional tables, and MoE/router leaves stay fp32 (tiny, or
accuracy-sensitive). The forward/decode paths consume either form via
:func:`dequant` / :func:`embed_rows`, so one code path serves both —
equality of the quantized path against dequantize-then-compute is
asserted in tests/test_quantize.py.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp


def is_quantized(w: Any) -> bool:
    return isinstance(w, dict) and set(w) == {"q", "s"}


def quantize_tensor(
    w: jax.Array, reduce_axes: Tuple[int, ...]
) -> Dict[str, jax.Array]:
    """Symmetric int8 with fp32 scales shared over ``reduce_axes`` (the
    contraction dims of the consuming matmul, i.e. per-output-channel)."""
    w = jnp.asarray(w, jnp.float32)
    s = jnp.max(jnp.abs(w), axis=reduce_axes, keepdims=True) / 127.0
    s = jnp.maximum(s, 1e-12)  # all-zero channels: avoid 0/0
    q = jnp.clip(jnp.round(w / s), -127, 127).astype(jnp.int8)
    return {"q": q, "s": s}


def dequant(w: Any, dt: Any) -> jax.Array:
    """Quantized node -> dense weights in ``dt``; plain arrays pass
    through (the training path pays nothing). The multiply sits directly
    before the consuming matmul so XLA folds it into the operand read."""
    if is_quantized(w):
        return (w["q"].astype(jnp.float32) * w["s"]).astype(dt)
    return w.astype(dt)


def embed_rows(table: Any, idx: jax.Array) -> jax.Array:
    """Row gather from a (possibly quantized) (V, D) table: gather the
    int8 rows and their scales, dequantize only what was read."""
    if is_quantized(table):
        return table["q"][idx].astype(jnp.float32) * table["s"][idx]
    return table[idx]


#: contraction (reduce) axes per QUANTIZED leaf of the stacked GPT tree;
#: leaves absent from a model (GQA vs fused, tied vs untied) are skipped.
_GPT_BLOCK_AXES: Dict[str, Tuple[int, ...]] = {
    "wqkv": (1,),  # (L, D, 3, H, hd): contract D
    "wq": (1,),  # (L, D, H, hd)
    "wkv": (1,),  # (L, D, 2, Hkv, hd)
    "wo": (1, 2),  # (L, H, hd, D): contract H, hd
    "wi": (1,),  # (L, D, F) or (L, D, 2, F): contract D
    "wo2": (1,),  # (L, F, D): contract F
}


def quantize_params_int8(params: Dict[str, Any]) -> Dict[str, Any]:
    """GPT parameter tree -> same tree with the large matmul weights as
    int8 nodes. MoE expert leaves (rank-4/5 ``wi``/``wo2`` with a leading
    expert dim) are left fp32 — expert weights are read sparsely and the
    router is accuracy-critical; quantize them separately if profiling
    says otherwise."""
    out: Dict[str, Any] = dict(params)
    blocks = dict(params["blocks"])
    moe = "router" in blocks  # MoE trees keep expert leaves fp32
    for name, axes in _GPT_BLOCK_AXES.items():
        if name not in blocks:
            continue
        if moe and name in ("wi", "wo2"):
            continue
        blocks[name] = quantize_tensor(blocks[name], axes)
    out["blocks"] = blocks
    out["wte"] = quantize_tensor(params["wte"], (1,))  # (V, D): contract D
    if "lm_head" in params:
        out["lm_head"] = quantize_tensor(params["lm_head"], (1,))
    return out


def dequantize_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """Inverse mapping to a plain fp32 tree (the reference-semantics
    oracle: quantized-path outputs must equal running THIS tree)."""

    def walk(node: Any) -> Any:
        if is_quantized(node):
            return node["q"].astype(jnp.float32) * node["s"]
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        return node

    return walk(params)
