"""Shared utilities for ray_lightning_tpu.

TPU-native re-imagination of the reference's ``util.py`` + ``launchers/utils.py``
(see /root/reference/ray_lightning/util.py:1-102): state streams are JAX pytrees
serialized to host numpy instead of torch tensors, and device binding is owned by
PJRT instead of ``torch.cuda.set_device``.
"""
from ray_lightning_tpu.utils.ports import find_free_port
from ray_lightning_tpu.utils.seed import reset_seed, seed_everything
from ray_lightning_tpu.utils.state_stream import (
    load_state_stream,
    to_state_stream,
)
from ray_lightning_tpu.utils.rank_zero import rank_zero_info, rank_zero_only, rank_zero_warn
from ray_lightning_tpu.utils.quantize import (
    dequantize_params,
    quantize_params_int8,
)
from ray_lightning_tpu.utils.unavailable import Unavailable

__all__ = [
    "quantize_params_int8",
    "dequantize_params",
    "find_free_port",
    "reset_seed",
    "seed_everything",
    "to_state_stream",
    "load_state_stream",
    "rank_zero_only",
    "rank_zero_info",
    "rank_zero_warn",
    "Unavailable",
]
