"""Published per-chip peak dense bf16 FLOP/s, for MFU arithmetic."""
from __future__ import annotations

from typing import Optional

# Public figures (per chip). Keys match jax Device.device_kind strings.
PEAK_BF16_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def peak_flops_for(device_kind: str) -> Optional[float]:
    """Peak bf16 FLOP/s for a device kind; None when unknown (CPU, new
    chips) — callers should then skip MFU rather than fabricate one."""
    return PEAK_BF16_FLOPS.get(device_kind)
