"""Free-port discovery for the distributed coordinator.

Plays the role of the reference's ``find_free_port``
(/root/reference/ray_lightning/launchers/utils.py:12-17) but the port feeds
``jax.distributed.initialize(coordinator_address=...)`` instead of
``MASTER_PORT`` for torch.distributed.
"""
import contextlib
import socket


def find_free_port(host: str = "") -> int:
    """Bind port 0 on ``host`` and return the OS-assigned free port."""
    with contextlib.closing(socket.socket(socket.AF_INET, socket.SOCK_STREAM)) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, 0))
        return s.getsockname()[1]


def get_node_ip() -> str:
    """Best-effort IP of this host, as the coordinator address."""
    try:
        with contextlib.closing(socket.socket(socket.AF_INET, socket.SOCK_DGRAM)) as s:
            # No packets are sent; connect() on UDP just resolves the route.
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
