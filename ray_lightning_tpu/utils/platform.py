"""Honoring an explicit JAX platform choice.

This environment's PJRT TPU plugin registers itself at interpreter boot
(sitecustomize) and force-sets the ``jax_platforms`` config, which silently
overrides the ``JAX_PLATFORMS`` env var. Anywhere the framework runs user
compute in the CURRENT process (worker actors, the in-process Trainer path,
the CLI) must therefore re-apply the env var through jax.config before the
first backend touch — otherwise ``JAX_PLATFORMS=cpu`` still initializes the
(possibly remote and wedged) TPU backend and can hang outright.
"""
from __future__ import annotations

import os


def apply_jax_platform_env() -> None:
    """Re-apply ``JAX_PLATFORMS`` over any plugin-forced platform config.

    No-op when the env var is unset or jax is unavailable; safe to call
    repeatedly, but must run before the first ``jax.devices()``.
    """
    if os.environ.get("JAX_PLATFORMS"):
        try:
            import jax

            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        except Exception:  # noqa: BLE001 - jax absent / backend already live
            pass
