"""Distributed environment descriptor shared by launcher and workers.

Carries what the reference spreads across env vars + strategy properties
(MASTER_ADDR/PORT broadcast at ray_launcher.py:85-87,159-175; rank
properties at ray_ddp.py:205-257): who I am (host_rank/node_rank), how many
of us there are, and where the coordination service lives. The TPU twist:
one worker *process* owns several chips, so chip-level ("worker") and
host-level (process) ranks are both represented (SURVEY.md §7 "hard parts").
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class DistEnv:
    world_size: int = 1  # total chips == data-parallel ranks ("num_workers")
    num_hosts: int = 1  # worker processes (one per TPU host)
    host_rank: int = 0  # this process's rank (coordinator process_id)
    node_rank: int = 0  # logical node index (== host_rank on 1-proc-per-node)
    local_chips: int = 1  # chips owned by this process
    coordinator_address: Optional[str] = None  # "ip:port" for rendezvous
    # global chip-rank of this host's first chip; chip-ranks are contiguous
    # per host: [first_chip_rank, first_chip_rank + local_chips)
    first_chip_rank: int = 0
    # host_rank -> (local_rank, node_rank) as computed by the launcher from
    # node IPs (the reference's get_local_ranks, ray_launcher.py:130-157)
    global_to_local: Dict[int, tuple] = field(default_factory=dict)

    @property
    def is_distributed(self) -> bool:
        return self.num_hosts > 1

    @property
    def global_rank(self) -> int:
        return self.host_rank

    @property
    def local_rank(self) -> int:
        if self.global_to_local and self.host_rank in self.global_to_local:
            return self.global_to_local[self.host_rank][0]
        return 0
