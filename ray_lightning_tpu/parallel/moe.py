"""Mixture-of-Experts layer with expert parallelism over a mesh axis.

Beyond-parity capability (the reference has no model code at all,
SURVEY.md §2c): a switch-style MoE feed-forward whose expert weights
carry a leading ``experts`` dim annotated with the "expert" logical axis —
mapped by GSPMDStrategy to the "ep" mesh axis, so each ep rank holds
E/ep_size experts and XLA routes tokens between ranks (the all-to-all
pattern) from the shardings alone.

Two dispatch implementations:

- ``moe_ffn`` (default, sort-based): tokens are grouped by expert with one
  stable argsort and moved with gather/scatter-add — O(T·K·D + E·C·D)
  memory, supports top-1 and top-2 routing. Static shapes throughout
  (argsort/scatter are XLA-native), so it jits and shards like any other op.
- ``moe_ffn_dense``: the original one-hot einsum formulation, O(T·E·C)
  dispatch tensors. Kept as the readable oracle the tests check the sparse
  path against, and as a fallback for tiny expert counts where the dense
  einsum fuses better.

Capacity factoring drops overflow tokens (standard switch behavior) to keep
per-expert compute static; with the stable sort, earlier tokens win expert
slots in both implementations, so top-1 sparse == dense exactly.
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp


def init_moe_params(
    rng: jax.Array,
    n_experts: int,
    d_model: int,
    d_ff: int,
    std: float = 0.02,
    res_std: float = 0.02,
) -> Dict[str, jax.Array]:
    k1, k2, k3 = jax.random.split(rng, 3)
    return {
        "router": (jax.random.normal(k1, (d_model, n_experts)) * std).astype(
            jnp.float32
        ),
        "wi": (
            jax.random.normal(k2, (n_experts, d_model, d_ff)) * std
        ).astype(jnp.float32),
        "bi": jnp.zeros((n_experts, d_ff)),
        "wo": (
            jax.random.normal(k3, (n_experts, d_ff, d_model)) * res_std
        ).astype(jnp.float32),
        "bo": jnp.zeros((n_experts, d_model)),
    }


def moe_logical_axes() -> Dict[str, Tuple]:
    return {
        "router": ("embed", None),
        "wi": ("expert", "embed", "mlp"),
        "bi": ("expert", "mlp"),
        "wo": ("expert", "mlp", "embed"),
        "bo": ("expert", None),
    }


def moe_ffn(
    params: Dict[str, jax.Array],
    x: jax.Array,
    capacity_factor: float = 1.25,
    compute_dtype: Any = jnp.float32,
    top_k: int = 1,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Sort-based MoE feed-forward with top-k routing (default top-1).

    x: (B, S, D) -> (B, S, D), plus aux metrics {"aux_loss", "dropped"}.
    ``aux_loss`` is the load-balancing loss of Shazeer et al. (mean expert
    load x mean router prob, scaled by E); add it to the task loss.

    Dispatch memory is O(T·K·D + E·C·D): one stable argsort groups the
    (token, expert) assignments by expert, positions within each expert
    queue come from a searchsorted offset, and tokens move via gather +
    scatter-add — no (T, E, C) one-hot tensors. For ``top_k=2`` every
    first-choice assignment outranks all second choices for capacity
    (GShard-style priority), and gates are renormalized over the kept
    choices' router probabilities.
    """
    B, S, D = x.shape
    E = params["router"].shape[1]
    T = B * S
    K = int(top_k)
    tokens = x.reshape(T, D)
    # Router in fp32 for stable softmax.
    logits = tokens.astype(jnp.float32) @ params["router"]  # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_e = jax.lax.top_k(probs, K)  # (T, K)
    # Switch top-1 gates with the raw router prob (dense-oracle semantics);
    # top-k>1 renormalizes over the selected experts (GShard).
    gates = (
        top_p
        if K == 1
        else top_p / jnp.clip(top_p.sum(axis=-1, keepdims=True), 1e-9, None)
    )

    capacity = max(1, int(capacity_factor * T * K / E))
    # Flatten choice-major: all first choices precede all second choices, so
    # the stable sort gives first choices capacity priority within experts
    # (and token order within the same choice rank, matching the dense
    # oracle's cumsum order for top-1).
    e_flat = top_e.T.reshape(-1)  # (K*T,)
    g_flat = gates.T.reshape(-1)
    t_flat = jnp.tile(jnp.arange(T), K)
    order = jnp.argsort(e_flat, stable=True)
    e_s = e_flat[order]
    t_s = t_flat[order]
    g_s = g_flat[order]
    # Position of each entry in its expert's queue.
    seg_start = jnp.searchsorted(e_s, jnp.arange(E))  # (E,)
    pos = jnp.arange(T * K) - seg_start[e_s]
    keep = pos < capacity
    pos_c = jnp.clip(pos, 0, capacity - 1)

    cdt = jnp.dtype(compute_dtype)
    keep_f = keep.astype(jnp.float32)[:, None]
    gathered = tokens.astype(jnp.float32)[t_s] * keep_f  # (K*T, D)
    expert_in = (
        jnp.zeros((E, capacity, D), jnp.float32).at[e_s, pos_c].add(gathered)
    ).astype(cdt)
    h = jax.nn.gelu(
        jnp.einsum("ecd,edf->ecf", expert_in, params["wi"].astype(cdt))
        + params["bi"][:, None, :].astype(cdt)
    )
    expert_out = jnp.einsum(
        "ecf,efd->ecd", h, params["wo"].astype(cdt)
    ) + params["bo"][:, None, :].astype(cdt)
    contrib = (
        expert_out.astype(jnp.float32)[e_s, pos_c]
        * (g_s[:, None] * keep_f)
    )  # (K*T, D)
    out = jnp.zeros((T, D), jnp.float32).at[t_s].add(contrib)

    # Load-balance aux loss + drop-rate metric (all K choices weighted).
    load = (
        jnp.zeros((E,), jnp.float32).at[e_flat].add(jnp.ones(T * K)) / (T * K)
    )
    importance = probs.mean(axis=0)
    aux_loss = E * jnp.sum(load * importance)
    dropped = 1.0 - keep.astype(jnp.float32).sum() / (T * K)
    return out.reshape(B, S, D).astype(x.dtype), {
        "aux_loss": aux_loss,
        "dropped": dropped,
    }


def moe_ffn_dense(
    params: Dict[str, jax.Array],
    x: jax.Array,
    capacity_factor: float = 1.25,
    compute_dtype: Any = jnp.float32,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Dense one-hot einsum dispatch (top-1 only) — the readable oracle.

    O(T·E·C) dispatch/combine tensors; kept for equivalence tests and tiny
    expert counts.
    """
    B, S, D = x.shape
    E = params["router"].shape[1]
    tokens = x.reshape(B * S, D)
    # Router in fp32 for stable softmax.
    logits = tokens.astype(jnp.float32) @ params["router"]  # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)  # (T,)
    gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=1)[:, 0]

    T = B * S
    capacity = max(1, int(capacity_factor * T / E))
    onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # (T, E)
    # Position of each token within its expert's queue; tokens past
    # capacity are dropped (residual passes through untouched).
    pos_in_expert = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # (T, E)
    keep = (pos_in_expert < capacity) & (onehot > 0)  # (T, E) bool
    pos = jnp.where(keep, pos_in_expert, 0.0).astype(jnp.int32)
    pos_onehot = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)  # (T, E, C)
    dispatch = pos_onehot * keep[..., None].astype(jnp.float32)  # (T, E, C)

    # Dispatch tokens to (E, C, D) expert buffers, run experts batched on
    # the leading (sharded) expert dim, combine back weighted by the gate.
    cdt = jnp.dtype(compute_dtype)
    expert_in = jnp.einsum(
        "tec,td->ecd", dispatch, tokens.astype(jnp.float32)
    ).astype(cdt)
    h = jax.nn.gelu(
        jnp.einsum("ecd,edf->ecf", expert_in, params["wi"].astype(cdt))
        + params["bi"][:, None, :].astype(cdt)
    )
    expert_out = jnp.einsum(
        "ecf,efd->ecd", h, params["wo"].astype(cdt)
    ) + params["bo"][:, None, :].astype(cdt)
    combine = dispatch * gate[:, None, None]
    out = jnp.einsum(
        "tec,ecd->td", combine, expert_out.astype(jnp.float32)
    )

    # Load-balance aux loss + drop-rate metric.
    load = onehot.mean(axis=0)  # fraction routed per expert
    importance = probs.mean(axis=0)
    aux_loss = E * jnp.sum(load * importance)
    dropped = 1.0 - keep.astype(jnp.float32).sum() / T
    return out.reshape(B, S, D).astype(x.dtype), {
        "aux_loss": aux_loss,
        "dropped": dropped,
    }
