"""Mixture-of-Experts layer with expert parallelism over a mesh axis.

Beyond-parity capability (the reference has no model code at all,
SURVEY.md §2c): a switch-style MoE feed-forward whose expert weights
carry a leading ``experts`` dim annotated with the "expert" logical axis —
mapped by GSPMDStrategy to the "ep" mesh axis, so each ep rank holds
E/ep_size experts and XLA routes tokens between ranks (the all-to-all
pattern) from the shardings alone.

Two dispatch implementations:

- ``moe_ffn`` (default, sort-based): tokens are grouped by expert with one
  stable argsort and moved with gather/scatter-add — O(T·K·D + E·C·D)
  memory, supports top-1 and top-2 routing. Static shapes throughout
  (argsort/scatter are XLA-native), so it jits and shards like any other op.
- ``moe_ffn_dense``: the original one-hot einsum formulation, O(T·E·C)
  dispatch tensors. Kept as the readable oracle the tests check the sparse
  path against, and as a fallback for tiny expert counts where the dense
  einsum fuses better.

Capacity factoring drops overflow tokens (standard switch behavior) to keep
per-expert compute static; with the stable sort, earlier tokens win expert
slots in both implementations, so top-1 sparse == dense exactly.
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from ray_lightning_tpu.utils.compat import shard_map


def init_moe_params(
    rng: jax.Array,
    n_experts: int,
    d_model: int,
    d_ff: int,
    std: float = 0.02,
    res_std: float = 0.02,
    mlp_variant: str = "gelu",
) -> Dict[str, jax.Array]:
    if mlp_variant not in ("gelu", "swiglu"):
        raise ValueError(
            f"unknown mlp_variant {mlp_variant!r}; use 'gelu' or 'swiglu'"
        )
    k1, k2, k3 = jax.random.split(rng, 3)
    if mlp_variant == "swiglu":
        # Mixtral-style experts: gate/up stacked (E, D, 2, F) — same
        # co-sharded packing as the dense decoder's SwiGLU.
        wi = (
            jax.random.normal(k2, (n_experts, d_model, 2, d_ff)) * std
        ).astype(jnp.float32)
        bi = jnp.zeros((n_experts, 2, d_ff))
    else:
        wi = (
            jax.random.normal(k2, (n_experts, d_model, d_ff)) * std
        ).astype(jnp.float32)
        bi = jnp.zeros((n_experts, d_ff))
    return {
        "router": (jax.random.normal(k1, (d_model, n_experts)) * std).astype(
            jnp.float32
        ),
        "wi": wi,
        "bi": bi,
        "wo": (
            jax.random.normal(k3, (n_experts, d_ff, d_model)) * res_std
        ).astype(jnp.float32),
        "bo": jnp.zeros((n_experts, d_model)),
    }


def _route_and_pack(
    tokens: jax.Array, router: jax.Array, top_k: int, capacity: int
) -> Tuple[jax.Array, ...]:
    """Shared routing + sort-based queue packing for the sparse dispatchers.

    tokens (T, D), router (D, E) -> (probs, e_flat, e_s, t_s, g_s, keep,
    pos_c): choice-major flattened assignments (e_flat unsorted, for load
    stats), stable-argsorted by expert (first choices outrank seconds,
    token order within a choice — the dense oracle's priority), with
    per-expert queue positions clipped to ``capacity``. Any routing-rule
    change lives HERE so the in-place (:func:`moe_ffn`) and
    expert-parallel (:func:`moe_ffn_ep`) paths cannot drift apart."""
    T = tokens.shape[0]
    E = router.shape[1]
    K = int(top_k)
    logits = tokens.astype(jnp.float32) @ router  # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_e = jax.lax.top_k(probs, K)  # (T, K)
    # Switch top-1 gates with the raw router prob (dense-oracle semantics);
    # top-k>1 renormalizes over the selected experts (GShard).
    gates = (
        top_p
        if K == 1
        else top_p / jnp.clip(top_p.sum(axis=-1, keepdims=True), 1e-9, None)
    )
    e_flat = top_e.T.reshape(-1)  # (K*T,)
    g_flat = gates.T.reshape(-1)
    t_flat = jnp.tile(jnp.arange(T), K)
    order = jnp.argsort(e_flat, stable=True)
    e_s = e_flat[order]
    t_s = t_flat[order]
    g_s = g_flat[order]
    seg_start = jnp.searchsorted(e_s, jnp.arange(E))  # (E,)
    pos = jnp.arange(T * K) - seg_start[e_s]
    keep = pos < capacity
    pos_c = jnp.clip(pos, 0, capacity - 1)
    return probs, e_flat, e_s, t_s, g_s, keep, pos_c


def _expert_ffn(
    expert_in: jax.Array, params: Dict[str, jax.Array], cdt: Any
) -> jax.Array:
    """(E, C, D) expert batches -> (E, C, D). Gelu MLP, or SwiGLU experts
    (Mixtral-style) when ``wi`` carries the stacked gate/up axis
    (E, D, 2, F) — the same (co-sharded) packing the dense decoder uses.
    One definition serves the in-place, expert-parallel, and dense-oracle
    dispatchers."""
    wi = params["wi"]
    if wi.ndim == 4:  # (E, D, 2, F): SwiGLU experts
        z = jnp.einsum(
            "ecd,edgf->ecgf", expert_in, wi.astype(cdt)
        ) + params["bi"][:, None].astype(cdt)
        h = jax.nn.silu(z[..., 0, :]) * z[..., 1, :]
    else:
        h = jax.nn.gelu(
            jnp.einsum("ecd,edf->ecf", expert_in, wi.astype(cdt))
            + params["bi"][:, None, :].astype(cdt)
        )
    return jnp.einsum(
        "ecf,efd->ecd", h, params["wo"].astype(cdt)
    ) + params["bo"][:, None, :].astype(cdt)


def moe_ffn(
    params: Dict[str, jax.Array],
    x: jax.Array,
    capacity_factor: float = 1.25,
    compute_dtype: Any = jnp.float32,
    top_k: int = 1,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Sort-based MoE feed-forward with top-k routing (default top-1).

    x: (B, S, D) -> (B, S, D), plus aux metrics {"aux_loss", "dropped"}.
    ``aux_loss`` is the load-balancing loss of Shazeer et al. (mean expert
    load x mean router prob, scaled by E); add it to the task loss.

    Dispatch memory is O(T·K·D + E·C·D): one stable argsort groups the
    (token, expert) assignments by expert, positions within each expert
    queue come from a searchsorted offset, and tokens move via gather +
    scatter-add — no (T, E, C) one-hot tensors. For ``top_k=2`` every
    first-choice assignment outranks all second choices for capacity
    (GShard-style priority), and gates are renormalized over the kept
    choices' router probabilities.
    """
    B, S, D = x.shape
    E = params["router"].shape[1]
    T = B * S
    K = int(top_k)
    tokens = x.reshape(T, D)
    capacity = max(1, int(capacity_factor * T * K / E))
    probs, e_flat, e_s, t_s, g_s, keep, pos_c = _route_and_pack(
        tokens, params["router"], K, capacity
    )

    cdt = jnp.dtype(compute_dtype)
    keep_f = keep.astype(jnp.float32)[:, None]
    gathered = tokens.astype(jnp.float32)[t_s] * keep_f  # (K*T, D)
    expert_in = (
        jnp.zeros((E, capacity, D), jnp.float32).at[e_s, pos_c].add(gathered)
    ).astype(cdt)
    expert_out = _expert_ffn(expert_in, params, cdt)
    contrib = (
        expert_out.astype(jnp.float32)[e_s, pos_c]
        * (g_s[:, None] * keep_f)
    )  # (K*T, D)
    out = jnp.zeros((T, D), jnp.float32).at[t_s].add(contrib)

    # Load-balance aux loss + drop-rate metric (all K choices weighted).
    load = (
        jnp.zeros((E,), jnp.float32).at[e_flat].add(jnp.ones(T * K)) / (T * K)
    )
    importance = probs.mean(axis=0)
    aux_loss = E * jnp.sum(load * importance)
    dropped = 1.0 - keep.astype(jnp.float32).sum() / (T * K)
    return out.reshape(B, S, D).astype(x.dtype), {
        "aux_loss": aux_loss,
        "dropped": dropped,
    }


def moe_ffn_ep(
    params: Dict[str, jax.Array],
    x: jax.Array,
    mesh: Any = None,
    ep_axis: str = "ep",
    capacity_factor: float = 1.25,
    compute_dtype: Any = jnp.float32,
    top_k: int = 1,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Expert-parallel MoE with an EXPLICIT token all-to-all over ``ep``.

    Why this exists: leaving the sort-based dispatch to GSPMD with
    ep-sharded expert weights lowers to all-gathers + all-reduces (checked
    on the compiled HLO: 6 all-gathers, 12 all-reduces, ZERO all-to-alls) —
    every ep rank materializes full-size dispatch buffers, so dispatch
    traffic does not shrink as the ep axis grows. The scalable TPU design
    (GShard; "How to Scale Your Model" ch. MoE) shards the TOKENS over ep
    too and exchanges only routed tokens with ``lax.all_to_all`` riding
    ICI: per-rank traffic drops from O(T·D) to O(T·K·D/ep) each way.

    Layout contract (per ``shard_map`` over the ``ep`` axis only; other
    mesh axes stay under GSPMD inside):
      - ``x`` (B, S, D): B divides by ep; each rank takes its B/ep slice
        (free: x is ep-replicated at entry), routes its local tokens, and
        builds per-expert send queues of quota C_src = cf·T_local·K/E.
      - one all-to-all ships (E_local, ep·C_src, D) expert batches to the
        owning ranks; experts run on their local shard; a second
        all-to-all ships contributions back. The OUTPUT STAYS EP-SHARDED
        on the batch dim (out_specs P(ep)): the consumer's next op makes
        GSPMD insert any layout-restoring gather exactly where needed
        (the compiled dispatch itself carries zero all-gathers, asserted
        in tests).
      - capacity semantics: per-expert capacity C = ep·C_src is enforced
        as the concatenation of per-SOURCE-rank quotas (each rank may fill
        at most C_src slots of any expert), vs the single-queue semantics
        of :func:`moe_ffn`. With drop-free capacity both reduce to the
        exact mixture, asserted against the dense oracle in tests.
      - aux loss / drop metrics are psum'd over ep: identical to the
        single-device statistics (router probs are token-local).

    Top-1 and top-k routing follow :func:`moe_ffn` (same gating math).

    ``mesh=None`` resolves the CONTEXT abstract mesh — the way to call
    this inside another shard_map (e.g. a pipeline stage, where the pp
    axis is already manual): nested shard_maps must be built on the
    context mesh, whose already-manual axes differ from the concrete
    mesh's.
    """
    if mesh is None:
        mesh = jax.sharding.get_abstract_mesh()
    if ep_axis not in mesh.shape:
        raise ValueError(
            f"moe_ffn_ep needs a mesh with an {ep_axis!r} axis; got mesh "
            f"axes {tuple(mesh.shape)} (pass mesh= explicitly or call "
            "under a mesh context that defines it)"
        )
    B, S, D = x.shape
    E = params["router"].shape[1]
    ep = mesh.shape[ep_axis]
    if E % ep:
        raise ValueError(f"n_experts {E} must divide by ep axis {ep}")
    if B % ep:
        raise ValueError(
            f"batch {B} must divide by ep axis {ep} for all-to-all MoE "
            "dispatch (moe_dispatch='gspmd' lifts the constraint)"
        )
    E_local = E // ep
    K = int(top_k)
    cdt = jnp.dtype(compute_dtype)

    def per_rank(router, wi, bi, wo, bo, x_l):
        # x_l: (B/ep, S, D) — this rank's token shard.
        T_l = x_l.shape[0] * x_l.shape[1]
        tokens = x_l.reshape(T_l, D)
        c_src = max(1, int(capacity_factor * T_l * K / E))
        probs, e_flat, e_s, t_s, g_s, keep, pos_c = _route_and_pack(
            tokens, router, K, c_src
        )

        keep_f = keep.astype(jnp.float32)[:, None]
        gathered = tokens.astype(jnp.float32)[t_s] * keep_f
        # Build the queues in fp32 (scatter-add determinism), ship in the
        # compute dtype: both all_to_alls carry cdt-width payloads — with
        # bf16 that halves the ICI bytes this path exists to minimize, and
        # costs nothing numerically (the expert matmuls consume cdt either
        # way; the cast just moves before the wire).
        send = (
            jnp.zeros((E, c_src, D), jnp.float32).at[e_s, pos_c].add(gathered)
        ).astype(cdt)
        # (E, C_src, D) -> (ep, E_local, C_src, D) -> a2a -> source-major
        # (ep, E_local, C_src, D): dim 0 now indexes the SOURCE rank.
        send = send.reshape(ep, E_local, c_src, D)
        recv = jax.lax.all_to_all(
            send, ep_axis, split_axis=0, concat_axis=0, tiled=False
        )
        # recv: (src, E_local, c, D) — bring experts to the front before
        # collapsing the (src, c) slots (a bare reshape would interleave
        # different experts' queues).
        expert_in = recv.transpose(1, 0, 2, 3).reshape(
            E_local, ep * c_src, D
        )
        expert_out = _expert_ffn(
            expert_in, {"wi": wi, "bi": bi, "wo": wo, "bo": bo}, cdt
        )
        # Ship contributions back to their source ranks (reverse a2a), still
        # cdt-wide — the fp32 upcast happens at the local combine:
        # (E_local, src*c, D) -> (src, E_local, c, D), send chunk src back
        # to its rank; the received (owner, E_local, c, D) flattens to the
        # global (E, c, D) queue order this rank built.
        back = jax.lax.all_to_all(
            expert_out.reshape(E_local, ep, c_src, D).transpose(1, 0, 2, 3),
            ep_axis,
            split_axis=0,
            concat_axis=0,
            tiled=False,
        ).reshape(E, c_src, D)
        contrib = back.astype(jnp.float32)[e_s, pos_c] * (
            g_s[:, None] * keep_f
        )
        out_l = jnp.zeros((T_l, D), jnp.float32).at[t_s].add(contrib)
        out_l = out_l.reshape(x_l.shape).astype(x_l.dtype)

        # Global routing statistics: psum the local sums over ep.
        load_cnt = jnp.zeros((E,), jnp.float32).at[e_flat].add(
            jnp.ones(T_l * K)
        )
        load_cnt = jax.lax.psum(load_cnt, ep_axis)
        imp_sum = jax.lax.psum(probs.sum(axis=0), ep_axis)
        kept = jax.lax.psum(keep.astype(jnp.float32).sum(), ep_axis)
        t_total = jnp.float32(T_l * ep)
        aux_loss = E * jnp.sum(
            (load_cnt / (t_total * K)) * (imp_sum / t_total)
        )
        dropped = 1.0 - kept / (t_total * K)
        return out_l, aux_loss, dropped

    from jax.sharding import PartitionSpec as P

    out, aux_loss, dropped = shard_map(
        per_rank,
        mesh=mesh,
        in_specs=(
            P(),  # router replicated
            P(ep_axis),  # wi: experts sharded
            P(ep_axis),
            P(ep_axis),
            P(ep_axis),
            P(ep_axis),  # x: batch dim sliced over ep (free at entry)
        ),
        # The output stays ep-sharded on the batch dim: the consumer's
        # residual add forces GSPMD to insert the layout-restoring gather
        # exactly where it is needed (often fused with the add), instead
        # of an unconditional all_gather here.
        out_specs=(P(ep_axis), P(), P()),
        axis_names={ep_axis},
    )(
        params["router"],
        params["wi"],
        params["bi"],
        params["wo"],
        params["bo"],
        x,
    )
    return out, {"aux_loss": aux_loss, "dropped": dropped}


def moe_ffn_dense(
    params: Dict[str, jax.Array],
    x: jax.Array,
    capacity_factor: float = 1.25,
    compute_dtype: Any = jnp.float32,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Dense one-hot einsum dispatch (top-1 only) — the readable oracle.

    O(T·E·C) dispatch/combine tensors; kept for equivalence tests and tiny
    expert counts.
    """
    B, S, D = x.shape
    E = params["router"].shape[1]
    tokens = x.reshape(B * S, D)
    # Router in fp32 for stable softmax.
    logits = tokens.astype(jnp.float32) @ params["router"]  # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)  # (T,)
    gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=1)[:, 0]

    T = B * S
    capacity = max(1, int(capacity_factor * T / E))
    onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # (T, E)
    # Position of each token within its expert's queue; tokens past
    # capacity are dropped (residual passes through untouched).
    pos_in_expert = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # (T, E)
    keep = (pos_in_expert < capacity) & (onehot > 0)  # (T, E) bool
    pos = jnp.where(keep, pos_in_expert, 0.0).astype(jnp.int32)
    pos_onehot = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)  # (T, E, C)
    dispatch = pos_onehot * keep[..., None].astype(jnp.float32)  # (T, E, C)

    # Dispatch tokens to (E, C, D) expert buffers, run experts batched on
    # the leading (sharded) expert dim, combine back weighted by the gate.
    cdt = jnp.dtype(compute_dtype)
    expert_in = jnp.einsum(
        "tec,td->ecd", dispatch, tokens.astype(jnp.float32)
    ).astype(cdt)
    expert_out = _expert_ffn(expert_in, params, cdt)
    combine = dispatch * gate[:, None, None]
    out = jnp.einsum(
        "tec,ecd->td", combine, expert_out.astype(jnp.float32)
    )

    # Load-balance aux loss + drop-rate metric.
    load = onehot.mean(axis=0)  # fraction routed per expert
    importance = probs.mean(axis=0)
    aux_loss = E * jnp.sum(load * importance)
    dropped = 1.0 - keep.astype(jnp.float32).sum() / T
    return out.reshape(B, S, D).astype(x.dtype), {
        "aux_loss": aux_loss,
        "dropped": dropped,
    }
