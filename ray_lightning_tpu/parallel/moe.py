"""Mixture-of-Experts layer with expert parallelism over a mesh axis.

Beyond-parity capability (the reference has no model code at all,
SURVEY.md §2c): a switch-style (top-1) MoE feed-forward whose expert weights
carry a leading ``experts`` dim annotated with the "expert" logical axis —
mapped by GSPMDStrategy to the "ep" mesh axis, so each ep rank holds
E/ep_size experts and XLA routes tokens between ranks (the all-to-all
pattern) from the shardings alone.

The dispatch is expressed densely with einsums (one-hot combine weights)
rather than gather/scatter: static shapes, MXU-friendly, differentiable,
and the partitioner can optimize the routing communication. Capacity
factoring drops overflow tokens (standard switch behavior) to keep per-
expert compute static.
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp


def init_moe_params(
    rng: jax.Array,
    n_experts: int,
    d_model: int,
    d_ff: int,
    std: float = 0.02,
    res_std: float = 0.02,
) -> Dict[str, jax.Array]:
    k1, k2, k3 = jax.random.split(rng, 3)
    return {
        "router": (jax.random.normal(k1, (d_model, n_experts)) * std).astype(
            jnp.float32
        ),
        "wi": (
            jax.random.normal(k2, (n_experts, d_model, d_ff)) * std
        ).astype(jnp.float32),
        "bi": jnp.zeros((n_experts, d_ff)),
        "wo": (
            jax.random.normal(k3, (n_experts, d_ff, d_model)) * res_std
        ).astype(jnp.float32),
        "bo": jnp.zeros((n_experts, d_model)),
    }


def moe_logical_axes() -> Dict[str, Tuple]:
    return {
        "router": ("embed", None),
        "wi": ("expert", "embed", "mlp"),
        "bi": ("expert", "mlp"),
        "wo": ("expert", "mlp", "embed"),
        "bo": ("expert", None),
    }


def moe_ffn(
    params: Dict[str, jax.Array],
    x: jax.Array,
    capacity_factor: float = 1.25,
    compute_dtype: Any = jnp.float32,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Switch (top-1) MoE feed-forward.

    x: (B, S, D) -> (B, S, D), plus aux metrics {"aux_loss", "dropped"}.
    ``aux_loss`` is the load-balancing loss of Shazeer et al. (mean expert
    load x mean router prob, scaled by E); add it to the task loss.
    """
    B, S, D = x.shape
    E = params["router"].shape[1]
    tokens = x.reshape(B * S, D)
    # Router in fp32 for stable softmax.
    logits = tokens.astype(jnp.float32) @ params["router"]  # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)  # (T,)
    gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=1)[:, 0]

    T = B * S
    capacity = max(1, int(capacity_factor * T / E))
    onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # (T, E)
    # Position of each token within its expert's queue; tokens past
    # capacity are dropped (residual passes through untouched).
    pos_in_expert = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # (T, E)
    keep = (pos_in_expert < capacity) & (onehot > 0)  # (T, E) bool
    pos = jnp.where(keep, pos_in_expert, 0.0).astype(jnp.int32)
    pos_onehot = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)  # (T, E, C)
    dispatch = pos_onehot * keep[..., None].astype(jnp.float32)  # (T, E, C)

    # Dispatch tokens to (E, C, D) expert buffers, run experts batched on
    # the leading (sharded) expert dim, combine back weighted by the gate.
    cdt = jnp.dtype(compute_dtype)
    expert_in = jnp.einsum(
        "tec,td->ecd", dispatch, tokens.astype(jnp.float32)
    ).astype(cdt)
    h = jax.nn.gelu(
        jnp.einsum("ecd,edf->ecf", expert_in, params["wi"].astype(cdt))
        + params["bi"][:, None, :].astype(cdt)
    )
    expert_out = jnp.einsum(
        "ecf,efd->ecd", h, params["wo"].astype(cdt)
    ) + params["bo"][:, None, :].astype(cdt)
    combine = dispatch * gate[:, None, None]
    out = jnp.einsum(
        "tec,ecd->td", combine, expert_out.astype(jnp.float32)
    )

    # Load-balance aux loss + drop-rate metric.
    load = onehot.mean(axis=0)  # fraction routed per expert
    importance = probs.mean(axis=0)
    aux_loss = E * jnp.sum(load * importance)
    dropped = 1.0 - keep.astype(jnp.float32).sum() / T
    return out.reshape(B, S, D).astype(x.dtype), {
        "aux_loss": aux_loss,
        "dropped": dropped,
    }
