"""Device mesh construction.

Replaces the reference's process-group bootstrap
(torch.distributed.init_process_group at ray_ddp.py:192-196): after
``jax.distributed.initialize``, every process sees the global device list and
builds the same Mesh; XLA routes collectives over ICI within a slice and DCN
across slices based on the mesh axes.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh


def local_chip_count() -> int:
    return len(jax.local_devices())


def build_mesh(
    axis_shape: Optional[Sequence[int]] = None,
    axis_names: Tuple[str, ...] = ("data",),
) -> Mesh:
    """Build a Mesh over all global devices.

    Default: 1-D "data" mesh over every chip (pure DP). Multi-axis shapes
    (e.g. ``(dp, model)``) carve the same device list for DP x TP/FSDP; on
    multi-host topologies the leading axis should span hosts so per-step DP
    all-reduces ride ICI within a host first.

    Axes are ``Auto`` (GSPMD propagation): the strategies annotate inputs
    with NamedShardings and let the partitioner infer the rest — newer JAX
    defaults to ``Explicit`` sharding-in-types, which rejects the
    ZeRO-style mixed shardings these strategies rely on.
    """
    devices = jax.devices()
    if axis_shape is None:
        axis_shape = (len(devices),)
    total = 1
    for s in axis_shape:
        total *= s
    if total != len(devices):
        raise ValueError(
            f"mesh shape {tuple(axis_shape)} needs {total} devices, "
            f"have {len(devices)}"
        )
    if hasattr(jax.sharding, "AxisType"):
        axis_types = (jax.sharding.AxisType.Auto,) * len(axis_names)
        return jax.make_mesh(
            tuple(axis_shape), axis_names, axis_types=axis_types
        )
    # Older JAX (< 0.5): no sharding-in-types; every axis is already Auto.
    return jax.make_mesh(tuple(axis_shape), axis_names)


def setup_distributed(env) -> None:
    """Rendezvous this process with its peers (no-op single-host)."""
    if not env.is_distributed:
        return
    jax.distributed.initialize(
        coordinator_address=env.coordinator_address,
        num_processes=env.num_hosts,
        process_id=env.host_rank,
    )
