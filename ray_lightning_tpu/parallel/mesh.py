"""Device mesh construction.

Replaces the reference's process-group bootstrap
(torch.distributed.init_process_group at ray_ddp.py:192-196): after
``jax.distributed.initialize``, every process sees the global device list and
builds the same Mesh; XLA routes collectives over ICI within a slice and DCN
across slices based on the mesh axes.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh


def local_chip_count() -> int:
    return len(jax.local_devices())


def parse_mesh_spec(spec: Union[str, int, None]) -> Tuple[int, int]:
    """Parse a serving mesh spec ``"MODELxDATA"`` into ``(model, data)``.

    The serve CLI's ``--serve.mesh`` vocabulary: ``"1x1"`` (single
    device), ``"4x1"`` (4-way tensor parallel), ``"4x2"``, or a bare
    integer/``"8"`` meaning ``8x1`` (model axis only — YAML coerces the
    undecorated form to int). Rejects anything else up front with the
    valid vocabulary, so a malformed flag fails before checkpoints load
    or replicas spawn. Whether the sizes actually factor the device
    count is :func:`build_mesh`'s check — that needs live devices, this
    one doesn't.
    """
    if spec is None:
        return (1, 1)
    if isinstance(spec, bool):  # YAML 1.1: a bare "on"/"off" typo
        raise ValueError(
            f"malformed mesh spec {spec!r}: use 'MODELxDATA' (e.g. '1x1', "
            "'4x1', '4x2') or a bare model-axis size like '8'"
        )
    if isinstance(spec, int):
        parts: Tuple[Union[str, int], ...] = (spec, 1)
    else:
        text = str(spec).strip().lower()
        parts = tuple(text.split("x")) if text else ()
        if len(parts) == 1:
            parts = (parts[0], 1)
    try:
        if len(parts) != 2:
            raise ValueError
        model, data = (int(p) for p in parts)
    except (TypeError, ValueError):
        raise ValueError(
            f"malformed mesh spec {spec!r}: use 'MODELxDATA' with positive "
            "integer axis sizes (e.g. '1x1', '4x1', '4x2'), or a bare "
            "model-axis size like '8'"
        ) from None
    if model < 1 or data < 1:
        raise ValueError(
            f"malformed mesh spec {spec!r}: 'MODELxDATA' axis sizes must "
            "be >= 1 (e.g. '1x1', '4x1', '4x2')"
        )
    return model, data


def mesh_from_spec(spec: Union[str, int, None]) -> Optional[Mesh]:
    """A serving ``("model", "data")`` mesh from a ``"MODELxDATA"`` spec.

    ``None``/``"1x1"`` (one device total) returns None — the engine's
    single-device path, byte-for-byte the pre-mesh behavior. Anything
    larger builds a mesh over ALL global devices; the sizes must factor
    the device count exactly (:func:`build_mesh` raises the friendly
    error naming both otherwise).
    """
    model, data = parse_mesh_spec(spec)
    if model * data == 1:
        return None
    return build_mesh((model, data), ("model", "data"))


def build_mesh(
    axis_shape: Optional[Sequence[int]] = None,
    axis_names: Tuple[str, ...] = ("data",),
) -> Mesh:
    """Build a Mesh over all global devices.

    Default: 1-D "data" mesh over every chip (pure DP). Multi-axis shapes
    (e.g. ``(dp, model)``) carve the same device list for DP x TP/FSDP; on
    multi-host topologies the leading axis should span hosts so per-step DP
    all-reduces ride ICI within a host first.

    Axes are ``Auto`` (GSPMD propagation): the strategies annotate inputs
    with NamedShardings and let the partitioner infer the rest — newer JAX
    defaults to ``Explicit`` sharding-in-types, which rejects the
    ZeRO-style mixed shardings these strategies rely on.
    """
    devices = jax.devices()
    if axis_shape is None:
        axis_shape = (len(devices),)
    total = 1
    for s in axis_shape:
        total *= s
    if total != len(devices):
        named = ", ".join(
            f"{n}={s}" for n, s in zip(axis_names, axis_shape)
        )
        raise ValueError(
            f"mesh shape ({named}) covers {total} device(s) but this "
            f"process sees {len(devices)}: the axis sizes must multiply to "
            f"EXACTLY the global device count. Pick sizes that factor "
            f"{len(devices)} (e.g. shrink an axis), or change the device "
            f"count — on CPU, virtual devices come from "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={total} set "
            f"before jax initializes."
        )
    if hasattr(jax.sharding, "AxisType"):
        axis_types = (jax.sharding.AxisType.Auto,) * len(axis_names)
        return jax.make_mesh(
            tuple(axis_shape), axis_names, axis_types=axis_types
        )
    # Older JAX (< 0.5): no sharding-in-types; every axis is already Auto.
    return jax.make_mesh(tuple(axis_shape), axis_names)


def setup_distributed(env) -> None:
    """Rendezvous this process with its peers (no-op single-host)."""
    if not env.is_distributed:
        return
    jax.distributed.initialize(
        coordinator_address=env.coordinator_address,
        num_processes=env.num_hosts,
        process_id=env.host_rank,
    )
