"""Logical-axis sharding rules (t5x/flax-style) for multi-axis meshes.

The reference's parallelism is pure DP (SURVEY.md §2c) so it never needs a
notion of *which tensor axis maps to which mesh axis*. A TPU-native GSPMD
strategy does: models annotate each parameter axis with a logical name
("embed", "heads", "mlp", ...) and the strategy maps logical names to mesh
axes ("data", "fsdp", "model", "seq") through a rule list. This decouples
model code from the physical mesh: the same model runs pure-DP, FSDP, TP, or
any combination by changing rules only.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Default rules, checked in order. First rule whose mesh axis exists in the
# mesh *and* divides the tensor dim wins. "embed"->fsdp gives ZeRO-3-style
# parameter sharding; "heads"/"mlp"/"vocab"->model is megatron-style TP.
DEFAULT_RULES: Tuple[Tuple[str, str], ...] = (
    ("batch", "data"),
    ("batch", "fsdp"),
    ("vocab", "model"),
    ("heads", "model"),
    ("mlp", "model"),
    ("embed", "fsdp"),
    ("kv", None),
    ("layers", "pp"),  # pipeline stages when the mesh has a pp axis...
    ("layers", None),  # ...replicated otherwise (terminal)
    ("seq", "seq"),
    ("expert", "ep"),
)


def spec_from_logical(
    shape: Sequence[int],
    logical_axes: Sequence[Optional[str]],
    rules: Sequence[Tuple[str, Optional[str]]],
    mesh: Mesh,
) -> P:
    """Resolve one tensor's logical axis names to a PartitionSpec.

    Rules are checked in order per logical name. A ``(name, None)`` rule is
    *terminal*: it pins that logical axis replicated (the t5x-style
    first-match-wins override — prepend ``('heads', None)`` to keep heads
    unsharded). A rule whose mesh axis is absent, has size 1, does not
    divide the tensor dim, or was already used by an earlier tensor axis (a
    mesh axis may appear at most once per spec) falls through to the next
    matching rule.
    """
    if len(shape) != len(logical_axes):
        raise ValueError(
            f"shape {tuple(shape)} has {len(shape)} dims but "
            f"{len(logical_axes)} logical axes {tuple(logical_axes)}"
        )
    used: set = set()
    spec: list = []
    for dim_size, logical in zip(shape, logical_axes):
        assigned = None
        if logical is not None:
            for name, mesh_axis in rules:
                if name != logical:
                    continue
                if mesh_axis is None:
                    break  # explicit replicate — terminal
                size = mesh.shape.get(mesh_axis, 1)
                if size <= 1 or mesh_axis in used:
                    continue
                if dim_size % size:
                    continue
                assigned = mesh_axis
                used.add(mesh_axis)
                break
        spec.append(assigned)
    return P(*spec)


def tree_logical_shardings(
    tree: Any,
    logical_tree: Any,
    mesh: Mesh,
    rules: Optional[Sequence[Tuple[str, Optional[str]]]] = None,
) -> Any:
    """Pytree of NamedShardings from a matching pytree of logical-axis tuples."""
    rules = tuple(rules) if rules is not None else DEFAULT_RULES

    def leaf(x: Any, axes: Any) -> NamedSharding:
        # ``axes`` is a tuple of per-dim logical names (None entries =
        # replicate that dim). tree_map stops descending at ``tree``'s leaf
        # positions (flatten_up_to), so the tuples survive intact.
        shape = np.shape(x)
        return NamedSharding(mesh, spec_from_logical(shape, axes, rules, mesh))

    return jax.tree_util.tree_map(leaf, tree, logical_tree)
