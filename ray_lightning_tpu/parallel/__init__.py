"""Parallelism substrate: device meshes, shardings, collectives, ZeRO rules.

The TPU-native replacement for the reference's delegation to
torch.distributed/NCCL/Horovod/FairScale (SURVEY.md §2b): rendezvous is
``jax.distributed.initialize``, gradient sync is a GSPMD-inserted (or
explicitly scheduled) XLA collective over the ICI mesh, and optimizer-state
sharding is a ``NamedSharding`` rule on the optimizer pytree.
"""
from ray_lightning_tpu.parallel.env import DistEnv
from ray_lightning_tpu.parallel.mesh import build_mesh, local_chip_count

__all__ = ["DistEnv", "build_mesh", "local_chip_count"]
