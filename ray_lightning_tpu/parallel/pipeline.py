"""SPMD pipeline parallelism (GPipe-style) over a "pp" mesh axis.

Beyond-parity capability (the reference's parallelism surface is DP-only,
SURVEY.md §2c). The stacked-layer parameter tree (every block leaf carries a
leading ``layers`` dim) is sharded over the "pp" axis, so each pipeline rank
holds L/P consecutive layers. Under ``shard_map`` (manual over "pp" only —
data/model/ep axes stay under GSPMD), every rank runs the same per-tick
program:

    tick t: rank 0 feeds microbatch t; every rank applies its local layers
    to its current activation; activations hop one rank down the pipeline
    via ``ppermute`` (ICI neighbor exchange).

After M + P - 1 ticks all M microbatches have drained; the last rank's
collected outputs are broadcast with a masked ``psum``. Built entirely from
``lax.scan`` + ``ppermute`` so the backward pass is the reverse pipeline
schedule by transposition — no hand-written backward needed.

The bubble fraction is the textbook (P-1)/(M+P-1); raise
``num_microbatches`` to amortize it. Fill/drain ticks where a rank holds
no real microbatch SKIP the layer compute via a per-rank ``lax.cond``
(the predicate is uniform across the model/data groups sharing a pp
stage, so GSPMD collectives inside the stage stay coherent) — the bubble
costs idle time, not redundant FLOPs.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ray_lightning_tpu.utils.compat import shard_map


def bubble_fraction(pp: int, num_microbatches: Optional[int] = None) -> float:
    """Textbook GPipe bubble: the share of the M+P-1 schedule ticks a rank
    spends without a real microbatch, (P-1)/(M+P-1)."""
    m = int(num_microbatches or pp)
    return (pp - 1) / (m + pp - 1)


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], Any],
    stacked_params: Any,
    x: jax.Array,
    mesh: Mesh,
    axis_name: str = "pp",
    num_microbatches: Optional[int] = None,
    with_aux: bool = False,
) -> Any:
    """Run ``x`` through L stacked layers pipelined over ``axis_name``.

    Args:
      stage_fn: applies ONE layer: ``stage_fn(layer_params, h) -> h`` with
        ``h`` (mb, S, D)-like. Scanned over each rank's local layer shard.
        With ``with_aux`` it returns ``(h, aux_scalar)`` instead — the MoE
        load-balancing loss rides this channel.
      stacked_params: pytree whose leaves have leading dim L, sharded
        ``P(axis_name)`` on that dim (the "layers" -> "pp" logical rule).
      x: global activations (B, ...), replicated w.r.t. the pp axis.
      num_microbatches: default P; B must divide by it.
      with_aux: when True, returns ``(activations, aux_total)`` where
        ``aux_total`` sums each layer's mean-over-microbatches aux scalar
        (fp32). Per-microbatch aux means match the unpipelined full-batch
        value exactly when routing statistics are microbatch-independent,
        and in expectation otherwise — the same contract gradient
        accumulation gives batch-statistic losses.

    Returns activations (B, ...) replicated w.r.t. the pp axis, plus the
    aux scalar when ``with_aux``.
    """
    pp = mesh.shape[axis_name]
    M = int(num_microbatches or pp)
    B = x.shape[0]
    if B % M:
        raise ValueError(f"batch {B} not divisible by num_microbatches {M}")

    param_specs = jax.tree_util.tree_map(lambda _: P(axis_name), stacked_params)

    def per_rank(blocks_local: Any, x_full: jax.Array):
        stage = jax.lax.axis_index(axis_name)
        mb = x_full.reshape(M, B // M, *x_full.shape[1:])

        def varying(v):
            # The scan carry genuinely differs per pp rank; mark it so for
            # shard_map's varying-mesh-axes type system.
            if hasattr(jax.lax, "pcast"):
                return jax.lax.pcast(v, (axis_name,), to="varying")
            if hasattr(jax.lax, "pvary"):
                return jax.lax.pvary(v, (axis_name,))
            return v  # pre-vma JAX (0.4.x): nothing to mark

        def apply_local(h: jax.Array) -> Tuple[jax.Array, jax.Array]:
            def body(carry, lp):
                h, a = carry
                if with_aux:
                    h2, da = stage_fn(lp, h)
                    return (h2, a + da.astype(jnp.float32)), None
                return (stage_fn(lp, h), a), None

            (h, a), _ = jax.lax.scan(
                body, (h, varying(jnp.zeros((), jnp.float32))), blocks_local
            )
            return h, a

        T = M + pp - 1
        perm = [(i, (i + 1) % pp) for i in range(pp)]

        zero = varying(jnp.zeros_like(mb[0]))
        outs0 = varying(jnp.zeros_like(mb))
        aux0 = varying(jnp.zeros((), jnp.float32))

        def tick(carry, t):
            recv, outs, aux_acc = carry
            feed = mb[jnp.clip(t, 0, M - 1)]
            inp = jnp.where(stage == 0, feed, recv)
            # Rank ``stage`` holds microbatch (t - stage) this tick; outside
            # [0, M) it's fill/drain garbage — skip the layer compute so the
            # bubble is idle time, not wasted FLOPs. Devices sharing a pp
            # stage (model/data/ep groups) share the predicate, so
            # collectives inside stage_fn stay coherent across the branch.
            valid = jnp.logical_and(t >= stage, t - stage <= M - 1)
            out, aux = jax.lax.cond(
                valid,
                apply_local,
                lambda h: (h, varying(jnp.zeros((), jnp.float32))),
                inp,
            )
            slot = t - (pp - 1)
            idx = jnp.clip(slot, 0, M - 1)
            collect = jnp.logical_and(stage == pp - 1, slot >= 0)
            outs = outs.at[idx].set(jnp.where(collect, out, outs[idx]))
            nxt = jax.lax.ppermute(out, axis_name, perm)
            return (nxt, outs, aux_acc + aux), None

        (_, outs, aux_local), _ = jax.lax.scan(
            tick, (zero, outs0, aux0), jnp.arange(T)
        )
        # Only the last stage holds real outputs; masked psum replicates
        # them across the pp axis (everyone else contributes zeros).
        outs = jax.lax.psum(
            jnp.where(stage == pp - 1, outs, jnp.zeros_like(outs)), axis_name
        )
        outs = outs.reshape(B, *x_full.shape[1:])
        if not with_aux:
            return outs
        # Every (layer, microbatch) pair contributed aux exactly once across
        # the ranks; the psum totals the layers and /M takes the microbatch
        # mean, matching the unpipelined per-layer full-batch scale.
        aux_total = jax.lax.psum(aux_local, axis_name) / M
        return outs, aux_total

    return shard_map(
        per_rank,
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=(P(), P()) if with_aux else P(),
        axis_names={axis_name},
    )(stacked_params, x)
