"""ZeRO-style sharding rules for parameter/optimizer pytrees.

The TPU-native replacement for FairScale's OSS/ShardedDDP, which the
reference inherits through PTL's ``DDPSpawnShardedStrategy``
(/root/reference/ray_lightning/ray_ddp_sharded.py:1-13): instead of a
C++/CUDA sharded optimizer, state is partitioned by GSPMD — each leaf is
annotated with a ``NamedSharding`` that splits its largest divisible axis
across the mesh's "data" axis, and XLA materializes the ZeRO gather/scatter
communication inside the compiled step.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_spec_for(shape, axis_size: int, axis_name: str = "data") -> P:
    """PartitionSpec splitting the largest axis divisible by ``axis_size``.

    Leaves too small (or with no divisible axis) stay replicated — the same
    pragmatic rule ZeRO implementations use to avoid padding overheads.
    """
    if not shape:
        return P()
    best_dim: Optional[int] = None
    best_size = 0
    for dim, size in enumerate(shape):
        if size % axis_size == 0 and size > best_size and size >= axis_size:
            best_dim = dim
            best_size = size
    if best_dim is None:
        return P()
    spec = [None] * len(shape)
    spec[best_dim] = axis_name
    return P(*spec)


def tree_shardings(
    tree: Any, mesh: Mesh, axis_name: str = "data"
) -> Any:
    """Pytree of NamedShardings mirroring ``tree``'s structure."""
    axis_size = mesh.shape[axis_name]

    def leaf_sharding(leaf: Any) -> NamedSharding:
        shape = np.shape(leaf)
        return NamedSharding(mesh, shard_spec_for(shape, axis_size, axis_name))

    return jax.tree_util.tree_map(leaf_sharding, tree)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def gather_to_host(tree: Any, mesh: Mesh) -> Any:
    """All-gather sharded leaves and return a full host-numpy pytree.

    The shared checkpoint-gather path for every sharded strategy
    (SURVEY.md §7 "checkpoint of sharded state"): a jitted identity with
    replicated out_shardings makes XLA emit the all-gathers, then the
    replicated copies are fetched to host.
    """
    import jax

    rep = NamedSharding(mesh, P())
    gathered = jax.jit(lambda t: t, out_shardings=rep)(tree)
    return jax.tree_util.tree_map(
        lambda x: np.asarray(jax.device_get(x)), gathered
    )


def sharded_bytes_fraction(tree: Any, shardings: Any) -> float:
    """Fraction of the tree's bytes that got sharded (diagnostics/tests)."""
    total = 0
    sharded = 0
    for leaf, sh in zip(
        jax.tree_util.tree_leaves(tree), jax.tree_util.tree_leaves(shardings)
    ):
        n = int(np.prod(np.shape(leaf) or (1,)))
        total += n
        if isinstance(sh, NamedSharding) and sh.spec != P():
            sharded += n
    return sharded / total if total else 0.0
