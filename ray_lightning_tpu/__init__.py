"""ray_lightning_tpu — TPU-native distributed training framework.

From-scratch rebuild of the capability surface of ``ray_lightning``
(/root/reference): Ray-style actor launch fabric + Lightning-style trainer +
distributed strategies, re-designed for TPU (JAX/XLA/pjit/Pallas). Public
surface mirrors the reference's three strategies
(/root/reference/ray_lightning/__init__.py:1-5) plus the Tune module, with a
standalone Trainer/TPUModule since the framework does not depend on PyTorch
Lightning.
"""
__version__ = "0.1.0"

_LAZY = {
    "fabric": "ray_lightning_tpu",
    "obs": "ray_lightning_tpu",
    "RayStrategy": "ray_lightning_tpu.strategies",
    "RayTPUStrategy": "ray_lightning_tpu.strategies",
    "RayShardedStrategy": "ray_lightning_tpu.strategies",
    "RingTPUStrategy": "ray_lightning_tpu.strategies",
    "HorovodRayStrategy": "ray_lightning_tpu.strategies",
    "GSPMDStrategy": "ray_lightning_tpu.strategies",
    "Trainer": "ray_lightning_tpu.trainer",
    "TPUModule": "ray_lightning_tpu.trainer",
    "ByteBPETokenizer": "ray_lightning_tpu.tokenizer",
}


def __getattr__(name):
    # Lazy exports keep `import ray_lightning_tpu` light (no jax import) so
    # the fabric can spawn workers whose env is configured before jax loads.
    if name in _LAZY:
        import importlib

        if name in ("fabric", "obs"):
            return importlib.import_module(f"ray_lightning_tpu.{name}")
        mod = importlib.import_module(_LAZY[name])
        return getattr(mod, name)
    raise AttributeError(f"module 'ray_lightning_tpu' has no attribute {name!r}")


__all__ = list(_LAZY)
