#!/usr/bin/env bash
# Lint/format gate (reference: format.sh:1-147, yapf+flake8). This build uses
# ruff for both roles. `./format.sh` fixes in place; `./format.sh --check` is
# the CI mode.
set -euo pipefail
cd "$(dirname "$0")"

# ray_lightning_tpu covers the obs/ package; tools/ carries the obs
# snapshot + profiling scripts the watcher runs from a bare archive.
TARGETS=(ray_lightning_tpu tests examples tools bench.py __graft_entry__.py)

if ! command -v ruff >/dev/null 2>&1; then
    echo "ruff not installed; skipping lint (CI installs it)" >&2
    exit 0
fi

if [[ "${1:-}" == "--check" ]]; then
    ruff check "${TARGETS[@]}"
    ruff format --check "${TARGETS[@]}"
else
    ruff check --fix "${TARGETS[@]}"
    ruff format "${TARGETS[@]}"
fi
