"""BERT masked-LM pretraining example (data-parallel strategy).

The encoder-side sibling of ``gpt_sharded_example.py``: pretrains a
bidirectional encoder with dynamic BERT masking (80/10/10) under
``RayTPUStrategy``, then demonstrates ``fill_mask`` — masking a held-out
sequence and measuring how many tokens the encoder recovers. The
reference carries no encoder example (its examples stop at MNIST-level
classifiers); this one exists because a model zoo is part of the
TPU-native framework's surface.

Doubles as an integration smoke test (run with ``--smoke-test``), the
role the reference's examples play in CI
(/root/reference/.github/workflows/test.yaml:95-107).
"""
import argparse

import numpy as np

from ray_lightning_tpu import fabric
from ray_lightning_tpu.models import BERTConfig, BERTEncoder
from ray_lightning_tpu.models.gpt import make_fake_text
from ray_lightning_tpu.strategies import RayTPUStrategy
from ray_lightning_tpu.trainer import Trainer


def train_bert(
    num_workers: int = 2,
    num_epochs: int = 4,
    use_tpu: bool = False,
    smoke: bool = False,
) -> BERTEncoder:
    cfg = BERTConfig(
        vocab_size=128,
        n_layer=2 if smoke else 4,
        n_head=4,
        d_model=64 if smoke else 256,
        max_seq=32 if smoke else 128,
        attn_impl="reference" if smoke else "flash",
        loss_chunk=16,
        compute_dtype="float32" if smoke else "bfloat16",
    )
    module = BERTEncoder(
        config=cfg,
        batch_size=8 if smoke else 32,
        n_train=64 if smoke else 2048,
        lr=1e-3,
    )
    trainer = Trainer(
        max_epochs=num_epochs,
        strategy=RayTPUStrategy(num_workers=num_workers, use_tpu=use_tpu),
        enable_checkpointing=False,
        seed=0,
        num_sanity_val_steps=0,
    )
    trainer.fit(module)
    print(
        "final loss:",
        float(trainer.callback_metrics.get("loss", float("nan"))),
        flush=True,
    )
    return module


def demo_fill_mask(
    module: BERTEncoder, use_tpu: bool, mask_frac: float = 0.15
) -> float:
    """Mask a held-out sequence and report the recovery rate.

    Runs inside a worker actor (the gpt_sharded_example.py pattern): the
    driver never initializes a jax backend — workers own the chips, and
    on CPU the actor env pins the platform."""
    from ray_lightning_tpu.launchers.utils import TrainWorker

    cfg = module.config
    params = module.params
    clean = np.asarray(
        make_fake_text(4, seq_len=cfg.max_seq - 1, vocab=cfg.mask_id, seed=99)
        .arrays[0],
        np.int32,
    )[:, : cfg.max_seq]
    g = np.random.default_rng(0)
    sel = g.random(clean.shape) < mask_frac
    masked = np.where(sel, cfg.mask_id, clean)

    def fill():
        import os

        import jax

        if os.environ.get("JAX_PLATFORMS") == "cpu":
            jax.config.update("jax_platforms", "cpu")
        m = BERTEncoder(config=cfg)
        m.params = params
        return np.asarray(m.fill_mask(masked))

    env = {} if use_tpu else {"JAX_PLATFORMS": "cpu"}
    resources = {"TPU": 1.0} if use_tpu else {}
    actor = (
        fabric.remote(TrainWorker)
        .options(num_cpus=1, resources=resources, env=env)
        .remote()
    )
    try:
        filled = fabric.get(actor.execute.remote(fill), timeout=600.0)
    finally:
        fabric.kill(actor)
    recovered = float((filled[sel] == clean[sel]).mean())
    print(
        f"fill_mask recovered {recovered:.1%} of {int(sel.sum())} masked tokens",
        flush=True,
    )
    return recovered


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-workers", type=int, default=2)
    parser.add_argument("--num-epochs", type=int, default=4)
    parser.add_argument("--use-tpu", action="store_true")
    parser.add_argument("--smoke-test", action="store_true")
    parser.add_argument(
        "--address", default=None,
        help="fabric head address (host:port) for client mode — start one "
        "with `python -m ray_lightning_tpu.fabric.server`",
    )
    args = parser.parse_args()

    # Smoke tests over-provision logical CPUs so worker bundles always
    # fit tiny CI hosts (the ray_ddp_example.py convention).
    fabric.init(
        address=args.address, num_cpus=8 if args.smoke_test else None
    )
    module = train_bert(
        num_workers=args.num_workers,
        num_epochs=2 if args.smoke_test else args.num_epochs,
        use_tpu=args.use_tpu,
        smoke=args.smoke_test,
    )
    demo_fill_mask(module, use_tpu=args.use_tpu)
    fabric.shutdown()


if __name__ == "__main__":
    main()
