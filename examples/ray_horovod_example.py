"""MNIST training with the ring (Horovod-flavor) strategy.

Counterpart of the reference's ``examples/ray_horovod_example.py``
(/root/reference/ray_lightning/examples/ray_horovod_example.py:1-174). The
reference's Horovod value proposition is a different collective protocol
(C++ ring-allreduce); here that niche is ``RingTPUStrategy`` — an explicit
``shard_map`` + ``lax.pmean`` schedule instead of GSPMD-inferred collectives
(strategies/ring.py).
"""
import argparse

from ray_lightning_tpu import fabric
from ray_lightning_tpu.models import MNISTClassifier
from ray_lightning_tpu.strategies import RingTPUStrategy
from ray_lightning_tpu.trainer import Trainer


def train_mnist(
    config: dict,
    num_workers: int = 2,
    num_epochs: int = 2,
    use_tpu: bool = False,
    callbacks: list = None,
) -> Trainer:
    module = MNISTClassifier(
        lr=config.get("lr", 1e-3), batch_size=config.get("batch_size", 32)
    )
    trainer = Trainer(
        max_epochs=num_epochs,
        callbacks=list(callbacks or []),
        strategy=RingTPUStrategy(num_workers=num_workers, use_tpu=use_tpu),
        enable_checkpointing=False,
    )
    trainer.fit(module)
    return trainer


def tune_mnist(num_workers: int = 2, num_epochs: int = 2, num_samples: int = 2,
               use_tpu: bool = False) -> None:
    from ray_lightning_tpu import tune

    def train_fn(config: dict) -> None:
        train_mnist(
            config,
            num_workers=num_workers,
            num_epochs=num_epochs,
            use_tpu=use_tpu,
            callbacks=[
                tune.TuneReportCallback(
                    {"loss": "ptl/val_loss", "mean_accuracy": "ptl/val_accuracy"},
                    on="validation_end",
                )
            ],
        )

    results = tune.Tuner(
        train_fn,
        param_space={"lr": tune.loguniform(1e-4, 1e-1)},
        num_samples=num_samples,
        resources_per_trial=tune.get_tune_resources(
            num_workers=num_workers, use_tpu=use_tpu
        ),
    ).fit()
    best = results.get_best_result("mean_accuracy", mode="max")
    print("Best hyperparameters found were:", best.config)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-workers", type=int, default=2)
    parser.add_argument("--num-epochs", type=int, default=2)
    parser.add_argument("--num-samples", type=int, default=2)
    parser.add_argument("--use-tpu", action="store_true", default=False)
    parser.add_argument("--tune", action="store_true")
    parser.add_argument("--smoke-test", action="store_true")
    parser.add_argument(
        "--address", type=str, default=None,
        help="fabric head address (host:port) for client mode — start one with `python -m ray_lightning_tpu.fabric.server`",
    )
    parser.add_argument(
        "--num-cpus", type=int, default=None,
        help="logical CPU capacity for the fabric head (defaults to the host count; smoke tests over-provision so worker bundles always fit)",
    )
    args = parser.parse_args()

    num_cpus = args.num_cpus
    if num_cpus is None and args.smoke_test:
        num_cpus = 8  # logical: lets tune trial bundles fit tiny CI hosts
    fabric.init(address=args.address, num_cpus=num_cpus)
    num_epochs = 1 if args.smoke_test else args.num_epochs
    num_samples = 1 if args.smoke_test else args.num_samples
    if args.tune:
        tune_mnist(args.num_workers, num_epochs, num_samples, args.use_tpu)
    else:
        trainer = train_mnist(
            {}, num_workers=args.num_workers, num_epochs=num_epochs, use_tpu=args.use_tpu
        )
        print("Final metrics:", trainer.callback_metrics)
    fabric.shutdown()


if __name__ == "__main__":
    main()
