"""GPT-2 training example: ZeRO-sharded data parallelism + generation.

The flagship-model analog of the reference's example set
(/root/reference/ray_lightning/examples/ray_ddp_sharded_example.py trains a
transformer under the FairScale-sharded strategy): trains a GPT on the
synthetic LM corpus under ``RayShardedStrategy`` (GSPMD-sharded optimizer
state), reports epoch wall time and device memory via ``TPUStatsCallback``,
then greedily generates from the fitted weights with the KV-cache decoder.

Smoke-test CI mode mirrors the reference's ``--smoke-test`` convention.
"""
import argparse

from ray_lightning_tpu import fabric
from ray_lightning_tpu.models import GPTConfig
from ray_lightning_tpu.models.gpt import GPTLM
from ray_lightning_tpu.strategies import RayShardedStrategy
from ray_lightning_tpu.trainer import Trainer, TPUStatsCallback


def train_gpt(
    num_workers: int = 2,
    num_epochs: int = 2,
    use_tpu: bool = False,
    smoke_test: bool = False,
    modern: bool = False,
    from_hf: str = None,
) -> Trainer:
    """``modern=True`` enables the Mistral-style variant: RoPE positions,
    grouped-query attention (12 -> 4 kv heads: a 3x smaller decode cache;
    MQA in smoke mode), and a sliding attention window — same
    trainer/strategy surface, one config change. ``from_hf`` fine-tunes a
    local Hugging Face GPT-2 checkpoint instead of training from scratch
    (weights imported via :func:`load_hf_gpt2`)."""
    if from_hf:
        if modern:
            raise SystemExit(
                "--from-hf imports a stock GPT-2 (learned positions, MHA); "
                "it cannot be combined with --modern"
            )
        from ray_lightning_tpu.models import load_hf_gpt2

        params, cfg = load_hf_gpt2(from_hf)
        module = GPTLM(config=cfg, batch_size=4 if smoke_test else 16,
                       n_train=64 if smoke_test else 2048, lr=1e-4)
    elif smoke_test:
        extra = dict(pos_embed="rope", n_kv_head=1, attn_window=16) if modern else {}
        cfg = GPTConfig(
            vocab_size=64, n_layer=2, n_head=2, d_model=32, max_seq=32,
            attn_impl="reference", **extra,
        )
        module = GPTLM(config=cfg, batch_size=4, n_train=64, lr=3e-3,
                       warmup_steps=5)
    else:
        extra = dict(pos_embed="rope", n_kv_head=4, attn_window=256) if modern else {}
        cfg = GPTConfig.gpt2_small(max_seq=512, **extra)
        module = GPTLM(config=cfg, batch_size=16, n_train=2048)
    stats = TPUStatsCallback()
    trainer = Trainer(
        max_epochs=num_epochs,
        callbacks=[stats],
        strategy=RayShardedStrategy(num_workers=num_workers, use_tpu=use_tpu),
        enable_checkpointing=False,
        precision="bf16" if use_tpu else "fp32",
        seed=0,
        log_grad_norm=True,
    )
    ckpt_path = None
    if from_hf:
        # fit() always initializes from the module's init_params; imported
        # weights enter through the resume path (params-only checkpoint).
        import tempfile

        from ray_lightning_tpu.utils import to_state_stream

        f = tempfile.NamedTemporaryFile(suffix=".ckpt", delete=False)
        f.write(to_state_stream({"params": params}))
        f.close()
        ckpt_path = f.name
    trainer.fit(module, ckpt_path=ckpt_path)
    print("val loss:", trainer.callback_metrics.get("val_loss"))

    # KV-cached greedy generation from the recovered rank-0 weights — run
    # inside a worker actor so the DRIVER never binds the accelerator (the
    # same discipline the launcher keeps during training).
    import numpy as np

    from ray_lightning_tpu.launchers.utils import TrainWorker

    params = module.params
    prompt = np.asarray([[1, 12, 3]], np.int32)

    def decode():
        import os

        import jax

        if os.environ.get("JAX_PLATFORMS") == "cpu":
            jax.config.update("jax_platforms", "cpu")
        from ray_lightning_tpu.models.gpt import gpt_generate

        return np.asarray(
            gpt_generate(params, cfg, prompt, max_new_tokens=8)
        )

    env = {} if use_tpu else {"JAX_PLATFORMS": "cpu"}
    resources = {"TPU": 1.0} if use_tpu else {}
    actor = (
        fabric.remote(TrainWorker)
        .options(num_cpus=1, resources=resources, env=env)
        .remote()
    )
    try:
        out = fabric.get(actor.execute.remote(decode), timeout=900)
    finally:
        fabric.kill(actor)
    print("generated:", out[0].tolist())
    return trainer


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-workers", type=int, default=2)
    parser.add_argument("--num-epochs", type=int, default=2)
    parser.add_argument("--use-tpu", action="store_true", default=False)
    parser.add_argument("--smoke-test", action="store_true")
    parser.add_argument(
        "--modern", action="store_true",
        help="RoPE + grouped-query attention + sliding window variant",
    )
    parser.add_argument(
        "--from-hf", type=str, default=None, metavar="PATH",
        help="fine-tune a LOCAL Hugging Face GPT-2 checkpoint directory "
        "instead of training from scratch (load_hf_gpt2 bridge)",
    )
    parser.add_argument(
        "--address", type=str, default=None,
        help="fabric head address (host:port) for client mode — start one "
        "with `python -m ray_lightning_tpu.fabric.server`",
    )
    parser.add_argument("--num-cpus", type=int, default=None)
    args = parser.parse_args()

    num_cpus = args.num_cpus
    if num_cpus is None and args.smoke_test:
        num_cpus = 8
    fabric.init(address=args.address, num_cpus=num_cpus)
    train_gpt(
        num_workers=args.num_workers,
        num_epochs=1 if args.smoke_test else args.num_epochs,
        use_tpu=args.use_tpu,
        smoke_test=args.smoke_test,
        modern=args.modern,
        from_hf=args.from_hf,
    )
    fabric.shutdown()


if __name__ == "__main__":
    main()
