"""Sharded (ZeRO) training example with the perf-probe callback.

Counterpart of the reference's ``examples/ray_ddp_sharded_example.py``
(/root/reference/ray_lightning/examples/ray_ddp_sharded_example.py:1-133),
which trains ImageGPT under the FairScale-sharded strategy with fp16 and a
``CUDACallback`` measuring epoch time + peak memory. Here:
``RayShardedStrategy`` (GSPMD optimizer-state sharding, strategies/
sharded.py), bf16 precision, and ``TPUStatsCallback`` as the perf probe.
"""
import argparse

from ray_lightning_tpu import fabric
from ray_lightning_tpu.strategies import RayShardedStrategy
from ray_lightning_tpu.trainer import TPUStatsCallback, Trainer


def _build_module(smoke_test: bool, batch_size: int):
    """GPT-2-style LM when available; MNIST MLP for smoke tests."""
    if not smoke_test:
        try:
            from ray_lightning_tpu.models import GPT2LM

            return GPT2LM.mini(batch_size=batch_size)
        except ImportError:
            print(
                "GPT2LM is not available in this build of "
                "ray_lightning_tpu.models; using the MNIST MLP instead"
            )
    from ray_lightning_tpu.models import MNISTClassifier

    return MNISTClassifier(batch_size=batch_size, n_train=256)


def train(
    num_workers: int = 2,
    num_epochs: int = 2,
    batch_size: int = 16,
    zero_stage: int = 1,
    use_tpu: bool = False,
    smoke_test: bool = False,
) -> Trainer:
    stats = TPUStatsCallback()
    module = _build_module(smoke_test, batch_size)
    trainer = Trainer(
        max_epochs=num_epochs,
        precision="bf16",
        callbacks=[stats],
        enable_checkpointing=False,
        strategy=RayShardedStrategy(
            num_workers=num_workers, use_tpu=use_tpu, zero_stage=zero_stage
        ),
    )
    trainer.fit(module)
    if stats.epoch_times:
        avg = sum(stats.epoch_times) / len(stats.epoch_times)
        print(f"Average epoch time: {avg:.3f} s")
    if stats.peak_memory and max(stats.peak_memory):
        print(f"Peak device memory: {max(stats.peak_memory) / 2**20:.1f} MiB")
    return trainer


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-workers", type=int, default=2)
    parser.add_argument("--num-epochs", type=int, default=2)
    parser.add_argument("--batch-size", type=int, default=16)
    parser.add_argument("--zero-stage", type=int, default=1, choices=(1, 2, 3))
    parser.add_argument("--use-tpu", action="store_true", default=False)
    parser.add_argument("--smoke-test", action="store_true")
    parser.add_argument(
        "--address", type=str, default=None,
        help="fabric head address (host:port) for client mode — start one with `python -m ray_lightning_tpu.fabric.server`",
    )
    parser.add_argument(
        "--num-cpus", type=int, default=None,
        help="logical CPU capacity for the fabric head (defaults to the host count; smoke tests over-provision so worker bundles always fit)",
    )
    args = parser.parse_args()

    num_cpus = args.num_cpus
    if num_cpus is None and args.smoke_test:
        num_cpus = 8  # logical: lets tune trial bundles fit tiny CI hosts
    fabric.init(address=args.address, num_cpus=num_cpus)
    trainer = train(
        num_workers=args.num_workers,
        num_epochs=1 if args.smoke_test else args.num_epochs,
        batch_size=args.batch_size,
        zero_stage=args.zero_stage,
        use_tpu=args.use_tpu,
        smoke_test=args.smoke_test,
    )
    print("Final metrics:", trainer.callback_metrics)
    fabric.shutdown()


if __name__ == "__main__":
    main()
