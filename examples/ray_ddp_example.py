"""MNIST data-parallel training example (RayTPUStrategy).

Counterpart of the reference's ``examples/ray_ddp_example.py``
(/root/reference/ray_lightning/examples/ray_ddp_example.py:1-173): trains an
MNIST classifier under the data-parallel strategy, with an optional ``--tune``
mode that wraps the same training function in a hyperparameter sweep.

Doubles as an integration smoke test (run with ``--smoke-test``), the role
the reference's examples play in CI (.github/workflows/test.yaml:95-107).
"""
import argparse

from ray_lightning_tpu import fabric
from ray_lightning_tpu.models import MNISTClassifier
from ray_lightning_tpu.strategies import RayTPUStrategy
from ray_lightning_tpu.trainer import Trainer


def train_mnist(
    config: dict,
    num_workers: int = 2,
    num_epochs: int = 2,
    use_tpu: bool = False,
    callbacks: list = None,
    steps_per_execution: int = 1,
) -> Trainer:
    module = MNISTClassifier(
        lr=config.get("lr", 1e-3),
        hidden=config.get("hidden", 128),
        batch_size=config.get("batch_size", 32),
    )
    trainer = Trainer(
        max_epochs=num_epochs,
        # TPU tip: >1 folds K optimizer steps into one compiled dispatch
        # (amortizes launch latency; math unchanged).
        steps_per_execution=steps_per_execution,
        callbacks=list(callbacks or []),
        strategy=RayTPUStrategy(num_workers=num_workers, use_tpu=use_tpu),
        enable_checkpointing=False,
    )
    trainer.fit(module)
    return trainer


def tune_mnist(
    num_workers: int = 2,
    num_epochs: int = 2,
    num_samples: int = 2,
    use_tpu: bool = False,
) -> None:
    from ray_lightning_tpu import tune

    def train_fn(config: dict) -> None:
        train_mnist(
            config,
            num_workers=num_workers,
            num_epochs=num_epochs,
            use_tpu=use_tpu,
            callbacks=[
                tune.TuneReportCallback(
                    {"loss": "ptl/val_loss", "mean_accuracy": "ptl/val_accuracy"},
                    on="validation_end",
                )
            ],
        )

    results = tune.Tuner(
        train_fn,
        param_space={
            "lr": tune.loguniform(1e-4, 1e-1),
            "hidden": tune.choice([64, 128]),
            "batch_size": tune.choice([32, 64]),
        },
        num_samples=num_samples,
        resources_per_trial=tune.get_tune_resources(
            num_workers=num_workers, use_tpu=use_tpu
        ),
    ).fit()
    best = results.get_best_result("mean_accuracy", mode="max")
    print("Best hyperparameters found were:", best.config)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-workers", type=int, default=2)
    parser.add_argument("--num-epochs", type=int, default=2)
    parser.add_argument(
        "--steps-per-execution", type=int, default=1,
        help="fold K optimizer steps into one compiled dispatch "
        "(recommended 8+ on TPU)",
    )
    parser.add_argument("--num-samples", type=int, default=2)
    parser.add_argument("--use-tpu", action="store_true", default=False)
    parser.add_argument("--tune", action="store_true", help="run a tune sweep")
    parser.add_argument(
        "--smoke-test", action="store_true", help="tiny fast run for CI"
    )
    parser.add_argument(
        "--auto-lr", action="store_true",
        help="pick the learning rate with an LR range test before the fit",
    )
    parser.add_argument(
        "--auto-batch", action="store_true",
        help="pick the batch size with the OOM-aware finder (throughput-"
        "optimal point) before the fit",
    )
    parser.add_argument(
        "--address", type=str, default=None,
        help="fabric head address (host:port) for client mode — start one "
        "with `python -m ray_lightning_tpu.fabric.server`",
    )
    parser.add_argument(
        "--num-cpus", type=int, default=None,
        help="logical CPU capacity for the fabric head (defaults to the host count; smoke tests over-provision so worker bundles always fit)",
    )
    args = parser.parse_args()

    num_cpus = args.num_cpus
    if num_cpus is None and args.smoke_test:
        num_cpus = 8  # logical: lets tune trial bundles fit tiny CI hosts
    fabric.init(address=args.address, num_cpus=num_cpus)
    num_epochs = 1 if args.smoke_test else args.num_epochs
    num_samples = 1 if args.smoke_test else args.num_samples
    if args.tune and (args.auto_lr or args.auto_batch):
        parser.error(
            "--auto-lr/--auto-batch feed the plain fit's config; a --tune "
            "sweep searches lr/batch itself — combine one or the other"
        )
    config = {}
    if args.auto_lr or args.auto_batch:
        # Probes run in-process ON CPU: the driver must never initialize
        # the TPU backend (libtpu is single-owner per process — a driver
        # that binds the chips starves the fit's worker actors). The lr
        # suggestion is model-shaped, not hardware-shaped; the batch probe
        # is illustrative on CPU (run it inside a worker for chip-accurate
        # OOM bounds).
        import jax

        jax.config.update("jax_platforms", "cpu")
        probe = MNISTClassifier(batch_size=32, n_train=512 if args.smoke_test else 4096)
        if args.auto_batch:
            from ray_lightning_tpu.trainer import scale_batch_size

            res = scale_batch_size(
                probe,
                max_val=64 if args.smoke_test else 512,
                steps_per_trial=2,
            )
            config["batch_size"] = res.throughput_optimal or 32
            print(f"auto-batch: {res.samples_per_sec} -> {config['batch_size']}")
        if args.auto_lr:
            from ray_lightning_tpu.trainer import lr_find

            res = lr_find(probe, num_steps=40 if args.smoke_test else 100)
            config["lr"] = res.suggestion_or(1e-3)
            print(f"auto-lr: suggestion {config['lr']:.2e}")
    if args.tune:
        tune_mnist(args.num_workers, num_epochs, num_samples, args.use_tpu)
    else:
        trainer = train_mnist(
            config,
            num_workers=args.num_workers,
            num_epochs=num_epochs,
            use_tpu=args.use_tpu,
            steps_per_execution=args.steps_per_execution,
        )
        print("Final metrics:", trainer.callback_metrics)
    fabric.shutdown()


if __name__ == "__main__":
    main()
