"""Hyperparameter sweep over distributed MNIST fits (Tuner + checkpointing).

Counterpart of the reference's ``examples/ray_ddp_tune.py``
(/root/reference/ray_lightning/examples/ray_ddp_tune.py:1-118): each trial
runs an N-worker distributed fit and reports metrics + checkpoints back to
the tuner (nested parallelism, SURVEY.md §3.3). Demonstrates
``TuneReportCheckpointCallback`` and an ``init_hook`` that runs once per
worker before training (the reference's FileLock download pattern,
ray_ddp_tune.py:21-36 — here it pre-builds the synthetic dataset).
"""
import argparse

from ray_lightning_tpu import fabric, tune
from ray_lightning_tpu.models import MNISTClassifier
from ray_lightning_tpu.strategies import RayTPUStrategy
from ray_lightning_tpu.trainer import Trainer


def download_data() -> None:
    """Per-worker init hook (reference's download_data, ray_ddp_tune.py:21-36)."""
    from ray_lightning_tpu.models.mnist import make_fake_mnist

    make_fake_mnist(128)


def train_mnist(config: dict, num_workers: int = 2, num_epochs: int = 2,
                use_tpu: bool = False) -> None:
    module = MNISTClassifier(
        lr=config["lr"], batch_size=config["batch_size"], n_train=256
    )
    trainer = Trainer(
        max_epochs=num_epochs,
        enable_checkpointing=False,
        callbacks=[
            tune.TuneReportCheckpointCallback(
                metrics={"loss": "ptl/val_loss", "mean_accuracy": "ptl/val_accuracy"},
                filename="checkpoint",
                on="validation_end",
            )
        ],
        strategy=RayTPUStrategy(
            num_workers=num_workers, use_tpu=use_tpu, init_hook=download_data
        ),
    )
    trainer.fit(module)


def tune_mnist(num_workers: int = 2, num_epochs: int = 2, num_samples: int = 2,
               use_tpu: bool = False) -> None:
    def train_fn(config: dict) -> None:
        train_mnist(config, num_workers, num_epochs, use_tpu)

    results = tune.Tuner(
        train_fn,
        param_space={
            "lr": tune.loguniform(1e-4, 1e-1),
            "batch_size": tune.choice([32, 64]),
        },
        num_samples=num_samples,
        resources_per_trial=tune.get_tune_resources(
            num_workers=num_workers, use_tpu=use_tpu
        ),
        scheduler=tune.ASHAScheduler("loss", mode="min", max_t=num_epochs),
    ).fit()
    best = results.get_best_result("mean_accuracy", mode="max")
    print("Best hyperparameters found were:", best.config)
    print("Best checkpoint:", best.checkpoint_path)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-workers", type=int, default=2)
    parser.add_argument("--num-epochs", type=int, default=2)
    parser.add_argument("--num-samples", type=int, default=2)
    parser.add_argument("--use-tpu", action="store_true", default=False)
    parser.add_argument("--smoke-test", action="store_true")
    parser.add_argument(
        "--address", type=str, default=None,
        help="fabric head address (host:port) for client mode — start one with `python -m ray_lightning_tpu.fabric.server`",
    )
    parser.add_argument(
        "--num-cpus", type=int, default=None,
        help="logical CPU capacity for the fabric head (defaults to the host count; smoke tests over-provision so worker bundles always fit)",
    )
    args = parser.parse_args()

    num_cpus = args.num_cpus
    if num_cpus is None and args.smoke_test:
        num_cpus = 8  # logical: lets tune trial bundles fit tiny CI hosts
    fabric.init(address=args.address, num_cpus=num_cpus)
    if args.smoke_test:
        tune_mnist(num_workers=2, num_epochs=1, num_samples=1, use_tpu=False)
    else:
        tune_mnist(args.num_workers, args.num_epochs, args.num_samples, args.use_tpu)
    fabric.shutdown()


if __name__ == "__main__":
    main()
